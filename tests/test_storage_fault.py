"""Crash-consistency harness: simulated power-loss crashes over the
storage plane (tpuraft/storage/fault.py).

Three generational harnesses — FileLogStorage + MetaJournal under live
``ChaosDir`` interposition, the native multilog under
``NativeJournalTracker`` tail imaging — each runs dozens of seeded
power-loss crashes (>= 220 in total across the module) and checks the
recovery invariants after EVERY one:

  - recovery never raises (a torn/bit-flipped unsynced tail is
    truncated at the last CRC-valid record, not crashed on);
  - log prefix property: recovered entries byte-match what was staged;
  - acked floor: nothing proven durable by a completed fsync is lost
    (last_recovered >= last_acked, {term, votedFor} never regresses
    below an acked save);
  - staged ceiling: recovery never invents entries beyond what was
    staged;
  - no orphaned gids: an acked registration keeps its gid across
    crashes; journal records whose registration was lost are truncated,
    never adopted or shadowed.

Bit rot of the DURABLE region is the opposite contract — fail loudly,
never truncate silently — and is covered by the explicit tests at the
bottom.
"""

from __future__ import annotations

import os
import random
import struct

from tpuraft.entity import EMPTY_PEER, EntryType, LogEntry, LogId, PeerId
from tpuraft.storage.fault import (
    ChaosDir,
    NativeJournalTracker,
)
from tpuraft.storage.log_storage import CorruptLogError, FileLogStorage
from tpuraft.storage.meta_multilog import MetaJournal
from tpuraft.storage.multilog import MultiLogStorage


def _entry(index: int, gen: int, term: int = 1) -> LogEntry:
    return LogEntry(type=EntryType.DATA, id=LogId(index, term),
                    data=b"g%03d-i%06d" % (gen, index))


# ---------------------------------------------------------------------------
# FileLogStorage under ChaosDir
# ---------------------------------------------------------------------------


def _filelog_lifetime(root: str, rng: random.Random, gens: int) -> int:
    """One directory, ``gens`` crash generations; returns crash count."""
    first, entries, acked_last = 1, {}, 0

    def staged_last():
        return max(entries) if entries else first - 1

    with ChaosDir(root) as chaos:
        for gen in range(gens):
            st = FileLogStorage(os.path.join(root, "log"),
                                segment_max_bytes=200)
            st.init()  # must tolerate whatever the crash left
            rf, rl = st.first_log_index(), st.last_log_index()
            assert rf == first, f"gen {gen}: first {rf} != {first}"
            assert acked_last <= rl <= staged_last(), \
                f"gen {gen}: last {rl} not in [{acked_last}, {staged_last()}]"
            for i in range(rf, rl + 1):
                e = st.get_entry(i)
                assert e is not None and e.data == entries[i], \
                    f"gen {gen}: entry {i} mismatch"
            # recovered state is durable (init re-fsyncs + watermarks)
            for i in list(entries):
                if i > rl:
                    del entries[i]
            acked_last = rl

            for _ in range(rng.randrange(1, 5)):
                op = rng.random()
                if op < 0.70 or not entries:
                    n = rng.randrange(1, 6)
                    batch = [_entry(staged_last() + 1 + k, gen)
                             for k in range(n)]
                    st.append_entries(batch, sync=True)  # fsynced => acked
                    for e in batch:
                        entries[e.id.index] = e.data
                    acked_last = staged_last()
                elif op < 0.85 and acked_last >= first:
                    keep = rng.randrange(first - 1, staged_last() + 1)
                    st.truncate_suffix(keep)  # fsynced by contract
                    for i in list(entries):
                        if i > keep:
                            del entries[i]
                    acked_last = min(acked_last, keep)
                elif op < 0.95 and staged_last() > first:
                    cut = rng.randrange(first, staged_last() + 1)
                    st.truncate_prefix(cut)  # meta fsynced by contract
                    first = max(first, cut)
                    for i in list(entries):
                        if i < first:
                            del entries[i]
                    acked_last = max(acked_last, first - 1)
                else:
                    nxt = staged_last() + rng.randrange(1, 10)
                    st.reset(nxt)
                    first, entries, acked_last = nxt, {}, nxt - 1

            if rng.random() < 0.7:
                # the in-flight append the power interrupts: staged
                # bytes on disk, fsync never completed — on-disk
                # identical to a crash mid sync=True append
                n = rng.randrange(1, 5)
                batch = [_entry(staged_last() + 1 + k, gen, term=2)
                         for k in range(n)]
                st.append_entries(batch, sync=False)
                for e in batch:
                    entries[e.id.index] = e.data

            plan = chaos.capture_crash(rng)   # power dies here
            st.shutdown()                     # in-proc cleanup only...
            chaos.apply_crash(plan)           # ...discarded by the image
        return chaos.crash_count


def test_filelog_power_loss_recovery():
    import tempfile

    crashes = 0
    for seed in range(3):
        with tempfile.TemporaryDirectory() as tmp:
            crashes += _filelog_lifetime(
                os.path.join(tmp, f"flog{seed}"),
                random.Random(1000 + seed), gens=20)
    assert crashes >= 60


# ---------------------------------------------------------------------------
# MetaJournal under ChaosDir
# ---------------------------------------------------------------------------


def _meta_lifetime(root: str, rng: random.Random, gens: int) -> int:
    groups = [f"r{i}" for i in range(4)]
    history = {g: [(0, "")] for g in groups}   # staged (term, voted) per group
    acked = {g: 0 for g in groups}             # index into history[g]
    term = {g: 0 for g in groups}

    with ChaosDir(root) as chaos:
        for gen in range(gens):
            j = MetaJournal(root)
            j.COMPACT_MIN_BYTES = 512  # force compaction under chaos
            for g in groups:
                t, voted = j.get(g)
                v = "" if voted.is_empty() else str(voted)
                hist = history[g]
                assert (t, v) in hist, f"gen {gen}: {g} has unknown {t}/{v}"
                pos = hist.index((t, v))
                assert pos >= acked[g], \
                    f"gen {gen}: {g} regressed below acked " \
                    f"({t} < {hist[acked[g]][0]})"
                # recovered value is durable now (reopen fsync + wm)
                history[g] = [(t, v)]
                acked[g] = 0
                term[g] = max(term[g], t)

            for _ in range(rng.randrange(2, 8)):
                g = rng.choice(groups)
                term[g] += rng.randrange(1, 3)
                voted = PeerId.parse(f"10.0.0.{rng.randrange(1, 5)}:80") \
                    if rng.random() < 0.8 else EMPTY_PEER
                j.stage(g, term[g], voted)
                history[g].append(
                    (term[g], "" if voted.is_empty() else str(voted)))
                if rng.random() < 0.4:
                    j.sync()  # group-commit round: everything staged acks
                    for gg in groups:
                        acked[gg] = len(history[gg]) - 1

            plan = chaos.capture_crash(rng)
            j.close()
            chaos.apply_crash(plan)
        return chaos.crash_count


def test_meta_journal_power_loss_recovery():
    import tempfile

    crashes = 0
    for seed in range(4):
        with tempfile.TemporaryDirectory() as tmp:
            crashes += _meta_lifetime(
                os.path.join(tmp, f"meta{seed}"),
                random.Random(2000 + seed), gens=20)
    assert crashes >= 80


# ---------------------------------------------------------------------------
# native multilog under tail imaging
# ---------------------------------------------------------------------------


class _GroupModel:
    def __init__(self) -> None:
        self.first = 1
        self.acked_first = 1
        self.entries: dict[int, bytes] = {}
        self.acked_last = 0

    def staged_last(self) -> int:
        return max(self.entries) if self.entries else self.first - 1


def _native_lifetime(base: str, rng: random.Random, gens: int) -> int:
    names = [f"g{i}" for i in range(3)]
    model = {n: _GroupModel() for n in names}
    gids: dict[str, int] = {}
    live = os.path.join(base, "gen0")
    crashes = 0

    for gen in range(gens):
        stores = {n: MultiLogStorage(live, n) for n in names}
        for n in names:
            stores[n].init()  # shared engine; recovery scan runs once
        eng = stores[names[0]].engine
        eng.sync()  # registrations of any new names ack immediately
        for n in names:
            if n in gids:
                assert stores[n]._gid == gids[n], \
                    f"gen {gen}: acked group {n} changed gid " \
                    f"{gids[n]} -> {stores[n]._gid} (orphan/shadow)"
            else:
                gids[n] = stores[n]._gid

        tracker = NativeJournalTracker(live)
        tracker.note_sync()  # the recovered image IS the durable state

        for n in names:
            m, s = model[n], stores[n]
            rf, rl = s.first_log_index(), s.last_log_index()
            assert m.acked_first <= rf, \
                f"gen {gen}: {n} first {rf} below acked {m.acked_first}"
            assert rf <= max(m.first, m.acked_first), \
                f"gen {gen}: {n} first {rf} beyond staged {m.first}"
            assert m.acked_last <= rl, \
                f"gen {gen}: {n} last {rl} below acked {m.acked_last}"
            assert rl <= m.staged_last() or not m.entries, \
                f"gen {gen}: {n} last {rl} beyond staged {m.staged_last()}"
            for i in range(rf, rl + 1):
                e = s.get_entry(i)
                assert e is not None and e.data == m.entries[i], \
                    f"gen {gen}: {n} entry {i} mismatch"
            m.first = rf
            m.acked_first = rf
            for i in list(m.entries):
                if i < rf or i > rl:
                    del m.entries[i]
            m.acked_last = rl

        synced = False
        for _ in range(rng.randrange(2, 6)):
            n = rng.choice(names)
            m, s = model[n], stores[n]
            op = rng.random()
            if op < 0.60 or not m.entries:
                cnt = rng.randrange(1, 5)
                batch = [_entry(m.staged_last() + 1 + k, gen)
                         for k in range(cnt)]
                s.append_entries(batch, sync=False)  # staged, not acked
                for e in batch:
                    m.entries[e.id.index] = e.data
            elif op < 0.75:
                eng.sync()
                tracker.note_sync()
                for mm in model.values():
                    mm.acked_last = mm.staged_last()
                    mm.acked_first = mm.first
                synced = True
            elif op < 0.85 and m.acked_last >= m.first:
                keep = rng.randrange(m.first - 1, m.staged_last() + 1)
                s.truncate_suffix(keep)  # fsyncs everything staged
                tracker.note_sync()
                for i in list(m.entries):
                    if i > keep:
                        del m.entries[i]
                for mm in model.values():
                    mm.acked_last = mm.staged_last()
                    mm.acked_first = mm.first
            elif op < 0.95 and m.staged_last() > m.first:
                cut = rng.randrange(m.first, m.staged_last() + 1)
                s.truncate_prefix(cut)  # lazily durable control record
                m.first = max(m.first, cut)
                # keep entries down to acked_first: a crash can lose the
                # staged trunc record and legitimately revive them
                for i in list(m.entries):
                    if i < m.acked_first:
                        del m.entries[i]
            else:
                nxt = m.staged_last() + rng.randrange(1, 8)
                s.reset(nxt)  # fsyncs everything staged
                tracker.note_sync()
                m.first = m.acked_first = nxt
                m.entries = {}
                m.acked_last = nxt - 1
                for mm in model.values():
                    mm.acked_last = mm.staged_last()
        del synced

        nxt_dir = os.path.join(base, f"gen{gen + 1}")
        tracker.crash_image(nxt_dir, rng)  # power dies here
        for s in stores.values():
            s.shutdown()  # releases/closes the live engine afterwards
        live = nxt_dir
        crashes += 1
    return crashes


def test_native_multilog_power_loss_recovery(tmp_path):
    crashes = 0
    for seed in range(4):
        crashes += _native_lifetime(
            str(tmp_path / f"nat{seed}"), random.Random(3000 + seed),
            gens=30)
    assert crashes >= 120


# ---------------------------------------------------------------------------
# explicit contract tests
# ---------------------------------------------------------------------------


def test_torn_tail_truncated_at_last_crc_valid_record(tmp_path):
    """A torn unsynced tail recovers by CRC truncation — acked prefix
    intact, no exception, no garbage read."""
    root = str(tmp_path / "torn")
    rng = random.Random(7)
    with ChaosDir(root, modes=(("torn-write", 1.0),)) as chaos:
        st = FileLogStorage(os.path.join(root, "log"))
        st.init()
        st.append_entries([_entry(i, 0) for i in range(1, 6)], sync=True)
        st.append_entries([_entry(i, 0) for i in range(6, 9)], sync=False)
        plan = chaos.capture_crash(rng)
        st.shutdown()
        chaos.apply_crash(plan)
        st2 = FileLogStorage(os.path.join(root, "log"))
        st2.init()
        assert 5 <= st2.last_log_index() <= 8
        for i in range(1, st2.last_log_index() + 1):
            assert st2.get_entry(i).data == _entry(i, 0).data
        st2.shutdown()


def test_bit_flip_in_unsynced_tail_is_truncated(tmp_path):
    root = str(tmp_path / "flip")
    rng = random.Random(11)
    with ChaosDir(root, modes=(("bit-flip", 1.0),)) as chaos:
        st = FileLogStorage(os.path.join(root, "log"))
        st.init()
        st.append_entries([_entry(i, 0) for i in range(1, 4)], sync=True)
        st.append_entries([_entry(i, 0) for i in range(4, 9)], sync=False)
        plan = chaos.capture_crash(rng)
        st.shutdown()
        chaos.apply_crash(plan)
        st2 = FileLogStorage(os.path.join(root, "log"))
        st2.init()  # must not raise: flip is in the unsynced region
        assert st2.last_log_index() >= 3
        for i in range(1, st2.last_log_index() + 1):
            assert st2.get_entry(i).data == _entry(i, 0).data
        st2.shutdown()


def test_durable_bit_rot_fails_loudly_filelog(tmp_path):
    """Corruption BELOW the durability watermark is not a torn tail:
    startup must refuse to truncate acked entries."""
    d = str(tmp_path / "rot")
    st = FileLogStorage(d)
    st.init()
    st.append_entries([_entry(i, 0) for i in range(1, 6)], sync=True)
    st.shutdown()  # advances the watermark over everything
    seg = next(n for n in os.listdir(d) if n.startswith("seg_"))
    p = os.path.join(d, seg)
    blob = bytearray(open(p, "rb").read())
    blob[len(blob) // 2] ^= 0x40
    open(p, "wb").write(bytes(blob))
    st2 = FileLogStorage(d)
    try:
        st2.init()
        raise AssertionError("durable-region rot went undetected")
    except CorruptLogError:
        pass


def test_multilog_get_crc_guards_read_path(tmp_path):
    """Bit rot in a live, indexed record: tlm_get must fail loudly
    (CorruptLogError), not hand garbage (or a silent hole) upward."""
    d = str(tmp_path / "mrot")
    s = MultiLogStorage(d, "g")
    s.init()
    s.append_entries([_entry(i, 0) for i in range(1, 4)], sync=True)
    jnl = next(n for n in sorted(os.listdir(d))
               if n.startswith("journal_"))
    p = os.path.join(d, jnl)
    blob = bytearray(open(p, "rb").read())
    blob[30] ^= 0x10  # inside the first record's payload
    open(p, "wb").write(bytes(blob))
    try:
        s.get_entry(1)
        raise AssertionError("rotted record served without complaint")
    except CorruptLogError:
        pass
    finally:
        s.shutdown()


def test_multilog_len_rot_on_live_record_fails_loudly(tmp_path):
    """A len field rotted HIGH on a live, indexed record must surface
    as corruption (CorruptLogError), not read as a missing-entry hole
    via a short payload read."""
    d = str(tmp_path / "lenrot")
    s = MultiLogStorage(d, "g")
    s.init()
    s.append_entries([_entry(i, 0) for i in range(1, 3)], sync=True)
    jnl = next(n for n in sorted(os.listdir(d))
               if n.startswith("journal_"))
    p = os.path.join(d, jnl)
    blob = bytearray(open(p, "rb").read())
    blob[3] |= 0x40  # inflate the first record's len field past the file
    open(p, "wb").write(bytes(blob))
    try:
        s.get_entry(1)
        raise AssertionError("len-rotted record read as a hole")
    except CorruptLogError:
        pass
    finally:
        s.shutdown()


def test_multilog_unreadable_registry_fails_open_not_truncates(tmp_path):
    """A registry that cannot be READ must fail the engine open loudly
    (retryable) — scanning journals against a partial registry would
    read every acked record as orphan garbage and truncate them."""
    d = str(tmp_path / "regdead")
    s = MultiLogStorage(d, "g")
    s.init()
    s.append_entries([_entry(1, 0)], sync=True)
    s.shutdown()
    jsize = os.path.getsize(os.path.join(d, next(
        n for n in sorted(os.listdir(d)) if n.startswith("journal_"))))
    reg = os.path.join(d, "groups")
    os.remove(reg)
    os.mkdir(reg)  # open(O_RDWR) now fails EISDIR: unreadable registry
    s2 = MultiLogStorage(d, "g")
    try:
        s2.init()
        raise AssertionError("open succeeded against unreadable registry")
    except IOError:
        pass
    # the acked journal bytes must be untouched by the failed open
    jnl = next(n for n in sorted(os.listdir(d))
               if n.startswith("journal_"))
    assert os.path.getsize(os.path.join(d, jnl)) == jsize
    os.rmdir(reg)


def test_multilog_registry_gid_alias_is_truncated(tmp_path):
    """A flipped gid in the registry's unsynced tail must not alias an
    acked gid (shadowing another group's log): the sequential-gid scan
    truncates the tail at the deviation."""
    d = str(tmp_path / "reg")
    sa, sb = MultiLogStorage(d, "a"), MultiLogStorage(d, "b")
    sa.init(), sb.init()
    sa.engine.sync()  # both registrations acked
    gid_a, gid_b = sa._gid, sb._gid
    sa.shutdown(), sb.shutdown()
    # forge a tail record claiming gid_a for a different name (what a
    # partial-page writeback bit flip can leave behind)
    with open(os.path.join(d, "groups"), "ab") as f:
        f.write(struct.pack("<II", gid_a, 1) + b"z")
    sa2, sz = MultiLogStorage(d, "a"), MultiLogStorage(d, "z")
    sa2.init(), sz.init()
    try:
        assert sa2._gid == gid_a
        assert sz._gid not in (gid_a, gid_b), "alias adopted: shadowing"
    finally:
        sa2.shutdown(), sz.shutdown()


def test_multilog_registry_tolerates_legacy_gid_gaps(tmp_path):
    """Registries written before register_group rolled next_gid back on
    a failed append can hold gid GAPS in their durable region; the
    alias guard must accept those (strictly increasing), not truncate
    acked registrations on upgrade."""
    d = str(tmp_path / "gap")
    sa, sb = MultiLogStorage(d, "a"), MultiLogStorage(d, "b")
    sa.init(), sb.init()
    gid_a, gid_b = sa._gid, sb._gid
    sa.engine.sync()
    sa.shutdown(), sb.shutdown()
    # legacy gap: a registration that consumed gid_b+1 without a record,
    # then a later group registered at gid_b+2
    with open(os.path.join(d, "groups"), "ab") as f:
        f.write(struct.pack("<II", gid_b + 2, 1) + b"c")
    sa2 = MultiLogStorage(d, "a")
    sb2 = MultiLogStorage(d, "b")
    sc2 = MultiLogStorage(d, "c")
    sd2 = MultiLogStorage(d, "dnew")
    for s in (sa2, sb2, sc2, sd2):
        s.init()
    try:
        assert sa2._gid == gid_a and sb2._gid == gid_b
        assert sc2._gid == gid_b + 2, "gap-following record truncated"
        assert sd2._gid == gid_b + 3  # next_gid resumed past the gap
    finally:
        for s in (sa2, sb2, sc2, sd2):
            s.shutdown()


def test_multilog_orphan_journal_records_are_torn(tmp_path):
    """Journal records whose registration never became durable are an
    unsynced tail by construction: recovery truncates them instead of
    adopting records for an unregistered gid."""
    import shutil

    d = str(tmp_path / "orph")
    sa = MultiLogStorage(d, "a")
    sa.init()
    sa.append_entries([_entry(1, 0)], sync=True)   # a: acked
    reg_durable = os.path.getsize(os.path.join(d, "groups"))
    sb = MultiLogStorage(d, "b")
    sb.init()                                       # b: registration staged
    sb.append_entries([_entry(1, 0), _entry(2, 0)], sync=False)
    # power loss: journal pages survived writeback, registry tail didn't
    img = str(tmp_path / "orph_img")
    shutil.copytree(d, img)
    with open(os.path.join(img, "groups"), "r+b") as f:
        f.truncate(reg_durable)
    sa.shutdown(), sb.shutdown()
    ra, rb = MultiLogStorage(img, "a"), MultiLogStorage(img, "b")
    ra.init(), rb.init()
    try:
        assert ra.last_log_index() == 1
        assert ra.get_entry(1).data == _entry(1, 0).data
        # b's staged-only records were truncated with its registration;
        # the re-registered b starts empty (no adopted orphan records)
        assert rb.last_log_index() == 0
        assert rb.get_entry(1) is None
    finally:
        ra.shutdown(), rb.shutdown()


async def test_reboot_after_compaction_keeps_acked_suffix(tmp_path):
    """Regression for the amnesiac-reboot bug the power-loss soak found:
    after snapshot compaction prunes the entry AT the snapshot index
    (margin 0, first == S+1), the next boot's set_snapshot saw term 0
    there, called it divergence, and RESET the log — silently dropping
    the whole acked suffix.  Two stores rebooting in one fault window
    then break quorum intersection and un-commit acked writes."""
    from tpuraft.conf import Configuration, ConfigurationEntry
    from tpuraft.storage.log_manager import LogManager

    d = str(tmp_path / "lm")
    conf = ConfigurationEntry(
        LogId(0, 0), Configuration.parse("1.1.1.1:1,1.1.1.2:1,1.1.1.3:1"))

    st = FileLogStorage(d)
    lm = LogManager(st)
    await lm.init()
    await lm.append_entries_follower(
        0, 0, [_entry(i, 0, term=3) for i in range(1, 11)])
    # snapshot at 5 (margin 0): prunes entries <= 5, first becomes 6
    await lm.set_snapshot(LogId(5, 3), conf)
    assert lm.first_log_index() == 6 and lm.last_log_index() == 10
    await lm.shutdown()

    # reboot: snapshot load replays set_snapshot on the compacted log
    st2 = FileLogStorage(d)
    lm2 = LogManager(st2)
    await lm2.init()
    await lm2.set_snapshot(LogId(5, 3), conf)
    assert lm2.last_log_index() == 10, \
        "acked suffix dropped on reboot after compaction"
    for i in range(6, 11):
        assert lm2.get_term(i) == 3
    assert lm2.check_consistency().is_ok()
    await lm2.shutdown()

    # the true-divergence case still resets: entry AT the snapshot index
    # present with a DIFFERENT term (install-snapshot over a stale log)
    st3 = FileLogStorage(str(tmp_path / "lm3"))
    lm3 = LogManager(st3)
    await lm3.init()
    await lm3.append_entries_follower(
        0, 0, [_entry(i, 0, term=2) for i in range(1, 11)])
    await lm3.set_snapshot(LogId(7, 5), conf)   # term 5 != stored term 2
    assert lm3.last_log_index() == 7            # stale tail dropped
    assert lm3.first_log_index() == 8
    await lm3.shutdown()


def test_chaosdir_lost_fsync_and_survival(tmp_path):
    """Sanity of the model itself: unsynced bytes vanish under
    lost-fsync; fsynced bytes always survive."""
    root = str(tmp_path / "model")
    rng = random.Random(5)
    with ChaosDir(root, modes=(("lost-fsync", 1.0),)) as chaos:
        p = os.path.join(root, "f.bin")
        f = open(p, "wb")
        f.write(b"durable")
        f.flush()
        os.fsync(f.fileno())
        f.write(b"+volatile")
        f.flush()
        f.close()
        assert open(p, "rb").read() == b"durable+volatile"
        chaos.crash(rng)
        assert open(p, "rb").read() == b"durable"


# ---------------------------------------------------------------------------
# disk-pressure fault plane: quota / ENOSPC (ISSUE 17)
# ---------------------------------------------------------------------------


def test_chaosdir_quota_partial_write_then_enospc(tmp_path):
    """The capacity fault plane itself: a write crossing the budget
    commits the fitting prefix (short write) then fails ENOSPC; deletes
    refund the budget."""
    import errno as _errno

    root = str(tmp_path / "quota")
    with ChaosDir(root) as chaos:
        p = os.path.join(root, "f.bin")
        with open(p, "wb") as f:
            f.write(b"x" * 60)
        chaos.set_quota(100)
        try:
            with open(p, "ab") as f:
                f.write(b"y" * 80)
            raise AssertionError("over-budget write admitted whole")
        except OSError as e:
            assert e.errno == _errno.ENOSPC
        # the fitting 40-byte prefix landed before the error
        assert os.path.getsize(p) == 100
        assert chaos.enospc_counts.get("write", 0) == 1
        limit, used = chaos.quota_state()
        assert limit == 100 and used >= 100
        # refund on remove: the budget frees and writes admit again
        os.remove(p)
        with open(os.path.join(root, "g.bin"), "wb") as f:
            f.write(b"z" * 50)
        assert os.path.getsize(os.path.join(root, "g.bin")) == 50


def test_chaosdir_quota_shrink_and_burst(tmp_path):
    """quota-shrink-over-time tightens the wall; seeded bursts fail
    writes wholesale regardless of budget and heal at rate 0."""
    root = str(tmp_path / "sq")
    with ChaosDir(root) as chaos:
        chaos.set_quota(1000)
        assert chaos.shrink_quota(400) == 600
        p = os.path.join(root, "f.bin")
        with open(p, "wb") as f:
            f.write(b"a" * 500)
        try:
            with open(p, "ab") as f:
                f.write(b"b" * 200)
            raise AssertionError("shrunk quota not enforced")
        except OSError:
            pass
        chaos.set_enospc_burst(1.0, seed=9)
        try:
            with open(os.path.join(root, "h.bin"), "wb") as f:
                f.write(b"c")
            raise AssertionError("burst rate 1.0 admitted a write")
        except OSError:
            pass
        assert chaos.enospc_counts.get("burst", 0) >= 1
        chaos.set_enospc_burst(0.0)
        chaos.clear_quota()
        with open(os.path.join(root, "h.bin"), "wb") as f:
            f.write(b"c" * 300)  # healed


def test_chaosdir_quota_rename_enospc(tmp_path):
    """Creating a fresh directory entry on a full tree fails ENOSPC
    (the path snapshot commit / meta compaction renames exercise)."""
    root = str(tmp_path / "rq")
    with ChaosDir(root) as chaos:
        src = os.path.join(root, "src.bin")
        with open(src, "wb") as f:
            f.write(b"x" * 100)
        chaos.set_quota(100)  # exactly full
        try:
            os.rename(src, os.path.join(root, "dst.bin"))
            raise AssertionError("rename to fresh entry on full tree")
        except OSError:
            pass
        assert chaos.enospc_counts.get("rename", 0) == 1
        # replacing an EXISTING entry stays allowed (no new inode)
        dst = os.path.join(root, "src.bin")  # self-replace: dst exists
        os.replace(src, dst)


def test_filelog_enospc_append_fails_clean_and_retries(tmp_path):
    """An append that dies ENOSPC leaves the storage view unchanged
    (no phantom index advance) and the SAME batch retries cleanly after
    space frees — partial garbage at the tail is overwritten, never
    served."""
    root = str(tmp_path / "flq")
    with ChaosDir(root) as chaos:
        st = FileLogStorage(os.path.join(root, "log"))
        st.init()
        st.append_entries([_entry(i, 0) for i in range(1, 6)], sync=True)
        base = st.last_log_index()
        chaos.set_quota(chaos.quota_state()[1] + 20)  # ~half an entry
        batch = [_entry(base + 1, 1), _entry(base + 2, 1)]
        try:
            st.append_entries(batch, sync=True)
            raise AssertionError("ENOSPC append reported success")
        except OSError:
            pass
        assert st.last_log_index() == base
        for i in range(1, base + 1):
            assert st.get_entry(i).data == _entry(i, 0).data
        chaos.clear_quota()
        st.append_entries(batch, sync=True)  # same batch, now fits
        assert st.last_log_index() == base + 2
        for e in batch:
            assert st.get_entry(e.id.index).data == e.data
        st.shutdown()
        # and the healed tail survives a reopen (no torn garbage kept)
        st2 = FileLogStorage(os.path.join(root, "log"))
        st2.init()
        assert st2.last_log_index() == base + 2
        st2.shutdown()


def test_filelog_shutdown_and_reopen_on_full_disk(tmp_path):
    """A store must SHUT DOWN and BOOT on a genuinely full disk: the
    non-sync watermark saves (init scan, clean shutdown) only advance a
    stale-LOW-safe optimization, so ENOSPC on ``synced.tmp`` must not
    propagate.  Caught by the 300s --disk-pressure --power-loss soak:
    the power-loss kill's graceful stop died mid-shutdown on the
    watermark write and the store never came back."""
    root = str(tmp_path / "flfull")
    with ChaosDir(root) as chaos:
        st = FileLogStorage(os.path.join(root, "log"))
        st.init()
        st.append_entries([_entry(i, 0) for i in range(1, 8)], sync=True)
        chaos.set_quota(chaos.quota_state()[1])  # zero headroom
        st.shutdown()                            # must not raise
        # boot on the still-full disk: init's watermark refresh is also
        # best-effort; the log itself is read back intact
        st2 = FileLogStorage(os.path.join(root, "log"))
        st2.init()
        assert st2.last_log_index() == 7
        for i in range(1, 8):
            assert st2.get_entry(i).data == _entry(i, 0).data
        st2.shutdown()
    # and with the quota lifted the watermark heals on the next cycle
    st3 = FileLogStorage(os.path.join(root, "log"))
    st3.init()
    assert st3.last_log_index() == 7
    st3.shutdown()


def test_meta_journal_close_on_full_disk(tmp_path):
    """MetaJournal.close() on a full disk: the fsync lands (durability
    holds), the watermark save is best-effort, close does not raise,
    and the values replay on reopen."""
    root = str(tmp_path / "mjfull")
    with ChaosDir(root) as chaos:
        j = MetaJournal(root)
        j.stage("g1", 7, PeerId.parse("127.0.0.1:1"))
        j.sync()
        chaos.set_quota(chaos.quota_state()[1])  # zero headroom
        try:
            # the staged append itself fails ENOSPC (the vote-save
            # handler surfaces that as a refused grant) — the landed
            # prefix is torn-tail garbage the replay discards
            j.stage("g2", 9, PeerId.parse("127.0.0.1:2"))
        except OSError:
            pass
        j.close()     # must not raise (watermark tmp hits ENOSPC)
    j2 = MetaJournal(root)
    term, voted = j2.get("g1")
    assert term == 7 and str(voted) == "127.0.0.1:1"
    j2.close()


def test_native_quota_mirror_enospc(tmp_path):
    """The native multilog's quota mirror: attach_quota installs the
    engine fault gate; appends past the journal budget fail ENOSPC,
    acked entries stay readable, clear_quota heals."""
    d = str(tmp_path / "natq")
    s = MultiLogStorage(d, "g")
    s.init()
    s.append_entries([_entry(i, 0) for i in range(1, 4)], sync=True)
    tracker = NativeJournalTracker(d)
    tracker.attach_quota(s.engine, limit_bytes=tracker._dir_usage() + 16)
    try:
        s.append_entries([_entry(4, 0)], sync=True)
        raise AssertionError("native append past journal budget")
    except OSError:
        pass
    assert s.last_log_index() == 3
    for i in range(1, 4):
        assert s.get_entry(i).data == _entry(i, 0).data
    tracker.clear_quota()
    s.append_entries([_entry(4, 0)], sync=True)
    assert s.get_entry(4).data == _entry(4, 0).data
    # burst mirror: whole-op seeded failures, rate 0 heals
    tracker.attach_quota(s.engine, burst_rate=1.0, seed=3)
    try:
        s.append_entries([_entry(5, 0)], sync=True)
        raise AssertionError("burst rate 1.0 admitted a native append")
    except OSError:
        pass
    tracker.attach_quota(s.engine, burst_rate=0.0)
    s.append_entries([_entry(5, 0)], sync=True)
    s.shutdown()


def test_snapshot_save_enospc_keeps_old_snapshot(tmp_path):
    """ENOSPC mid snapshot save: the previous snapshot stays loadable,
    the aborted temp is swept, and the save succeeds once space frees."""
    from tpuraft.rpc.messages import SnapshotMeta
    from tpuraft.storage.snapshot import LocalSnapshotStorage

    root = str(tmp_path / "snapq")
    with ChaosDir(root) as chaos:
        stor = LocalSnapshotStorage(os.path.join(root, "snap"))
        stor.init()
        w = stor.create()
        w.write_file("kv", b"gen1" * 50)
        stor.commit(w, SnapshotMeta(last_included_index=10,
                                    last_included_term=1))
        assert stor.open().load_meta().last_included_index == 10

        chaos.set_quota(chaos.quota_state()[1] + 30)
        w2 = stor.create()
        try:
            w2.write_file("kv", b"gen2" * 200)
            raise AssertionError("over-budget snapshot write admitted")
        except OSError:
            pass
        # old snapshot intact, correct bytes
        r = stor.open()
        assert r.load_meta().last_included_index == 10
        assert r.read_file("kv") == b"gen1" * 50

        chaos.clear_quota()
        stor.init()  # sweeps the aborted temp dir
        w3 = stor.create()
        w3.write_file("kv", b"gen2" * 200)
        stor.commit(w3, SnapshotMeta(last_included_index=20,
                                     last_included_term=1))
        assert stor.open().load_meta().last_included_index == 20


def test_snapshot_storage_init_sweeps_orphans(tmp_path):
    """init() removes crash-orphaned snapshot_<N> dirs: stale older
    dirs the post-commit prune never got to, and unreadable newer dirs
    whose manifest never became durable."""
    from tpuraft.rpc.messages import SnapshotMeta
    from tpuraft.storage.snapshot import LocalSnapshotStorage

    root = str(tmp_path / "sweep")
    stor = LocalSnapshotStorage(root)
    stor.init()
    w = stor.create()
    w.write_file("kv", b"live")
    stor.commit(w, SnapshotMeta(last_included_index=10,
                                last_included_term=1))
    # stale older dir (prune-after-replace never ran) + manifestless
    # newer dir (replace landed, manifest lost to the crash)
    os.makedirs(os.path.join(root, "snapshot_5"))
    with open(os.path.join(root, "snapshot_5", "kv"), "wb") as f:
        f.write(b"stale")
    os.makedirs(os.path.join(root, "snapshot_20"))

    stor2 = LocalSnapshotStorage(root)
    stor2.init()
    names = sorted(n for n in os.listdir(root) if n.startswith("snapshot_"))
    assert names == ["snapshot_10"], names
    assert stor2.open().read_file("kv") == b"live"

    # nothing loadable at all -> keep everything for forensics
    root2 = str(tmp_path / "sweep2")
    os.makedirs(os.path.join(root2, "snapshot_7"))
    s3 = LocalSnapshotStorage(root2)
    s3.init()
    assert os.path.isdir(os.path.join(root2, "snapshot_7"))


def test_meta_journal_enospc_mid_compaction(tmp_path):
    """ENOSPC during the journal's compaction rewrite must not fail the
    sync round or hurt the journal: values stay readable, the partial
    tmp is dropped, and compaction succeeds after space frees."""
    root = str(tmp_path / "mjq")
    with ChaosDir(root) as chaos:
        j = MetaJournal(root)
        j.COMPACT_MIN_BYTES = 512
        peer = PeerId.parse("10.0.0.1:80")
        # pile up garbage records well past the compaction threshold
        for t in range(1, 120):
            j.stage("g0", t, peer)
            j.stage("g1", t, peer)
        chaos.set_quota(chaos.quota_state()[1])  # zero headroom
        j.sync()   # fsync ok (bytes already staged); compaction dies
        assert j.get("g0") == (119, peer)
        assert j.get("g1") == (119, peer)
        assert not os.path.exists(os.path.join(root, "meta.jnl.tmp"))
        # journal still ACCEPTS overwrites of staged bytes... heal and
        # prove full service: stage + sync + eventual compaction
        chaos.clear_quota()
        j.stage("g0", 200, peer)
        j.sync()
        assert j.get("g0") == (200, peer)
        j.close()
        j2 = MetaJournal(root)
        assert j2.get("g0") == (200, peer)
        assert j2.get("g1") == (119, peer)
        j2.close()


def test_disk_budget_thresholds_hysteresis_resume():
    from tpuraft.util.health import (
        PRESSURE_FULL,
        PRESSURE_NEAR_FULL,
        PRESSURE_OK,
        DiskBudget,
        DiskBudgetOptions,
    )

    b = DiskBudget(DiskBudgetOptions(budget_bytes=1000, worsen_after=1,
                                     recover_after=2))
    b.note_append(500)
    assert b.evaluate() == PRESSURE_OK
    b.note_append(350)            # 850/1000 >= 0.80
    assert b.evaluate() == PRESSURE_NEAR_FULL
    b.note_append(100)            # 950/1000 >= 0.92
    assert b.evaluate() == PRESSURE_FULL
    # recovery is hysteretic: reclaim must PROVE space recover_after
    # consecutive rounds before pressure relaxes (then: one resume)
    b.note_reclaimed(500)         # 450/1000
    assert b.evaluate() == PRESSURE_FULL
    assert b.evaluate() == PRESSURE_OK
    c = b.counters()
    assert c["disk_pressure_resumes"] == 1
    assert c["disk_reclaimed_bytes"] == 500
    # reconcile re-bases the estimate (rmtree deletes the hot path
    # never saw), and set_budget adopts an operator resize
    b.reconcile(900)
    assert b.used_bytes() == 900
    b.set_budget(2000)
    assert b.evaluate() == PRESSURE_OK   # 900/2000: headroom again
    assert b.capacity_bytes() == 2000


def test_disk_budget_enospc_latch_pins_full():
    """An observed ENOSPC pins raw FULL for enospc_latch_rounds no
    matter what the byte estimate says — the errno outranks it."""
    from tpuraft.util.health import (
        PRESSURE_FULL,
        PRESSURE_OK,
        DiskBudget,
        DiskBudgetOptions,
    )

    b = DiskBudget(DiskBudgetOptions(budget_bytes=1000, worsen_after=1,
                                     recover_after=1, enospc_latch_rounds=2))
    b.note_append(10)             # estimate says: nearly empty
    assert b.evaluate() == PRESSURE_OK
    b.note_enospc()
    assert b.evaluate() == PRESSURE_FULL
    assert b.evaluate() == PRESSURE_FULL     # latch round 2
    assert b.evaluate() == PRESSURE_OK       # latch expired, estimate rules
    assert b.counters()["disk_enospc_events"] == 1
    assert b.counters()["disk_pressure_resumes"] == 1


async def test_log_manager_enospc_flush_rolls_back_frontier(tmp_path):
    """Regression for the non-contiguous-append wedge the disk-pressure
    soak found: a flush that dies ENOSPC must fail its waiters AND roll
    the in-memory frontier back to what storage holds — otherwise the
    next append passes the in-memory contiguity check, trips storage's
    gap check, and the node is wedged in ERROR forever."""
    from tpuraft.errors import RaftException
    from tpuraft.storage.log_manager import LogManager

    root = str(tmp_path / "lmq")
    with ChaosDir(root) as chaos:
        st = FileLogStorage(os.path.join(root, "log"))
        lm = LogManager(st)
        await lm.init()
        await lm.append_entries_follower(
            0, 0, [_entry(i, 0, term=2) for i in range(1, 6)])
        assert lm.last_log_index() == 5
        chaos.set_quota(chaos.quota_state()[1] + 10)
        try:
            await lm.append_entries_follower(
                5, 2, [_entry(i, 0, term=2) for i in range(6, 9)])
            raise AssertionError("ENOSPC flush reported success")
        except RaftException:
            pass
        # frontier converged back onto storage: no phantom suffix
        assert lm.last_log_index() == st.last_log_index()
        # heal -> the SAME entries re-append cleanly (leader retry)
        chaos.clear_quota()
        base = lm.last_log_index()
        ok = await lm.append_entries_follower(
            base, 2, [_entry(i, 0, term=2) for i in range(base + 1, 9)])
        assert ok and lm.last_log_index() == 8
        assert lm.check_consistency().is_ok()
        for i in range(1, 9):
            assert lm.get_term(i) == 2
        await lm.shutdown()


def _filelog_quota_crash_lifetime(root: str, rng: random.Random,
                                  gens: int) -> int:
    """Seeded-crash matrix with quota faults layered in: every
    generation runs under a shifting byte budget (including seeded
    ENOSPC bursts), appends tolerate ENOSPC without model drift, and a
    power-loss crash ends the generation.  Invariants are the usual
    acked floor / staged ceiling / byte-match set."""
    first, entries, acked_last = 1, {}, 0

    def staged_last():
        return max(entries) if entries else first - 1

    with ChaosDir(root) as chaos:
        for gen in range(gens):
            chaos.clear_quota()
            chaos.set_enospc_burst(0.0)
            st = FileLogStorage(os.path.join(root, "log"),
                                segment_max_bytes=200)
            st.init()
            rf, rl = st.first_log_index(), st.last_log_index()
            assert rf == first, f"gen {gen}: first {rf} != {first}"
            assert acked_last <= rl <= staged_last(), \
                f"gen {gen}: last {rl} not in [{acked_last}, {staged_last()}]"
            for i in range(rf, rl + 1):
                e = st.get_entry(i)
                assert e is not None and e.data == entries[i], \
                    f"gen {gen}: entry {i} mismatch"
            for i in list(entries):
                if i > rl:
                    del entries[i]
            acked_last = rl

            # quota fault for this generation: tight budget, seeded
            # burst, or free-running (the original matrix)
            mode = rng.random()
            if mode < 0.4:
                chaos.set_quota(chaos.quota_state()[1]
                                + rng.randrange(0, 400))
            elif mode < 0.6:
                chaos.set_enospc_burst(0.3, seed=rng.randrange(1 << 30))

            for _ in range(rng.randrange(1, 5)):
                n = rng.randrange(1, 6)
                batch = [_entry(staged_last() + 1 + k, gen)
                         for k in range(n)]
                try:
                    st.append_entries(batch, sync=True)
                except OSError:
                    # ENOSPC: storage contract says the view advanced
                    # only to what landed whole — adopt ITS frontier
                    # (landed entries are staged, NOT acked: the batch
                    # fsync never ran)
                    landed = st.last_log_index()
                    for e in batch:
                        if e.id.index <= landed:
                            entries[e.id.index] = e.data
                    if rng.random() < 0.5:
                        chaos.clear_quota()
                        chaos.set_enospc_burst(0.0)
                    continue
                for e in batch:
                    entries[e.id.index] = e.data
                acked_last = staged_last()

            if rng.random() < 0.5:
                batch = [_entry(staged_last() + 1 + k, gen, term=2)
                         for k in range(rng.randrange(1, 4))]
                try:
                    st.append_entries(batch, sync=False)
                    for e in batch:
                        entries[e.id.index] = e.data
                except OSError:
                    landed = st.last_log_index()
                    for e in batch:
                        if e.id.index <= landed:
                            entries[e.id.index] = e.data

            plan = chaos.capture_crash(rng)   # power dies (quota live)
            # the faults die with the power: shutdown's own writes are
            # discarded by the image anyway, but they must not blow up
            # the harness on the still-armed quota
            chaos.clear_quota()
            chaos.set_enospc_burst(0.0)
            st.shutdown()
            chaos.apply_crash(plan)
        return chaos.crash_count


def test_filelog_quota_crash_matrix():
    import tempfile

    crashes = 0
    for seed in range(3):
        with tempfile.TemporaryDirectory() as tmp:
            crashes += _filelog_quota_crash_lifetime(
                os.path.join(tmp, f"qlog{seed}"),
                random.Random(4000 + seed), gens=20)
    assert crashes >= 60
