"""[1.3+] parity features: priority election, snapshot throttle, describe.

Reference anchors (SURVEY.md §3.1/§6): NodeImpl#allowLaunchElection /
targetPriority decay, ThroughputSnapshotThrottle, NodeImpl#describe +
Describer signal dumps.
"""

import asyncio
import time

import pytest

from tests.cluster import TestCluster
from tpuraft.core.node import State
from tpuraft.entity import ElectionPriority, PeerId
from tpuraft.storage.snapshot import ThroughputSnapshotThrottle
from tpuraft.util import describer


def _priority_cluster(tmp_path, prios, **kw):
    c = TestCluster(len(prios), tmp_path=None, **kw)
    c.peers = [PeerId("127.0.0.1", 5000 + i, 0, pr)
               for i, pr in enumerate(prios)]
    from tpuraft.conf import Configuration

    c.conf = Configuration(list(c.peers))
    return c


# -- throttle (pure unit, fake clock) ---------------------------------------

def test_throttle_token_bucket():
    now = [0.0]
    t = ThroughputSnapshotThrottle(1000, clock=lambda: now[0])
    assert t.throttled_by_throughput(400) == 400
    assert t.throttled_by_throughput(800) == 600  # bucket drained
    assert t.throttled_by_throughput(100) == 0
    now[0] += 0.5  # refills 500
    assert t.throttled_by_throughput(10_000) == 500
    now[0] += 10.0  # burst capped at 1s worth
    assert t.throttled_by_throughput(10_000) == 1000


@pytest.mark.asyncio
async def test_throttle_acquire_waits():
    t = ThroughputSnapshotThrottle(10_000)
    t.throttled_by_throughput(10_000)  # drain
    t0 = time.monotonic()
    got = await t.acquire_upto(1000)
    assert got > 0
    assert time.monotonic() - t0 < 1.0  # refills quickly at 10KB/s


@pytest.mark.asyncio
async def test_get_file_throttled_end_to_end():
    """File service serves partial chunks under throttle; copier still
    reassembles the full file, paced to the byte rate."""
    from tpuraft.core.node_manager import NodeManager
    from tpuraft.core.snapshot_executor import _ChunkAdapter
    from tpuraft.rpc.transport import InProcNetwork, InProcTransport, RpcServer
    from tpuraft.storage.snapshot import RemoteFileCopier

    class OneFile:
        data = bytes(range(256)) * 16  # 4 KiB

        def read_chunk(self, name, offset, count):
            assert name == "blob"
            chunk = self.data[offset:offset + count]
            return chunk, offset + len(chunk) >= len(self.data)

    net = InProcNetwork()
    server = RpcServer("srv:0")
    manager = NodeManager(server)
    net.bind(server)
    net.start_endpoint("srv:0")
    throttle = ThroughputSnapshotThrottle(16 * 1024)  # 16 KiB/s, 4 KiB file
    rid = manager.register_file_reader(_ChunkAdapter(OneFile(), throttle))
    throttle.throttled_by_throughput(16 * 1024)  # start with an empty bucket
    copier = RemoteFileCopier(InProcTransport(net, "cli:0"), "srv:0", rid,
                              chunk_size=1024)
    t0 = time.monotonic()
    blob = await copier.read_bytes("blob")
    elapsed = time.monotonic() - t0
    assert blob == OneFile.data
    assert elapsed >= 0.2  # 4 KiB at 16 KiB/s from empty bucket ≈ 0.25s


# -- priority election ------------------------------------------------------

@pytest.mark.asyncio
async def test_priority_highest_wins():
    c = _priority_cluster(None, [60, 40, 20], election_timeout_ms=200)
    await c.start_all()
    try:
        leader = await c.wait_leader()
        assert leader.server_id.priority == 60
        # followers never decayed: target still the max
        for n in c.nodes.values():
            assert n.target_priority == 60
    finally:
        await c.stop_all()


@pytest.mark.asyncio
async def test_priority_decay_when_high_node_dead():
    """With the priority-60 node never started, the 40-node must decay the
    target (60 -> 48 -> 38) and then win."""
    c = _priority_cluster(None, [60, 40, 20], election_timeout_ms=150)
    started = c.peers[1:]
    for p in started:
        await c.start(p)
    try:
        # the 40-node can only *start* an election after decaying the
        # target below 60, so it winning proves the decay ran (the
        # target itself may legitimately refresh back to the conf max on
        # any later step-down, so don't assert its final value)
        leader = await c.wait_leader(timeout_s=10.0)
        assert leader.server_id.priority == 40
    finally:
        await c.stop_all()


@pytest.mark.asyncio
async def test_priority_not_elected_never_starts_election():
    c = _priority_cluster(None, [ElectionPriority.NOT_ELECTED,
                                 ElectionPriority.NOT_ELECTED],
                          election_timeout_ms=100)
    await c.start_all()
    try:
        await asyncio.sleep(1.0)
        for n in c.nodes.values():
            assert n.state == State.FOLLOWER
            assert n.current_term == 0
    finally:
        await c.stop_all()


@pytest.mark.asyncio
async def test_disabled_priority_unchanged_behavior():
    """Default peers (priority -1) elect as before — gate is a no-op."""
    c = TestCluster(3, election_timeout_ms=200)
    await c.start_all()
    try:
        leader = await c.wait_leader()
        assert leader.target_priority == ElectionPriority.DISABLED
    finally:
        await c.stop_all()


# -- describe ---------------------------------------------------------------

@pytest.mark.asyncio
async def test_describe_and_registry_dump(tmp_path):
    c = TestCluster(3, election_timeout_ms=200)
    await c.start_all()
    try:
        leader = await c.wait_leader()
        st = await c.apply_ok(leader, b"x")
        assert st.is_ok()
        text = leader.describe()
        assert "state: leader" in text
        assert f"term: {leader.current_term}" in text
        assert "replicators:" in text
        assert "commit:" in text
        dump = describer.dump_all()
        # all three live nodes are registered
        for n in c.nodes.values():
            assert str(n) in dump
        # a follower's describe names the leader
        follower = next(n for n in c.nodes.values() if not n.is_leader())
        assert str(leader.server_id) in follower.describe()
    finally:
        await c.stop_all()
    # shutdown unregisters
    dump = describer.dump_all()
    for n in c.fsms:
        assert f"Node<{c.group_id}/{n}>" not in dump
