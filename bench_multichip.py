"""Mesh-mode engine ladder: ONE MultiRaftEngine spanning N devices
drives 64K+ raft groups with every [G] protocol lane active (ISSUE 19).

Three modes:

``--smoke``
    CPU dryrun on 8 virtual host devices (XLA_FLAGS force_host_platform
    _device_count): boots a mesh-mode engine at a small G and PROVES
    each lane engaged — witness commit clamp (device commit pinned to
    the best data-replica match on adversarial rows), stepdown/priority
    tick delivery, device read-fence quorum tallies, election-due
    scheduling.  Wired into ``make multichip-smoke`` / ``make check``.

``--scale``
    The acceptance rung: G=65536 groups sharded over 8 devices, same
    lane assertions, sustained tick-rate + commit-rate measurement.
    Writes MULTICHIP_r06.json and merges a ``sharded_engine`` row into
    BENCH_SCALE.json (riding alongside the real-protocol ladder rows,
    which prove the same lanes with full nodes at smaller G).

``--engine-shape``
    Single-device calibration shape for bench_gate.py: G leader-heavy
    groups on the no-jax numpy tick path, tick_once in a tight loop,
    RESULT line with best-of-N ticks/s.  Pre/post-PR comparable — the
    committed calibration pins the single-device engine against
    regressions from the mesh work.

The scale/smoke driver is a synthetic harness around the REAL engine:
stub controls stand in for nodes (counting the handler deliveries the
tick schedules), while the tensors, the sharded tick, the clamp, the
fence lane and the apply loops are the production code paths.  The
full-protocol proofs (elections, transfers, linearizability) live in
pytest and examples/soak.py; this bench proves the mesh plane carries
the lanes at a G no single-process node population can reach.
"""

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))


def _force_host_devices(n: int) -> None:
    """Must run before the first jax import anywhere in the process."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={n}".strip())
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


# ---------------------------------------------------------------------------
# stub control plane: counts what the tick delivers, owns nothing else
# ---------------------------------------------------------------------------

class _StubReplicators:
    def all(self):
        return []


class _StubNode:
    replicators = _StubReplicators()

    def is_leader(self):
        return True

    # handler objects the tick schedules by reference; the stub ctrl
    # counts deliveries instead of running them (real handlers re-verify
    # under the node lock — there is no node here)
    def _check_dead_nodes(self):
        pass

    def _on_election_due(self):
        pass

    def _on_engine_elected(self):
        pass

    def _on_engine_quorum_dead(self):
        pass

    def _on_snapshot_due(self):
        pass


class _StubCtrl:
    """EngineControl stand-in: the exact surface _apply_protocol and
    _flush_heartbeats touch, with shared delivery counters."""

    def __init__(self, engine, slot: int, counts: dict):
        self.engine = engine
        self.slot = slot
        self.node = _StubNode()
        self.counts = counts

    def _adopt_eto(self, eff_eto_ms: int) -> None:
        pass

    def push_election_deadline(self, now_ms=None) -> None:
        e = self.engine
        now = e.now_ms() if now_ms is None else now_ms
        e.elect_deadline[self.slot] = now + int(e.eto_ms[self.slot])

    def schedule(self, name: str, handler) -> None:
        self.counts[name] = self.counts.get(name, 0) + 1

    def maybe_quiesce(self, now: int) -> None:
        pass

    def wake_from_quiescence(self, reason: str = "activity",
                             *a, **kw) -> None:
        pass


class _StubFence:
    __slots__ = ("done",)
    resolved = 0

    def __init__(self):
        self.done = False

    def note_quorum(self):
        self.done = True
        _StubFence.resolved += 1


# ---------------------------------------------------------------------------
# mesh-mode driver (smoke + scale)
# ---------------------------------------------------------------------------

async def _drive_mesh(groups: int, devices: int, duration_s: float,
                      seed: int) -> dict:
    import resource

    import numpy as np

    from tpuraft.conf import Configuration
    from tpuraft.core.engine import (ROLE_FOLLOWER, ROLE_LEADER,
                                     MultiRaftEngine)
    from tpuraft.options import TickOptions

    rng = np.random.default_rng(seed)
    eng = MultiRaftEngine(TickOptions(
        max_groups=groups, max_peers=4, mesh_devices=devices,
        tick_interval_ms=20, eager_commit=False,
        density_aware_timeouts=False))
    t_boot = time.monotonic()
    await eng.start()
    assert eng._deadline_fold is not None, "mesh mode did not engage"

    G = eng.G
    factory = eng.ballot_box_factory()
    counts: dict = {}
    commits = [0]
    confs = {
        # 3 data voters — the witness-free steady state
        "data": Configuration.parse(
            "10.0.0.1:80,10.0.0.2:80,10.0.0.3:80"),
        # 2 data + 1 witness: the valid geo shape (quorum 2, one copy +
        # one metadata ack commits)
        "witness": Configuration.parse(
            "10.0.0.1:80,10.0.0.2:80,10.0.0.3:80/witness"),
        # witness-MAJORITY rows: invalid as a conf (is_valid refuses it
        # node-side) but exactly the degenerate tensor state the commit
        # clamp is the third safety layer against — the probe slots
        # prove the device clamp pins commit to the best data match
        "probe": Configuration.parse(
            "10.0.0.1:80,10.0.0.2:80/witness,10.0.0.3:80/witness"),
    }
    self_peer = confs["data"].peers[0]
    empty = Configuration()

    boxes = []
    kinds = np.zeros(G, dtype=np.int8)   # 0=data 1=witness 2=probe
    for s in range(G):
        box = factory(lambda idx, _c=commits: _c.__setitem__(
            0, _c[0] + 1))
        # probe stride lands on EVEN slots — the leader half, so the
        # clamp assertion actually measures committing groups
        kind = "probe" if s % 64 == 62 else (
            "witness" if s % 4 == 3 else "data")
        kinds[s] = {"data": 0, "witness": 1, "probe": 2}[kind]
        box.update_conf(confs[kind], empty)
        eng.register_ctrl(_StubCtrl(eng, s, counts), self_peer,
                          eto_ms=500, hb_ms=100, lease_ms=450)
        boxes.append(box)

    now = eng.now_ms()
    leaders = np.arange(G) % 2 == 0
    L = np.nonzero(leaders)[0]
    for s in L:
        boxes[s].reset_pending_index(1)
    eng.role[~leaders] = ROLE_FOLLOWER
    # election lane: a seeded sample of followers falls due during the
    # window; everyone else schedules far out (the election protocol
    # itself is proven in pytest/soak — here we prove lane delivery
    # without a 32K-slot python storm per eto)
    eng.elect_deadline[:] = now + 3_600_000
    sample = rng.choice(np.nonzero(~leaders)[0],
                        size=min(64, int((~leaders).sum())), replace=False)
    eng.elect_deadline[sample] = now + 50
    # beat fan-out is bench_scale's measurement (real replicators); the
    # stub has none to flush, so park the hb lane out of the window
    eng.hb_deadline[:] = now + 3_600_000
    # stepdown/priority lane: stagger first fire over one eto/2 period
    eng.stepdown_deadline[:] = now + rng.integers(1, 250, G)
    boot_s = time.monotonic() - t_boot

    # standing match rows.  Probe slots: data col 0 at 3, witness cols
    # at 9 — the unclamped quorum stat says 9, the clamp must pin 3.
    probe = kinds == 2
    Pn = np.nonzero(probe)[0]
    lead_probe = probe & leaders
    eng.match_abs[np.ix_(Pn, [1, 2])] = 9
    eng.match_abs[Pn, 0] = 3

    t0 = time.monotonic()
    ticks = 0
    rounds = 0
    fences: list = []
    drive = L[~probe[L]]
    while time.monotonic() - t0 < duration_s:
        rounds += 1
        now = eng.now_ms()
        # fresh voter acks for every leader (cols 0..2 are the voters)
        eng.last_ack[np.ix_(L, [0, 1, 2])] = now
        # advance the replicated tail: self + one follower move, the
        # second follower lags a round — quorum = the moving pair
        eng.match_abs[np.ix_(drive, [0, 1])] = rounds
        eng.match_abs[drive, 2] = max(0, rounds - 1)
        # arm a read-fence wave on a rotating slice of leaders
        wave = L[(rounds % 8)::16]
        for s in wave[:256]:
            f = _StubFence()
            fences.append((int(s), f))
            eng.arm_read_fence(int(s), f)
        eng.tick_once()
        ticks += 1
    elapsed = time.monotonic() - t0
    # one settle tick so the last fence wave sees a covering q_ack
    eng.last_ack[np.ix_(L, [0, 1, 2])] = eng.now_ms()
    eng.tick_once()
    ticks += 1

    # -- lane proofs --------------------------------------------------------
    # witness clamp: every probe LEADER's commit sits at the best data
    # match (3), never the unclamped quorum stat (9)
    probe_commits = eng.commit_abs[lead_probe]
    clamp_ok = bool((probe_commits <= 3).all())
    clamp_engaged = bool((probe_commits == 3).all())
    # plain witness groups commit normally through the clamp lane
    wit_lead = (kinds == 1) & leaders
    wit_commit_ok = bool((eng.commit_abs[wit_lead] >= rounds - 1).all())
    stats = eng.lane_stats()
    res = {
        "groups": G,
        "peers": 4,
        "mesh_devices": devices,
        "platform": "cpu-host-devices" if os.environ.get(
            "JAX_PLATFORMS", "").startswith("cpu") else "accelerator",
        "boot_s": round(boot_s, 1),
        "duration_s": round(elapsed, 2),
        "ticks": ticks,
        "ticks_per_sec": round(ticks / elapsed, 1),
        "drive_rounds": rounds,
        "commits": commits[0],
        "commits_per_sec": round(commits[0] / elapsed, 1),
        "witness_groups": stats["witness_groups"],
        "witness_commit_ok": wit_commit_ok,
        "clamp_probe_groups": int(lead_probe.sum()),
        "clamp_held": clamp_ok,
        "clamp_engaged": clamp_engaged,
        "stepdown_ticks": stats["stepdown_ticks"],
        "stepdown_handler_calls": counts.get("stepdown_tick", 0),
        "election_due_handled": counts.get("election_due", 0),
        "fence_armed": stats["fence_lane_armed"],
        "fence_resolved": stats["fence_lane_resolves"],
        "fences_pending": stats["fences_pending"],
        "rss_mb": round(resource.getrusage(
            resource.RUSAGE_SELF).ru_maxrss / 1024, 1),
    }
    failures = []
    if not int(lead_probe.sum()):
        failures.append("no clamp probe groups on the leader half")
    if not clamp_ok:
        failures.append(
            f"witness clamp BREACHED: probe commits {probe_commits[:8]}")
    if not clamp_engaged:
        failures.append("witness clamp never engaged on probe rows")
    if not wit_commit_ok:
        failures.append("witness-conf groups failed to commit")
    if res["stepdown_ticks"] <= 0 or res["stepdown_handler_calls"] <= 0:
        failures.append("stepdown/priority lane never fired")
    if res["fence_resolved"] <= 0:
        failures.append("device fence lane never resolved a round")
    if res["election_due_handled"] <= 0:
        failures.append("election lane never delivered")
    if commits[0] <= 0:
        failures.append("no commits advanced through the device tick")
    res["ok"] = not failures
    res["failures"] = failures
    await eng.shutdown()
    return res


def _merge_json(path: str, key: str, row: dict) -> None:
    out = {}
    if os.path.exists(path):
        with open(path) as f:
            out = json.load(f)
    out[key] = row
    with open(path, "w") as f:
        json.dump(out, f, indent=1)


def _run_mesh(args) -> int:
    import asyncio

    _force_host_devices(args.devices)
    groups = args.groups or (1024 if args.smoke else 65536)
    duration = args.duration or (1.5 if args.smoke else 6.0)
    res = asyncio.run(_drive_mesh(groups, args.devices, duration,
                                  args.seed))
    print("RESULT " + json.dumps(res), flush=True)
    if args.scale:
        tail = (f"sharded_engine({res['groups']}g x "
                f"{res['mesh_devices']}dev): {res['ticks_per_sec']} "
                f"ticks/s, {res['commits_per_sec']} commits/s, lanes "
                f"witness+stepdown+fence+election all engaged")
        with open(os.path.join(REPO, "MULTICHIP_r06.json"), "w") as f:
            json.dump({"n_devices": args.devices, "rc": 0 if res["ok"]
                       else 1, "ok": res["ok"], "skipped": False,
                       "tail": tail, "sharded_engine": res}, f, indent=1)
        _merge_json(os.path.join(REPO, "BENCH_SCALE.json"),
                    "sharded_engine", res)
    if not res["ok"]:
        print("FAIL: " + "; ".join(res["failures"]), file=sys.stderr)
        return 1
    print(f"multichip {'smoke' if args.smoke else 'scale'} OK: "
          f"{res['groups']} groups / {res['mesh_devices']} devices, "
          f"{res['ticks_per_sec']} ticks/s", flush=True)
    return 0


# ---------------------------------------------------------------------------
# --engine-shape: single-device calibration for bench_gate.py
# ---------------------------------------------------------------------------

def _engine_shape_once(groups: int, peers: int, duration_s: float,
                       seed: int) -> float:
    import numpy as np

    from tpuraft.core.engine import (ROLE_FOLLOWER, ROLE_LEADER,
                                     MultiRaftEngine)
    from tpuraft.options import TickOptions

    rng = np.random.default_rng(seed)
    # never start()ed: _tick_fn stays None, so this measures the numpy
    # tick path — identical pre/post mesh work, which is the point of
    # the gate (the single-device shape must not regress)
    eng = MultiRaftEngine(TickOptions(max_groups=groups, max_peers=peers,
                                      tick_interval_ms=20))
    g = eng.G
    now = eng.now_ms()
    # leader-heavy standing state: half leaders, half followers, 3 voters
    eng.role[:] = np.where(np.arange(g) % 2 == 0, ROLE_LEADER,
                           ROLE_FOLLOWER)
    eng.voter_mask[:, :3] = True
    eng.self_col[:] = 0
    eng.has_ctrl[:] = False      # no ctrls: measure the tick plane only
    eng.last_ack[:, :3] = now    # fresh quorum: no step_down churn
    eng.elect_deadline[:] = now + 3_600_000
    eng.hb_deadline[:] = now + 3_600_000
    eng.stepdown_deadline[:] = now + 3_600_000
    eng.match_abs[:, :3] = rng.integers(1, 50, size=(g, 3))
    eng.pending_rel[:] = 1
    t0 = time.perf_counter()
    ticks = 0
    while time.perf_counter() - t0 < duration_s:
        eng.tick_once()
        ticks += 1
    return ticks / (time.perf_counter() - t0)


def _run_engine_shape(args) -> int:
    best = max(_engine_shape_once(args.groups or 1024, 4,
                                  args.duration or 2.0, args.seed)
               for _ in range(3))
    print("RESULT " + json.dumps(
        {"engine_ticks_per_sec": round(best, 1),
         "groups": args.groups or 1024}), flush=True)
    return 0


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    mode = ap.add_mutually_exclusive_group(required=True)
    mode.add_argument("--smoke", action="store_true",
                      help="fast CPU 8-device lane-parity dryrun")
    mode.add_argument("--scale", action="store_true",
                      help="64K-group acceptance rung; writes "
                           "MULTICHIP_r06.json + BENCH_SCALE.json row")
    mode.add_argument("--engine-shape", action="store_true",
                      help="single-device tick-rate calibration shape "
                           "(bench_gate.py row)")
    ap.add_argument("--groups", type=int, default=0,
                    help="override G (default: 1024 smoke / 65536 scale)")
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--duration", type=float, default=0.0)
    ap.add_argument("--seed", type=int, default=7)
    args = ap.parse_args()
    if args.engine_shape:
        sys.exit(_run_engine_shape(args))
    sys.exit(_run_mesh(args))


if __name__ == "__main__":
    main()
