"""Scale ladder for the REAL protocol plane (VERDICT r2 #1): 1K -> 4K
-> 16K raft groups per process under sustained write load — engine
device-plane ticks + multilog fsync + RPC + FSM apply, NO synthetic
acks — recording commits/s, ack p50/p99, RSS, and asyncio task count
per G, plus the per-G overhead curve.

Topology per ladder rung: ONE process hosts all three replica
endpoints of every group (in-proc RPC; VERDICT: "in-proc or
loopback-TCP is fine"), each endpoint with its own MultiRaftEngine and
its own shared-journal multilog directory (real fsync on every append
round).  Leadership spreads by election priority.  The offered load is
paced per group so the ladder measures protocol capacity at scale, not
collapse behavior (the 3-process loopback-TCP variant lives in
bench_e2e.py and is recorded separately at its own G).

Each rung runs in a fresh subprocess (clean RSS accounting, no
cross-rung warm state).  Writes BENCH_SCALE.json; bench.py embeds it
as extra.scale so the driver's record carries the curve.
"""

import argparse
import asyncio
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))


async def run_rung(args) -> dict:
    import jax

    jax.config.update("jax_platforms", "cpu")

    import random
    import resource

    from tpuraft.conf import Configuration
    from tpuraft.core.engine import MultiRaftEngine
    from tpuraft.core.node import Node
    from tpuraft.core.node_manager import NodeManager
    from tpuraft.core.state_machine import StateMachine
    from tpuraft.entity import PeerId, Task
    from tpuraft.options import NodeOptions, TickOptions
    from tpuraft.rpc.transport import (InProcNetwork, InProcTransport,
                                       RpcServer)

    G, R = args.groups, args.replicas
    net = InProcNetwork()
    eps = [PeerId.parse(f"127.0.0.1:{7800 + i}") for i in range(R)]

    class CountFSM(StateMachine):
        applied = 0

        async def on_apply(self, it):
            while it.valid():
                CountFSM.applied += 1
                it.next()

    cap = 1 << max(4, (G + 3).bit_length())
    engines, factories, managers, transports = [], [], [], []
    for i, ep in enumerate(eps):
        server = RpcServer(ep.endpoint)
        manager = NodeManager(server)
        net.bind(server)
        engine = MultiRaftEngine(TickOptions(
            max_groups=cap, max_peers=4, tick_interval_ms=20))
        await engine.start()
        engines.append(engine)
        factories.append(engine.ballot_box_factory())
        managers.append(manager)
        transports.append(InProcTransport(net, ep.endpoint))

    t_boot = time.monotonic()
    nodes: list[list[Node]] = [[] for _ in range(R)]

    async def boot_group(k: int) -> None:
        gid = f"g{k}"
        peers = [PeerId(ep.ip, ep.port, 0, 100 if k % R == i else 10)
                 for i, ep in enumerate(eps)]
        for i in range(R):
            opts = NodeOptions(
                election_timeout_ms=args.election_timeout_ms,
                initial_conf=Configuration(list(peers)),
                fsm=CountFSM(),
                log_uri=f"multilog://{args.dir}/store{i}/mlog#{gid}",
                raft_meta_uri=(
                    f"multimeta://{args.dir}/store{i}/meta#{gid}"
                    if args.meta == "multimeta" else "memory://"),
                enable_metrics=False)
            opts.raft_options.quiesce_after_rounds = args.quiesce
            node = Node(gid, peers[i], opts, transports[i],
                        ballot_box_factory=factories[i])
            node.node_manager = managers[i]
            managers[i].add(node)
            if not await node.init():
                raise RuntimeError(f"init failed {gid}@{i}")
            # defer this group's first election far past boot: at high G
            # the already-booted groups' elections + heartbeats otherwise
            # interfere superlinearly with the remaining inits (measured:
            # 16K-rung boot crawling at >45ms/node)
            eng = engines[i]
            eng.elect_deadline[node._ctrl.slot] = eng.now_ms() + 3_600_000
            nodes[i].append(node)

    # batched-concurrent boot (VERDICT r3 #7: 16Kx1 boot was 183s, 16Kx3
    # 1356s, serialized one node.init at a time): inits inside a batch
    # overlap their await points; batches stay bounded so the loop and
    # engine registration never see an unbounded task herd
    BOOT_BATCH = 256
    for k0 in range(0, G, BOOT_BATCH):
        await asyncio.gather(*(boot_group(k)
                               for k in range(k0, min(G, k0 + BOOT_BATCH))))
    # release elections en masse, jittered over ~4 timeouts: the
    # election_due mask fires them from the device tick (the mass
    # re-election path proven at 4K in test_engine_protocol)
    import numpy as np
    rng = np.random.default_rng(0)
    for i in range(R):
        eng = engines[i]
        now = eng.now_ms()
        spread_ms = (int(float(args.elect_spread_s) * 1000)
                     or 4 * args.election_timeout_ms)
        jit = rng.integers(0, spread_ms, eng.G)
        eng.elect_deadline[:] = now + args.election_timeout_ms // 4 + jit
        eng.mark_dirty()
    boot_s = time.monotonic() - t_boot

    # leadership: priority placement, converge to >= 98%
    deadline = time.monotonic() + 120 + G * 0.05
    led: list[Node] = []
    last_print = 0.0
    while time.monotonic() < deadline:
        led = [n for row in nodes for n in row if n.is_leader()]
        if len(led) >= int(G * 0.98):
            break
        if time.monotonic() - last_print > 15:
            last_print = time.monotonic()
            print(f"PROGRESS leaders={len(led)}/{G} "
                  f"t={time.monotonic() - t_boot - boot_s:.0f}s",
                  flush=True)
        await asyncio.sleep(0.5)
    elect_s = time.monotonic() - t_boot - boot_s

    if args.idle_window > 0:
        # -- idle beat-plane probe (ISSUE 4 acceptance): no write drive.
        # Seed one committed write per group so every group is provably
        # at a fully-matched tail, let quiescence (if enabled) take
        # hold, then measure the beat plane's RPC rate over a quiet
        # window from the hub + engine counters.
        async def seed(node: Node) -> None:
            fut = asyncio.get_running_loop().create_future()

            def done_cb(st, fut=fut):
                if not fut.done():
                    fut.set_result(st)

            await node.apply(Task(data=b"s", done=done_cb))
            await asyncio.wait_for(fut, 60)

        for k0 in range(0, len(led), 256):
            await asyncio.gather(*(seed(n) for n in led[k0:k0 + 256]))
        # settle: quiesce_after_rounds fully-acked beat rounds + the
        # handshake round, at the (possibly floor-raised) beat interval
        hb_s = max(float(e.hb_ms[e.has_ctrl].max()) for e in engines
                   if e.has_ctrl.any()) / 1000.0
        settle = min(120.0, (args.quiesce + 3) * hb_s + 2.0)
        print(f"PROGRESS idle-probe settling {settle:.0f}s "
              f"(hb={hb_s * 1000:.0f}ms)", flush=True)
        await asyncio.sleep(settle)
        hubs = [m.heartbeat_hub for m in managers]

        def beat_counters():
            return {
                "rpcs": sum(h.rpcs_sent for h in hubs),
                "beats": sum(h.beats_sent + h.fast_beats_sent
                             for h in hubs),
                "lease_rpcs": sum(h.lease_rpcs_sent for h in hubs),
            }

        c0 = beat_counters()
        await asyncio.sleep(args.idle_window)
        c1 = beat_counters()
        w = args.idle_window
        from tpuraft.ops.tick import ROLE_LEADER as _RL
        res = {
            "groups": G,
            "replicas": R,
            "leaders": len(led),
            "quiesce_after_rounds": args.quiesce,
            "idle_window_s": w,
            "beat_rpcs_per_s": round((c1["rpcs"] - c0["rpcs"]) / w, 2),
            "beats_per_s": round((c1["beats"] - c0["beats"]) / w, 2),
            "lease_rpcs_per_s": round(
                (c1["lease_rpcs"] - c0["lease_rpcs"]) / w, 2),
            "idle_rpcs_per_s": round(
                (c1["rpcs"] - c0["rpcs"]
                 + c1["lease_rpcs"] - c0["lease_rpcs"]) / w, 2),
            "quiescent_groups": sum(int(e.quiescent.sum())
                                    for e in engines),
            "quiescent_leaders": sum(
                int((e.quiescent & (e.role == _RL)).sum())
                for e in engines),
            "groups_quiesced": sum(h.groups_quiesced for h in hubs),
            "groups_woken": sum(h.groups_woken for h in hubs),
            "lease_expiries": sum(h.lease_expiries for h in hubs),
            # tick-plane gauges (fleet observability): the [G]-lane
            # reductions metrics_text serves — the per-engine
            # hibernation fractions here must agree with the raw
            # quiescent_groups count above (same arrays, one reduce)
            "lane_stats": [e.lane_stats() for e in engines],
            "tick_p99_ms": round(max(
                e.tick_hists["tick_total_ms"].percentile(99)
                for e in engines), 3),
            "eto_floor_ms": max(e._floor_applied_ms for e in engines),
            "eff_eto_ms": int(max(int(e.eto_ms[e.has_ctrl].max())
                                  for e in engines if e.has_ctrl.any())),
            "rss_mb": round(
                resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
                / 1024, 1),
        }
        print("RESULT " + json.dumps(res), flush=True)
        os._exit(0)

    ok = [0]
    errs = [0]
    errs_by: dict[str, int] = {}  # error-class attribution (VERDICT r4 #7)
    lats: list[tuple[float, float]] = []  # (completion time, latency)
    t_drive0 = time.monotonic()
    stop_at = time.monotonic() + args.duration
    payload = b"x" * 16

    # replica rows per group, so the driver can follow leadership the
    # way a RouteTable client does: without this, a mid-window election
    # turns every later apply to the stale leader into EPERM noise
    # (r5 attribution: ALL residual 4Kx3 errors were EPERM/ENEWLEADER
    # from driving the boot-time leader list)
    by_group: dict[str, list[Node]] = {}
    for row in nodes:
        for n in row:
            by_group.setdefault(n.group_id, []).append(n)

    async def drive(node: Node) -> None:
        await asyncio.sleep(random.random() * args.pace_ms / 1e3)
        i = 0
        while time.monotonic() < stop_at:
            i += 1
            if not node.is_leader():
                cur = next((n for n in by_group[node.group_id]
                            if n.is_leader()), None)
                if cur is None:
                    await asyncio.sleep(args.pace_ms / 1e3)  # electing
                    continue
                node = cur
            fut = asyncio.get_running_loop().create_future()
            left = [args.batch]
            t0 = time.perf_counter()

            def cb(st, left=left, t0=t0, sample=True):
                if st.is_ok():
                    ok[0] += 1
                else:
                    errs[0] += 1
                    name = st.raft_error.name
                    errs_by[name] = errs_by.get(name, 0) + 1
                left[0] -= 1
                if left[0] == 0:
                    if sample:
                        lats.append((time.monotonic() - t_drive0,
                                     time.perf_counter() - t0))
                    if not fut.done():
                        fut.set_result(None)

            await node.apply_batch(
                [Task(data=payload, done=cb) for _ in range(args.batch)])
            try:
                await asyncio.wait_for(fut, 30)
            except asyncio.TimeoutError:
                pass
            await asyncio.sleep(args.pace_ms / 1e3)

    t0 = time.monotonic()
    await asyncio.gather(*(drive(n) for n in led))
    elapsed = time.monotonic() - t0
    # steady-state view: samples completing in the second half of the
    # window, after the boot-adjacent stragglers (late elections, cold
    # engine) have flushed — attributes how much of the overall p99 is
    # transient vs steady behavior
    half = elapsed / 2
    late = sorted(lt for (ts, lt) in lats if ts >= half)
    lats_v = sorted(lt for (_ts, lt) in lats)

    def pct(s, p):
        return round(s[min(len(s) - 1, int(p * len(s)))] * 1e3, 2) \
            if s else None

    res = {
        "groups": G,
        "replicas": R,
        "leaders": len(led),
        "boot_s": round(boot_s, 1),
        "elect_s": round(elect_s, 1),
        "commits_per_sec": round(ok[0] / elapsed, 1),
        "ok": ok[0],
        "errors": errs[0],
        "errors_by_class": dict(sorted(errs_by.items())),
        "ack_p50_ms": pct(lats_v, 0.50),
        "ack_p99_ms": pct(lats_v, 0.99),
        "ack_p50_ms_steady": pct(late, 0.50),
        "ack_p99_ms_steady": pct(late, 0.99),
        "rss_mb": round(
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024, 1),
        "asyncio_tasks": len(asyncio.all_tasks()),
        "applied_total": CountFSM.applied,
        "pace_ms": args.pace_ms,
        "batch": args.batch,
        "meta": args.meta,
        "engine_ticks": sum(e.ticks for e in engines),
        # density-aware floors (ISSUE 4): the effective operating point
        # the engine derived — no hand-tuned timeout in the command line
        "eto_floor_ms": max(e._floor_applied_ms for e in engines),
        "eff_eto_ms": int(max(int(e.eto_ms[e.has_ctrl].max())
                              for e in engines if e.has_ctrl.any())),
        "requested_eto_ms": args.election_timeout_ms,
    }
    print("RESULT " + json.dumps(res), flush=True)
    # skip graceful teardown of 3G nodes: the subprocess exits and the
    # measurement is done — teardown at 48K nodes costs minutes
    os._exit(0)


def _run_idle_probe(args) -> None:
    """A/B the idle beat plane at one (G, R): quiescence off vs on.
    Acceptance: idle beat-plane RPC rate drops >= 10x with quiescence
    (the hub's rpcs+lease counters are the measurement)."""
    import tempfile

    from tpuraft.storage.multilog import ensure_built

    ensure_built()
    g = int(args.rungs.split(",")[0])
    window = args.duration if args.duration > 0 else 30.0
    pair = {}
    for label, quiesce in (("quiesce_off", 0),
                           ("quiesce_on", args.quiesce or 8)):
        workdir = tempfile.mkdtemp(prefix=f"tpuraft_idle_{g}_")
        cmd = [sys.executable, os.path.join(REPO, "bench_scale.py"),
               "--rung", "--groups", str(g), "--dir", workdir,
               "--replicas", str(args.replicas),
               "--elect-spread-s", str(args.elect_spread_s),
               "--duration", "0", "--idle-window", str(window),
               "--quiesce", str(quiesce), "--meta", args.meta,
               "--election-timeout-ms", str(args.election_timeout_ms)]
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
        p = subprocess.Popen(cmd, stdout=subprocess.PIPE, env=env)
        row = None
        for line in p.stdout:
            line = line.decode().strip()
            if line.startswith("RESULT "):
                row = json.loads(line[len("RESULT "):])
            elif line.startswith("PROGRESS"):
                print(line, flush=True)
        p.wait()
        pair[label] = row or {"error": "rung produced no result"}
        print(label, json.dumps(pair[label]), flush=True)
        subprocess.run(["rm", "-rf", workdir])
    off = pair.get("quiesce_off") or {}
    on = pair.get("quiesce_on") or {}
    if "idle_rpcs_per_s" in off and "idle_rpcs_per_s" in on:
        denom = max(on["idle_rpcs_per_s"], 0.01)
        pair["rpc_reduction_x"] = round(off["idle_rpcs_per_s"] / denom, 1)
    path = os.path.join(REPO, args.json_out)
    out = {}
    if os.path.exists(path):
        with open(path) as f:
            out = json.load(f)
    out["idle_beat_plane"] = pair
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"idle_probe": "done",
                      "rpc_reduction_x": pair.get("rpc_reduction_x")}))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rungs", default="1024,4096,16384")
    ap.add_argument("--offered", default="3000",
                    help="offered entries/s; one value or comma list "
                         "matched to --rungs (capacity at high G is "
                         "1-core bound — over-offering measures queue "
                         "collapse, not protocol capacity)")
    # parent-side replicas passthrough (single-voter rungs measure the
    # engine+journal+FSM plane at G beyond the 3-replica election
    # capacity of one core)
    ap.add_argument("--duration", type=float, default=20.0)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--election-timeout-ms", type=int, default=10000)
    ap.add_argument("--json-out", default="BENCH_SCALE.json")
    ap.add_argument("--rung", action="store_true",
                    help="internal: run one rung in this process")
    ap.add_argument("--groups", type=int, default=0)
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--pace-ms", type=float, default=0.0)
    ap.add_argument("--elect-spread-s", default="0",
                    help="window over which the boot-deferred elections "
                         "release (0 = 4x election timeout); one value "
                         "or comma list matched to --rungs; widen at "
                         "high GxR so the election herd stays under the "
                         "host's per-second election capacity")
    ap.add_argument("--meta", default="memory",
                    choices=["memory", "multimeta"],
                    help="raft meta storage: memory:// (volatile, the "
                         "r1-r4 ladder default) or multimeta:// (fsynced "
                         "{term, votedFor} via the shared group-commit "
                         "journal — the durable-meta election-herd "
                         "measurement, VERDICT r4 #3)")
    ap.add_argument("--dir", default="")
    ap.add_argument("--quiesce", type=int, default=0,
                    help="RaftOptions.quiesce_after_rounds: >0 lets "
                         "idle groups hibernate (store-level lease "
                         "liveness; ISSUE 4)")
    ap.add_argument("--idle-window", type=float, default=0.0,
                    help="rung-internal: measure the IDLE beat plane "
                         "over this window instead of driving writes")
    ap.add_argument("--idle-probe", action="store_true",
                    help="run the quiescence A/B idle probe at "
                         "--rungs[0] x --replicas (quiesce off vs on), "
                         "merge the pair into BENCH_SCALE.json as "
                         "'idle_beat_plane', and leave the drive rows "
                         "untouched")
    args = ap.parse_args()

    if args.rung:
        asyncio.run(run_rung(args))
        return

    if args.idle_probe:
        _run_idle_probe(args)
        return

    import tempfile

    from tpuraft.storage.multilog import ensure_built

    ensure_built()
    rows = []
    rung_list = [int(x) for x in args.rungs.split(",")]
    offered_list = [float(x) for x in args.offered.split(",")]
    if len(offered_list) == 1:
        offered_list *= len(rung_list)
    spread_list = [float(x) for x in str(args.elect_spread_s).split(",")]
    if len(spread_list) == 1:
        spread_list *= len(rung_list)
    if len(offered_list) != len(rung_list) or \
            len(spread_list) != len(rung_list):
        raise SystemExit("--offered/--elect-spread-s list lengths must "
                         "match --rungs (or be a single value)")
    for g, offered, spread in zip(rung_list, offered_list, spread_list):
        # offered load below the measured 1-core protocol capacity, so
        # ack latency reflects service time, not queue growth:
        # pace = G*batch/offered; the window stretches so every group
        # gets >= ~2 turns even when pace > duration
        pace_ms = max(200.0, g * args.batch / offered * 1000.0)
        rung_duration = max(args.duration, pace_ms * 2.0 / 1000.0)
        workdir = tempfile.mkdtemp(prefix=f"tpuraft_scale_{g}_")
        cmd = [sys.executable, os.path.join(REPO, "bench_scale.py"),
               "--rung", "--groups", str(g), "--dir", workdir,
               "--replicas", str(args.replicas),
               "--elect-spread-s", str(spread),
               "--duration", str(rung_duration), "--batch", str(args.batch),
               "--pace-ms", str(pace_ms), "--meta", args.meta,
               "--election-timeout-ms", str(args.election_timeout_ms)]
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
        t0 = time.monotonic()
        p = subprocess.Popen(cmd, stdout=subprocess.PIPE, env=env)
        row = None
        for line in p.stdout:
            line = line.decode().strip()
            if line.startswith("RESULT "):
                row = json.loads(line[len("RESULT "):])
            elif line.startswith("PROGRESS"):
                print(line, flush=True)
        p.wait()
        if row is None:
            row = {"groups": g, "error": "rung produced no result"}
        row["wall_s"] = round(time.monotonic() - t0, 1)
        if "error" not in row:
            row["offered_per_sec"] = round(
                g * args.batch / (pace_ms / 1000.0), 1)
        rows.append(row)
        print(json.dumps(row), flush=True)
        subprocess.run(["rm", "-rf", workdir])

    complete = [r for r in rows if "error" not in r]
    prev = {}
    prev_path = os.path.join(REPO, args.json_out)
    if os.path.exists(prev_path):
        with open(prev_path) as f:
            prev = json.load(f)
    out = {
        "metric": "protocol_plane_scale_ladder",
        "rows": rows,
        "per_g_overhead": {
            str(r["groups"]): {
                "rss_kb_per_group": round(r["rss_mb"] * 1024 / r["groups"], 1),
                "tasks_per_group": round(
                    r["asyncio_tasks"] / r["groups"], 2),
            } for r in complete
        },
        "stack": "in-proc RPC x3 replica endpoints, multilog shared-journal "
                 "fsync per store, engine protocol plane, priority "
                 "placement, paced offered load (~8K entries/s)",
        "note": "one PROCESS hosts all three replicas of every group; the "
                "3-process loopback-TCP variant is BENCH_E2E.json",
    }
    if "idle_beat_plane" in prev:   # the quiescence A/B rides along
        out["idle_beat_plane"] = prev["idle_beat_plane"]
    with open(os.path.join(REPO, args.json_out), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"rungs": len(rows), "ok": len(complete)}))


if __name__ == "__main__":
    main()
