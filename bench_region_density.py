"""RheaKV at region density (VERDICT r3 #5): >= 1K regions on a
3-store cluster through the FULL KV stack — region engines + KV state
machines + native C++ data engine + multilog shared journal + engine
protocol plane + the batching RheaKV client — under mixed load, with PD
heartbeat volume counted.

rhea:StoreEngine's whole point is thousands of regions per process
(SURVEY.md §3.2); until r4 the densest recorded KV run was 64 regions
(BENCH_E2E.json).  Writes BENCH_REGIONS.json; bench.py embeds it as
extra.regions.

Topology: ONE process hosts all three stores over in-proc RPC (the
loopback-TCP e2e variant at its own G lives in bench_e2e.py), each
store with its own MultiRaftEngine, its own native:// KV engine and
its own multilog journal.  Regions split a 4-hex-digit keyspace evenly.
"""

import argparse
import asyncio
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))


async def run_config(args) -> dict:
    import jax

    jax.config.update("jax_platforms", "cpu")

    import random
    import resource

    import numpy as np

    from tpuraft.core.engine import MultiRaftEngine
    from tpuraft.options import TickOptions
    from tpuraft.rheakv.client import BatchingOptions, RheaKVStore
    from tpuraft.rheakv.metadata import Region
    from tpuraft.rheakv.native_store import NativeRawKVStore
    from tpuraft.rheakv.pd_client import FakePlacementDriverClient
    from tpuraft.rheakv.store_engine import StoreEngine, StoreEngineOptions
    from tpuraft.rpc.transport import (InProcNetwork, InProcTransport,
                                       RpcServer)

    R, S = args.regions, args.stores
    net = InProcNetwork()
    endpoints = [f"127.0.0.1:{6600 + i}" for i in range(S)]

    # R regions split a 4-hex keyspace: region k owns [hex(k), hex(k+1))
    def bkey(k: int) -> bytes:
        return b"%06x" % k

    regions = [Region(id=k + 1, start_key=bkey(k) if k else b"",
                      end_key=bkey(k + 1) if k + 1 < R else b"",
                      peers=list(endpoints))
               for k in range(R)]

    class CountingPD(FakePlacementDriverClient):
        store_hbs = 0      # legacy per-store RPCs (pre-delta-batch path)
        region_hbs = 0     # legacy per-region RPCs (the r5 1476/s metric)
        batch_hbs = 0      # pd_store_heartbeat_batch RPCs
        delta_rows = 0     # changed-region rows carried inside batches
        heat_rows = 0      # noise-gated heat rows carried inside batches

        async def store_heartbeat(self, meta) -> None:
            CountingPD.store_hbs += 1
            await super().store_heartbeat(meta)

        async def region_heartbeat(self, region, leader, *a, **kw):
            CountingPD.region_hbs += 1
            return await super().region_heartbeat(region, leader, *a, **kw)

        async def store_heartbeat_batch(self, meta, deltas, full=False,
                                        health="", heat=None,
                                        occupancy=None):
            # count what a real PD would SEE: one RPC + its delta rows
            # (not the base class's legacy decomposition, which would
            # double-count every row as a per-region RPC)
            CountingPD.batch_hbs += 1
            CountingPD.delta_rows += len(deltas)
            CountingPD.heat_rows += len(heat or [])
            return [], False

    # --lifecycle-pd: swap the counting fake for a REAL single-member
    # placement driver running the region-lifecycle policy loop with
    # every actuator held idle (thresholds/floors no run can cross), so
    # the A/B row isolates the pure policy-evaluation cost riding the
    # heartbeat stream — heat scoring, merge/move candidate scans —
    # from any actual split/merge/move churn.
    pd_server = None
    pd_ep = "127.0.0.1:7600"
    if args.lifecycle_pd:
        from tpuraft.rheakv.pd_server import (PlacementDriverOptions,
                                              PlacementDriverServer)

        os.makedirs(f"{args.dir}/pd", exist_ok=True)
        pd_rpc = RpcServer(pd_ep)
        net.bind(pd_rpc)
        pd_server = PlacementDriverServer(
            PlacementDriverOptions(
                endpoints=[pd_ep],
                election_timeout_ms=args.election_timeout_ms,
                data_path=f"{args.dir}/pd",
                initial_regions=[r.copy() for r in regions],
                lifecycle=True,
                # actuation-idle knobs: the policy evaluates every
                # heartbeat round but no decision can ever fire
                lifecycle_heat_split_min_keys=1 << 30,
                lifecycle_min_regions=R + 1,
                lifecycle_move_imbalance=1 << 30,
            ),
            pd_ep, pd_rpc, InProcTransport(net, pd_ep))
        await pd_server.start()
        deadline = time.monotonic() + 30
        while not (pd_server.node and pd_server.node.is_leader()):
            if time.monotonic() > deadline:
                raise RuntimeError("lifecycle PD failed to elect")
            await asyncio.sleep(0.05)

    t0 = time.monotonic()
    engines, stores = [], []
    cap = 1 << max(4, (R + 3).bit_length())
    for i, ep in enumerate(endpoints):
        # the native kv engine's open mkdirs one level only
        os.makedirs(f"{args.dir}/store{i}", exist_ok=True)
        server = RpcServer(ep)
        net.bind(server)
        transport = InProcTransport(net, ep)
        engine = MultiRaftEngine(TickOptions(
            max_groups=cap, max_peers=4, tick_interval_ms=20,
            # --no-write-batch A/B: tick-cadence commits (pre-ISSUE-15)
            eager_commit=not args.no_write_batch))
        engines.append(engine)
        opts = StoreEngineOptions(
            server_id=ep,
            initial_regions=[r.copy() for r in regions],
            data_path=f"{args.dir}/store{i}",
            election_timeout_ms=args.election_timeout_ms,
            log_scheme="multilog",
            raw_store_factory=lambda i=i: NativeRawKVStore(
                f"{args.dir}/store{i}/kv", sync=False),
            heartbeat_interval_ms=1000,
            # --no-heat: the bench-gate heat-overhead row's A/B knob
            heat_tracking=not args.no_heat,
            # --no-disk-guard: the bench-gate disk-guard-overhead
            # row's A/B knob (DiskBudget accounting + health-round
            # pressure evaluation off)
            disk_guard=not args.no_disk_guard,
            # --no-write-batch: the write-plane A/B knob — send-plane
            # stop-and-wait appends + ack-after-apply (pre-ISSUE-15)
            append_batching=not args.no_write_batch,
            ack_at_commit=not args.no_write_batch,
        )
        if args.chaos_clock:
            # --chaos-clock: the bench-gate clock-overhead row's A/B
            # knob — every timing read pays the injected-clock
            # indirection (ChaosClock at rate 1.0 == real time), so
            # the row isolates the virtual-clock cost from any fault
            from tpuraft.util.clock import ChaosClock

            opts.clock = ChaosClock(seed=i)
        if args.lease_reads:
            from tpuraft.options import ReadOnlyOption

            opts.read_only_option = ReadOnlyOption.LEASE_BASED
        if args.quiesce:
            opts.quiesce_after_rounds = 4
        if args.lifecycle_pd:
            from tpuraft.rheakv.pd_client import RemotePlacementDriverClient

            pd_client = RemotePlacementDriverClient(transport, [pd_ep])
        else:
            pd_client = CountingPD([r.copy() for r in regions])
        store = StoreEngine(opts, server, transport,
                            multi_raft_engine=engine,
                            pd_client=pd_client)
        # defer elections past boot (the bench_scale pattern): engine
        # deadlines move en masse after every store is up
        orig_start_region = store._start_region

        async def deferred(region, store=store, engine=engine,
                           orig=orig_start_region):
            eng_region = await orig(region)
            node = eng_region.node
            engine.elect_deadline[node._ctrl.slot] = \
                engine.now_ms() + 3_600_000
            return eng_region

        store._start_region = deferred
        await store.start()
        stores.append(store)
    # release elections jittered over ~4 timeouts
    rng = np.random.default_rng(0)
    for engine in engines:
        now = engine.now_ms()
        jit = rng.integers(0, 4 * args.election_timeout_ms, engine.G)
        engine.elect_deadline[:] = now + args.election_timeout_ms // 4 + jit
        engine.mark_dirty()
    boot_s = time.monotonic() - t0

    # leadership convergence
    t1 = time.monotonic()
    deadline = time.monotonic() + 120 + R * 0.05
    led = 0
    while time.monotonic() < deadline:
        led = sum(1 for s in stores for re in s._regions.values()
                  if re.is_leader())
        if led >= int(R * 0.98):
            break
        await asyncio.sleep(0.5)
    elect_s = time.monotonic() - t1

    pd = FakePlacementDriverClient([r.copy() for r in regions])
    # batching ON: concurrent worker ops drain into store-grouped
    # kv_command_batch RPCs (pre-batch builds passed a default-disabled
    # BatchingOptions() here, i.e. one kv_command per op)
    client = RheaKVStore(pd, InProcTransport(net, "kvclient:0"),
                         batching=BatchingOptions(
                             enabled=True,
                             max_store_inflight=args.store_inflight),
                         read_from=args.read_from)
    hb0 = (CountingPD.store_hbs, CountingPD.region_hbs,
           CountingPD.batch_hbs, CountingPD.delta_rows,
           CountingPD.heat_rows)

    ok = [0]
    errs = [0]
    lats: list[float] = []
    payload = b"v" * 32

    # read-mix shapes (--read-frac >= 0): reads with that probability,
    # writes otherwise; negative = the legacy 75/25 put/get mix.  A
    # pure-read probe against a quiescent fleet (--read-frac 1
    # --lease-reads --quiesce) additionally asserts hibernation holds.
    read_frac = args.read_frac if args.read_frac >= 0 else 0.25
    quiesced_before = woken_before = 0
    if args.quiesce:
        # seed every region once so groups have one committed entry,
        # then wait for hibernation to take hold before the window
        for k in range(0, R, max(1, R // 64)):
            try:
                await client.put(b"%06x/seed" % k, payload)
            except Exception:
                pass
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            quiesced_before = sum(int(e.quiescent.sum()) for e in engines)
            if quiesced_before >= int(R * S * 0.9):
                break
            await asyncio.sleep(0.5)
        woken_before = sum(
            s.node_manager.heartbeat_hub.groups_woken for s in stores)

    if args.trace_sample > 0:
        # sampled product tracing through the measured window (the
        # bench-gate overhead row drives this; seeded => same sampled
        # op sequence run to run)
        from tpuraft.util.trace import TRACER

        TRACER.configure(enabled=True, sample_rate=args.trace_sample,
                         seed=0)

    if args.profile_ticks > 0:
        # device-tick profiling window on the first store's engine:
        # each of the next N ticks records build/device/apply phase
        # spans, exported below as a perfetto tick timeline
        engines[0].profile_ticks(args.profile_ticks)

    stop_at = time.monotonic() + args.duration

    async def worker(wid: int) -> None:
        r = random.Random(wid)
        while time.monotonic() < stop_at:
            k = b"%06x" % r.randrange(R)
            key = k + b"/%04d" % r.randrange(100)
            t = time.perf_counter()
            try:
                if r.random() < read_frac:
                    await client.get(key)
                else:
                    await client.put(key, payload)
                ok[0] += 1
                lats.append(time.perf_counter() - t)
            except Exception:
                errs[0] += 1
            await asyncio.sleep(args.pace_ms / 1e3)

    t2 = time.monotonic()
    await asyncio.gather(*(worker(i) for i in range(args.workers)))
    elapsed = time.monotonic() - t2
    hb1 = (CountingPD.store_hbs, CountingPD.region_hbs,
           CountingPD.batch_hbs, CountingPD.delta_rows,
           CountingPD.heat_rows)
    # snapshot hibernation state BEFORE the stage probes: the write
    # probe below legitimately wakes its target group
    quiesced_after = sum(int(e.quiescent.sum()) for e in engines) \
        if args.quiesce else 0
    woken_after = sum(s.node_manager.heartbeat_hub.groups_woken
                      for s in stores) if args.quiesce else 0
    lats.sort()

    stage = await stage_probe(client, stores, R)
    read_stage = await read_stage_probe(client, stores) \
        if read_frac > 0 else {}

    # read-plane counters: store-wide confirm batching, per-batch fence
    # dedupe, lease vs SAFE vs forwarded serve counts, engine lease lane
    read_plane: dict = {}

    def _acc(d: dict) -> None:
        for k, v in d.items():
            read_plane[k] = read_plane.get(k, 0) + v

    for s in stores:
        if s.read_batcher is not None:
            _acc(s.read_batcher.counters())
        _acc({"kv_read_fences": s.kv_processor.read_fences,
              "kv_fenced_reads": s.kv_processor.fenced_reads})
        for re in s._regions.values():
            if re.node is not None:
                _acc(re.node.read_only_service.counters())
    _acc({"lease_lane_hits": sum(e.lease_lane_hits for e in engines),
          "lease_lane_misses": sum(e.lease_lane_misses for e in engines)})

    ls = [e.lane_stats() for e in engines]
    rss_mb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024
    coalesced_flushes = sum(re.fsm.coalesced_flushes
                            for s in stores for re in s._regions.values())
    coalesced_ops = sum(re.fsm.coalesced_ops
                        for s in stores for re in s._regions.values())
    res = {
        "regions": R,
        "stores": S,
        # client + every store multiplexed onto ONE loop in ONE process
        # — compare against row_mp_* (bench_multiproc) for the same
        # stack across real OS processes
        "topology": "single-process",
        "leaders": led,
        "boot_s": round(boot_s, 1),
        "elect_s": round(elect_s, 1),
        "ops_per_sec": round(ok[0] / elapsed, 1),
        "ok": ok[0],
        "errors": errs[0],
        "ack_p50_ms": round(lats[len(lats) // 2] * 1e3, 2) if lats else None,
        "ack_p99_ms": round(lats[int(len(lats) * 0.99)] * 1e3, 2)
        if lats else None,
        "rss_mb": round(rss_mb, 1),
        "rss_kb_per_region": round(rss_mb * 1024 / (R * S), 1),
        "pd_store_hb_per_s": round((hb1[0] - hb0[0]) / elapsed, 2),
        "pd_region_hb_per_s": round((hb1[1] - hb0[1]) / elapsed, 2),
        # delta-batched PD reporting (ISSUE 4): total PD-visible RPC
        # rate is batches (+ any legacy calls); rows ride inside
        "pd_batch_hb_per_s": round((hb1[2] - hb0[2]) / elapsed, 2),
        "pd_delta_rows_per_s": round((hb1[3] - hb0[3]) / elapsed, 2),
        "pd_rpcs_per_s": round(
            (hb1[0] - hb0[0] + hb1[1] - hb0[1] + hb1[2] - hb0[2])
            / elapsed, 2),
        "asyncio_tasks": len(asyncio.all_tasks()),
        "workers": args.workers,
        "pace_ms": args.pace_ms,
        "read_frac": round(read_frac, 2),
        "read_from": args.read_from,
        "lease_reads": bool(args.lease_reads),
        # serving-plane batching (ISSUE 6): store-grouped client RPCs +
        # server fan-out + FSM apply coalescing
        "kv_batch_rpcs_per_s": round(client.batch_rpcs / elapsed, 1),
        "kv_batch_items_per_rpc": round(
            client.batch_items / max(1, client.batch_rpcs), 2),
        "kv_batch_fallbacks": client.batch_fallbacks,
        "kv_batch_retry_codes": {str(k): v
                                 for k, v in client.batch_retries.items()},
        "srv_batch_rpcs": sum(s.kv_processor.batch_rpcs for s in stores),
        "srv_single_rpcs": sum(s.kv_processor.single_rpcs for s in stores),
        "fsm_coalesced_flushes": coalesced_flushes,
        "fsm_coalesced_ops": coalesced_ops,
        # per-stage latency marks for one post-run probe put (relative
        # ms, BENCH_E2E ack_breakdown style): queue=batcher wait,
        # rpc_s→rpc_e=wire round trip, propose_s=server handler reached
        # the region store, submit=entry handed to the raft node,
        # apply_s/apply_e=FSM executed, ack=proposal future resolved
        "stage_marks_ms": stage,
        # write-plane batching (ISSUE 15): store-wide append rounds +
        # event-driven commits + ack-at-commit pipelined apply
        "write_plane": {
            "enabled": not args.no_write_batch,
            **{k: sum(s.append_batcher.counters()[k] for s in stores
                      if s.append_batcher is not None)
               for k in (stores[0].append_batcher.counters()
                         if stores[0].append_batcher is not None else {})},
            "engine_eager_commits": sum(e.eager_commits for e in engines),
            "fsm_eager_acked": sum(
                re.node.fsm_caller.eager_acked
                for s in stores for re in s._regions.values()
                if re.node is not None),
        },
        # read-side attribution for one probe GET: queue → rpc →
        # fence_s/fence_e (read_index confirmation incl. the store-wide
        # batched round) → done (local serve + reply)
        "read_stage_marks_ms": read_stage,
        "read_plane": read_plane,
        # tick-plane occupancy (fleet observability): [G]-lane
        # vectorized reductions summed across the S engines, plus the
        # first engine's per-tick phase attribution
        "tick_plane": {
            "groups": sum(ls[i]["groups"] for i in range(S)),
            "leaders": sum(ls[i]["leaders"] for i in range(S)),
            "quiescent": sum(ls[i]["quiescent"] for i in range(S)),
            "tick_hists": engines[0].tick_histograms(),
        },
        # per-region heat telemetry: intake volume + noise-gated rows
        # that actually rode the heartbeats
        "heat": {
            "enabled": not args.no_heat,
            "rows_per_s": round((hb1[4] - hb0[4]) / elapsed, 2),
            "writes_noted": sum(
                s.heat.writes_noted for s in stores if s.heat),
            "reads_noted": sum(
                s.heat.reads_noted for s in stores if s.heat),
        },
    }
    if args.lifecycle_pd and pd_server is not None:
        # the row's evidence: a real PD saw the whole fleet and ran the
        # policy every round, yet ordered zero actuations (pure
        # evaluation cost is the only delta vs the base kv row)
        res["lifecycle_pd"] = {
            "regions_known": len(pd_server.fsm.regions),
            "heat_splits_ordered": pd_server.heat_splits_ordered,
            "merges_ordered": pd_server.merges_ordered,
            "merges_completed": pd_server.merges_completed,
            "moves_ordered": pd_server.moves_ordered,
        }
    if args.quiesce:
        res["quiescent_replicas_before"] = quiesced_before
        res["quiescent_replicas_after"] = quiesced_after
        res["groups_woken_during_load"] = woken_after - woken_before
    if args.trace_sample > 0 or args.trace:
        from tpuraft.util.trace import TRACER

        res["trace"] = TRACER.stats()
        if args.trace:
            # perfetto-loadable export: the probe put/get traces (and
            # any window-sampled ops still in the ring)
            res["trace_file"] = args.trace
            res["trace_spans"] = TRACER.export_chrome(args.trace)
    if args.profile_ticks > 0:
        # tick timeline: the N-tick window as a perfetto-loadable
        # export (root tick span + build/device/apply phase spans)
        out = args.profile_ticks_out or os.path.join(
            args.dir, "tick_timeline.json")
        res["tick_timeline_file"] = out
        res["tick_timeline_spans"] = engines[0].export_tick_timeline(out)
    print("RESULT " + json.dumps(res), flush=True)
    os._exit(0)  # 3R region engines: teardown is not the measurement


# span name -> (start mark, end mark): the product trace plane's stage
# spans rendered into the historical stage_marks_ms shape (relative ms
# from the probe op's start).  One attribution implementation — the
# bench reads what production emits instead of monkeypatching a twin.
_SPAN_MARKS = {
    "client_queue": ("queue_s", "sent"),
    "kv_batch_rpc": ("rpc_s", "rpc_e"),
    "kv_rpc": ("rpc_s", "rpc_e"),
    "srv_validate": ("validate_s", "validate_e"),
    "srv_propose": ("propose_s", "ack"),
    "quorum_commit": ("submit", "quorum_e"),
    "fsm_apply": ("apply_s", "apply_e"),
    "srv_read_fence": ("fence_s", "fence_e"),
    "srv_read_serve": ("serve_s", "serve_e"),
}


def _marks_from_spans(spans: list) -> dict:
    """Fold one trace's spans into the stage-marks dict.  Leader-side
    stages key off the proc that served the propose/fence; the flush
    and follower stages land as flush_s/flush_e (leader store) and
    fol_append_s/fol_append_e (first follower)."""
    roots = [s for s in spans if s["name"] == "kv_op"]
    if not roots:
        return {}
    root = roots[-1]
    tid, t0 = root["trace_id"], root["ts_s"]
    mine = [s for s in spans if s["trace_id"] == tid]

    def rel(x: float) -> float:
        return round((x - t0) * 1e3, 3)

    marks = {"queue_s": 0.0, "done": rel(root["ts_s"] + root["dur_s"])}
    leader_proc = next((s["proc"] for s in mine
                        if s["name"] in ("srv_propose", "srv_read_fence")),
                       None)
    for s in mine:
        name = s["name"]
        if name == "log_flush":
            pfx = "flush" if s["proc"] == leader_proc else "fol_flush"
            marks.setdefault(f"{pfx}_s", rel(s["ts_s"]))
            marks.setdefault(f"{pfx}_e", rel(s["ts_s"] + s["dur_s"]))
        elif name == "follower_append":
            marks.setdefault("fol_append_s", rel(s["ts_s"]))
            marks.setdefault("fol_append_e", rel(s["ts_s"] + s["dur_s"]))
        elif name == "fsm_apply" and s["proc"] != leader_proc:
            continue  # follower applies happen off the ack path
        elif name in _SPAN_MARKS:
            a, b = _SPAN_MARKS[name]
            marks.setdefault(a, rel(s["ts_s"]))
            marks.setdefault(b, rel(s["ts_s"] + s["dur_s"]))
    return marks


async def _traced_probe(client, stores, op: str) -> dict:
    """One fully-sampled probe op after the measured window, attributed
    entirely by the PRODUCT trace plane (tpuraft/util/trace)."""
    from tpuraft.util.trace import TRACER

    target = None
    for s in stores:
        for re in s._regions.values():
            if re.is_leader():
                target = re
                break
        if target is not None:
            break
    if target is None:
        return {}
    # no reset: _marks_from_spans keys off the newest kv_op root, so
    # window-sampled spans (--trace-sample) survive into the export
    was_enabled, was_rate = TRACER.enabled, TRACER.sample_rate
    TRACER.configure(enabled=True, sample_rate=1.0, seed=0)
    key = target.region.start_key + b"/stage-probe"
    try:
        if op == "put":
            await asyncio.wait_for(client.put(key, b"p"), 30.0)
        else:
            await asyncio.wait_for(client.get(key), 30.0)
    except Exception:
        return {}
    finally:
        TRACER.enabled = was_enabled
        TRACER.sample_rate = was_rate
    return _marks_from_spans(TRACER.spans())


async def stage_probe(client, stores, R: int) -> dict:
    """One traced put after the measured window: the product spans
    attribute each serving-plane stage so the NEXT bottleneck is
    addressable — client-queue → rpc → validate → propose →
    flush/quorum → apply → ack (+ follower append/flush)."""
    return await _traced_probe(client, stores, "put")


async def read_stage_probe(client, stores) -> dict:
    """One traced GET after the measured window: client-queue → rpc →
    read fence (ReadIndex confirmation incl. the store-wide batched
    round) → local serve → ack, from the same product spans."""
    return await _traced_probe(client, stores, "get")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--regions", type=int, default=1024)
    ap.add_argument("--stores", type=int, default=3)
    ap.add_argument("--duration", type=float, default=30.0)
    ap.add_argument("--workers", type=int, default=24)
    ap.add_argument("--pace-ms", type=float, default=2.0)
    ap.add_argument("--election-timeout-ms", type=int, default=10000)
    ap.add_argument("--store-inflight", type=int, default=4,
                    help="concurrent kv_command_batch RPCs per store "
                         "(BatchingOptions.max_store_inflight)")
    ap.add_argument("--read-frac", type=float, default=-1.0,
                    help="read/write-mix shape: GET with this probability "
                         "(0.95 = the 95/5 row, 0.5 = 50/50, 1.0 = pure "
                         "read); negative (default) = legacy 75/25 "
                         "put/get mix")
    ap.add_argument("--read-from",
                    choices=["leader", "follower", "learner", "any"],
                    default="leader",
                    help="client read fan-out target (RheaKVStore "
                         "read_from)")
    ap.add_argument("--lease-reads", action="store_true",
                    help="LEASE_BASED readIndex on the region groups "
                         "(no per-read quorum round)")
    ap.add_argument("--quiesce", action="store_true",
                    help="enable group quiescence and assert a pure-read "
                         "load leaves hibernated groups hibernated "
                         "(reports wake counters)")
    ap.add_argument("--trace", default="",
                    help="export a Chrome trace-event JSON "
                         "(perfetto-loadable) of the traced ops to this "
                         "path (the post-run stage-probe put/get at "
                         "minimum; with --trace-sample also the "
                         "window's sampled ops)")
    ap.add_argument("--trace-sample", type=float, default=0.0,
                    help="enable product tracing through the measured "
                         "window at this sample rate (0 = off; the "
                         "bench-gate overhead row uses 0.05)")
    ap.add_argument("--no-heat", action="store_true",
                    help="disable per-region heat tracking (the "
                         "bench-gate heat-overhead row's A/B knob)")
    ap.add_argument("--chaos-clock", action="store_true",
                    help="install a per-store injected ChaosClock at "
                         "rate 1.0 (real time through the virtual-"
                         "clock indirection) — the bench-gate clock-"
                         "overhead row's A/B knob")
    ap.add_argument("--no-disk-guard", action="store_true",
                    help="disable the disk budget / pressure plane "
                         "(the bench-gate disk-guard-overhead row's "
                         "A/B knob)")
    ap.add_argument("--lifecycle-pd", action="store_true",
                    help="run a REAL placement driver (lifecycle "
                         "policy loop on, every actuator held idle) "
                         "instead of the counting fake — the bench-"
                         "gate lifecycle-overhead row's A/B knob")
    ap.add_argument("--no-write-batch", action="store_true",
                    help="disable the write plane (store-wide append "
                         "rounds, eager commits, ack-at-commit) — the "
                         "unbatched A/B comparator")
    ap.add_argument("--profile-ticks", type=int, default=0,
                    help="arm an N-tick device profiling window on the "
                         "first store's engine; exports a perfetto "
                         "tick timeline (build/device/apply phases)")
    ap.add_argument("--profile-ticks-out", default="",
                    help="tick timeline output path (default: "
                         "<workdir>/tick_timeline.json)")
    ap.add_argument("--json-out", default="BENCH_REGIONS.json")
    ap.add_argument("--config", action="store_true",
                    help="internal: run one config in this process")
    ap.add_argument("--dir", default="")
    args = ap.parse_args()

    if args.config:
        asyncio.run(run_config(args))
        return

    import tempfile

    from tpuraft.storage.multilog import ensure_built
    from tpuraft.rheakv.native_store import ensure_built as kv_built

    ensure_built()
    kv_built()
    workdir = tempfile.mkdtemp(prefix=f"tpuraft_regions_{args.regions}_")
    cmd = [sys.executable, os.path.join(REPO, "bench_region_density.py"),
           "--config", "--regions", str(args.regions),
           "--stores", str(args.stores), "--dir", workdir,
           "--duration", str(args.duration),
           "--workers", str(args.workers),
           "--pace-ms", str(args.pace_ms),
           "--election-timeout-ms", str(args.election_timeout_ms),
           "--store-inflight", str(args.store_inflight),
           "--read-frac", str(args.read_frac),
           "--read-from", args.read_from,
           "--trace-sample", str(args.trace_sample)]
    if args.trace:
        cmd += ["--trace", os.path.abspath(args.trace)]
    if args.lease_reads:
        cmd.append("--lease-reads")
    if args.quiesce:
        cmd.append("--quiesce")
    if args.no_heat:
        cmd.append("--no-heat")
    if args.no_disk_guard:
        cmd.append("--no-disk-guard")
    if args.chaos_clock:
        cmd.append("--chaos-clock")
    if args.no_write_batch:
        cmd.append("--no-write-batch")
    if args.lifecycle_pd:
        cmd.append("--lifecycle-pd")
    if args.profile_ticks > 0:
        cmd += ["--profile-ticks", str(args.profile_ticks)]
        if args.profile_ticks_out:
            cmd += ["--profile-ticks-out",
                    os.path.abspath(args.profile_ticks_out)]
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    t0 = time.monotonic()
    p = subprocess.Popen(cmd, stdout=subprocess.PIPE, env=env)
    row = None
    for line in p.stdout:
        line = line.decode().strip()
        if line.startswith("RESULT "):
            row = json.loads(line[len("RESULT "):])
    p.wait()
    if row is None:
        row = {"regions": args.regions, "error": "no result"}
    row["wall_s"] = round(time.monotonic() - t0, 1)
    # merge into the committed JSON: "row" is the 1024-region headline,
    # other densities land as row_<regions> (the r5 file shape)
    path = os.path.join(REPO, args.json_out)
    out = {}
    if os.path.exists(path):
        with open(path) as f:
            out = json.load(f)
    out.setdefault("metric", "rheakv_region_density")
    out["stack"] = ("3 StoreEngines in-proc, native C++ KV engine per "
                    "store, multilog shared journal, engine protocol "
                    "plane, batching RheaKV client, counting PD")
    key = "row" if args.regions == 1024 else f"row_{args.regions}"
    if args.workers != 24:   # non-default load shapes get their own row
        key += f"_w{args.workers}"
    if args.read_frac >= 0:  # read-mix shapes: row_r95 / row_r50 / ...
        key += f"_r{int(round(args.read_frac * 100))}"
    if args.lease_reads:
        key += "_lease"
    if args.quiesce:
        key += "_quiesce"
    if args.no_heat:
        key += "_noheat"
    if args.no_disk_guard:
        key += "_nodg"
    if args.chaos_clock:
        key += "_ck"
    if args.no_write_batch:
        key += "_nowb"
    if args.lifecycle_pd:
        key += "_lcpd"
    out[key] = row
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(row), flush=True)
    subprocess.run(["rm", "-rf", workdir])


if __name__ == "__main__":
    main()
