"""Benchmark: batched multi-raft commit throughput on the device plane.

Measures the north-star hot path (BASELINE.json config row 3/4): G raft
groups' quorum commit advancement as one [G, P] kernel per tick, with the
realistic per-tick host<->device traffic — upload the updated matchIndex
matrix, run the fused tick, download commit results.  commits/sec = total
log entries whose commit index advanced, summed over groups.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "commits/s", "vs_baseline": N/1e6}
vs_baseline is against the BASELINE.md north-star target of 1M commits/s
(the reference repo publishes no benchmark numbers — mount was empty; see
BASELINE.md).
"""

import json
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    from tpuraft.ops.tick import (
        ROLE_FOLLOWER,
        ROLE_LEADER,
        GroupState,
        TickParams,
        raft_tick,
    )

    G = 16384       # groups (north-star scale)
    P = 8           # peer slots
    VOTERS = 3      # 3-replica groups
    BATCH = 32      # entries acked per follower per tick (apply_batch)
    TICKS = 200
    WARMUP = 20

    rng = np.random.default_rng(0)
    state = GroupState.zeros(G, P)
    state.role = jnp.full((G,), ROLE_LEADER, jnp.int32)
    voter = np.zeros((G, P), bool)
    voter[:, :VOTERS] = True
    state.voter_mask = jnp.asarray(voter)
    state.pending_rel = jnp.ones((G,), jnp.int32)
    params = TickParams.make(1000, 100, 900)

    tick = jax.jit(raft_tick, donate_argnums=(0,))

    # host-side match bookkeeping: per tick, followers ack BATCH more
    # entries with realistic jitter (stragglers ack less)
    host_match = np.zeros((G, P), np.int32)

    def run_tick(i):
        nonlocal state, host_match
        adv = rng.integers(BATCH // 2, BATCH + 1, (G, P)).astype(np.int32)
        adv[:, VOTERS:] = 0
        host_match[:, :] += adv
        # the per-tick upload: one coalesced [G, P] transfer
        state.match_rel = jax.device_put(host_match)
        state, out = tick(state, jnp.int32(i), params)
        # the per-tick download: commit results back to the host runtime
        return np.asarray(out.commit_rel)

    for i in range(WARMUP):
        commit = run_tick(i)
    commits_start = int(commit.sum())
    lat = []
    t0 = time.perf_counter()
    for i in range(WARMUP, WARMUP + TICKS):
        t1 = time.perf_counter()
        commit = run_tick(i)
        lat.append(time.perf_counter() - t1)
    elapsed = time.perf_counter() - t0
    total_commits = int(commit.sum()) - commits_start

    commits_per_sec = total_commits / elapsed
    lat_ms = sorted(x * 1000 for x in lat)
    p50 = lat_ms[len(lat_ms) // 2]
    p99 = lat_ms[int(len(lat_ms) * 0.99)]

    print(json.dumps({
        "metric": "multiraft_batched_commits_per_sec_16k_groups",
        "value": round(commits_per_sec, 1),
        "unit": "commits/s",
        "vs_baseline": round(commits_per_sec / 1e6, 3),
        "extra": {
            "groups": G, "peer_slots": P, "voters": VOTERS,
            "ticks_per_sec": round(TICKS / elapsed, 1),
            "tick_p50_ms": round(p50, 3), "tick_p99_ms": round(p99, 3),
            "device": str(jax.devices()[0]),
            "baseline": "north-star 1e6 commits/s (BASELINE.md; reference publishes none)",
        },
    }))


if __name__ == "__main__":
    main()
