"""Benchmark: batched multi-raft commit throughput on the device plane.

Measures the north-star hot path (BASELINE.json config row 3/4): G raft
groups' quorum commit advancement as one [G, P] kernel per tick, with the
realistic per-tick host<->device traffic — upload the updated matchIndex
matrix, run the fused tick, download commit results.  commits/sec = total
log entries whose commit index advanced, summed over groups.

Dispatch is pipelined with a bounded in-flight window, matching how the
host runtime actually consumes the device plane: tick i+1's upload+launch
does not wait for tick i's commit download (commit acks are delivered to
waiting closures asynchronously), but no more than DEPTH ticks may be
outstanding so commit-ack latency stays bounded.  Acks are drained as
they arrive (non-blocking ``is_ready`` polling between submits), so the
reported latency is submit-to-arrival per tick — the commit-index ack
latency the host runtime observes — quantized by the submit interval,
with the link's completion RTT reported separately as its floor.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "commits/s", "vs_baseline": N/1e6}
vs_baseline is against the BASELINE.md north-star target of 1M commits/s
(the reference repo publishes no benchmark numbers — mount was empty; see
BASELINE.md).
"""

import json
import time
from collections import deque

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    from tpuraft.ops.tick import (
        ROLE_FOLLOWER,
        ROLE_LEADER,
        GroupState,
        TickParams,
        raft_tick,
    )

    G = 16384       # groups (north-star scale)
    P = 8           # peer slots
    VOTERS = 3      # 3-replica groups
    BATCH = 32      # entries acked per follower per tick (apply_batch)
    TICKS = 400
    WARMUP = 40

    rng = np.random.default_rng(0)
    state = GroupState.zeros(G, P)
    state.role = jnp.full((G,), ROLE_LEADER, jnp.int32)
    voter = np.zeros((G, P), bool)
    voter[:, :VOTERS] = True
    state.voter_mask = jnp.asarray(voter)
    state.pending_rel = jnp.ones((G,), jnp.int32)
    params = TickParams.make(1000, 100, 900)

    tick = jax.jit(raft_tick, donate_argnums=(0,))

    # host-side match bookkeeping: per tick, followers ack BATCH more
    # entries with realistic jitter (stragglers ack less).  Ack arrival is
    # workload generation, not framework work — precompute outside the
    # timed loop (int8: values fit; the cumulative matrix stays int32).
    host_match = np.zeros((G, P), np.int32)
    total = WARMUP + TICKS
    advances = rng.integers(BATCH // 2, BATCH + 1, (total, G, P)).astype(np.int8)
    advances[:, :, VOTERS:] = 0

    inflight = deque()   # (submit_time, tick_idx, device commit array)
    lat = []
    last_commit = None   # most recently materialized commit array
    DEPTH = 16           # provisional for warmup; re-sized to the link below

    def drain_one():
        nonlocal last_commit
        ts, idx, arr = inflight.popleft()
        last_commit = np.asarray(arr)        # materialize = commit ack
        lat.append(time.perf_counter() - ts)

    def submit(i):
        nonlocal state
        host_match[:, :] += advances[i]
        # the per-tick upload: one coalesced [G, P] transfer.  Copy: the
        # async transfer must not observe later in-place += mutations.
        state.match_rel = jax.device_put(host_match.copy())
        new_state, out = tick(state, jnp.int32(i), params)
        state = new_state
        commit = out.commit_rel
        commit.copy_to_host_async()
        inflight.append((time.perf_counter(), i, commit))
        # drain acks as they actually arrive (non-blocking), then enforce
        # the bound: at most DEPTH ticks outstanding.
        while inflight and inflight[0][2].is_ready():
            drain_one()
        while len(inflight) >= DEPTH:
            drain_one()

    for i in range(WARMUP):
        submit(i)
    while inflight:
        drain_one()

    # dispatch->completion latency floor of the host<->chip link: the
    # minimum observable ack latency regardless of pipelining.
    rtts = []
    for _ in range(5):
        t1 = time.perf_counter()
        state2, out2 = tick(state, jnp.int32(0), params)
        out2.commit_rel.block_until_ready()
        rtts.append(time.perf_counter() - t1)
        state = state2
    completion_rtt_ms = round(min(rtts) * 1000, 2)

    # post-compile dispatch cost: a short unsynchronized burst
    burst = 8
    t_b = time.perf_counter()
    for i in range(WARMUP, WARMUP + burst):
        submit(i)
    dispatch_s = (time.perf_counter() - t_b) / burst
    while inflight:
        drain_one()

    # size the in-flight window to the LINK, not a constant: enough
    # outstanding ticks to cover the completion RTT at the measured
    # dispatch cost (plus margin), so a co-located chip (sub-ms RTT)
    # isn't saddled with tunnel-sized ack latency
    DEPTH = max(4, min(64, int(min(rtts) / max(dispatch_s, 1e-4)) + 4))

    # three measurement passes, report the MEDIAN: the tunnel to the
    # chip shares a congested link with ~2x run-to-run variance, and the
    # median is robust to one bad window without the upward bias of max
    passes = []
    half = (TICKS - burst) // 3
    start_i = WARMUP + burst
    for _ in range(3):
        lat.clear()
        base_commits = int(last_commit.sum())
        t0 = time.perf_counter()
        for i in range(start_i, start_i + half):
            submit(i)
        while inflight:
            drain_one()
        elapsed = time.perf_counter() - t0
        pass_commits = int(last_commit.sum()) - base_commits
        lat_ms = sorted(x * 1000 for x in lat)
        passes.append({
            "cps": pass_commits / elapsed,
            "tps": half / elapsed,
            "p50": lat_ms[len(lat_ms) // 2],
            "p99": lat_ms[int(len(lat_ms) * 0.99)],
        })
        start_i += half
    med = sorted(passes, key=lambda r: r["cps"])[len(passes) // 2]
    commits_per_sec = med["cps"]
    p50, p99 = med["p50"], med["p99"]

    # quorum kernel auto-selection on THIS device (VERDICT r1 #4): try
    # the Pallas kernel, A/B it against XLA when it compiles, record
    # the failure reason when it can't (tunneled TPUs: Mosaic
    # remote-compile 500 — direct-attach hardware required)
    from tpuraft.ops.quorum_pallas import (_fused_quorum_pallas,
                                           _fused_quorum_xla, select_impl)

    impl, impl_reason = select_impl()
    quorum_impl = {"impl": impl, "reason": impl_reason}
    if impl != "pallas":
        # AOT probe (VERDICT r2 #10): attempt an explicit
        # lower().compile() against this device once per round, so the
        # moment the remote-compile path heals BENCH records a real
        # pallas_speedup instead of a stale failure reason
        try:
            jax.jit(_fused_quorum_pallas, static_argnames=("interpret",)
                    ).lower(jnp.zeros((G, P), jnp.int32),
                            jnp.zeros((G, P), bool),
                            jnp.zeros((G, P), jnp.int32),
                            jnp.zeros((G, P), bool),
                            jnp.zeros((G, P), bool)).compile()
            quorum_impl["aot"] = "compiled — flip TPURAFT_QUORUM_IMPL"
        except Exception as e:  # noqa: BLE001
            quorum_impl["aot"] = f"{type(e).__name__}: {str(e)[:120]}"
    if impl == "pallas":
        gq, pq = G, P
        rngq = np.random.default_rng(1)
        m = jnp.asarray(rngq.integers(0, 1000, (gq, pq)).astype(np.int32))
        gr = jnp.asarray(rngq.random((gq, pq)) < 0.5)
        ak = jnp.asarray(rngq.integers(0, 10**6, (gq, pq)).astype(np.int32))
        vmq = np.zeros((gq, pq), bool)
        vmq[:, :VOTERS] = True
        vmq = jnp.asarray(vmq)
        ovq = jnp.zeros((gq, pq), bool)
        times = {}
        for name, fn in (("xla", _fused_quorum_xla),
                         ("pallas", _fused_quorum_pallas)):
            jax.block_until_ready(fn(m, gr, ak, vmq, ovq))  # warm
            t0 = time.perf_counter()
            for _ in range(20):
                r = fn(m, gr, ak, vmq, ovq)
            jax.block_until_ready(r)
            times[name] = (time.perf_counter() - t0) / 20
        quorum_impl["pallas_speedup"] = round(
            times["xla"] / times["pallas"], 3)

    # the END-TO-END number (real store processes: native TCP + shared
    # multilog fsync + engine plane) rides along from the last
    # bench_e2e.py run, so the driver's record carries both planes
    def load_sidecar(name):
        """A sibling benchmark's record riding along in extra; absent
        records are fine (the sidecar benches run separately)."""
        import os

        try:
            with open(os.path.join(
                    os.path.dirname(os.path.abspath(__file__)), name)) as f:
                return json.load(f)
        except Exception:
            return None

    e2e = None
    d = load_sidecar("BENCH_E2E.json")
    if d is not None:
        e2e = {
            "commits_per_sec": d["value"],
            "per_core_commits_per_sec":
                d["extra"].get("per_core_commits_per_sec"),
            "host_cores": d["extra"].get("host_cores"),
            "lowload_single_group_ack_ms":
                d["extra"].get("lowload_single_group_ack"),
            "ack_breakdown": d["extra"].get("ack_breakdown"),
            "stack": d["extra"].get("stack"),
        }

    # the scale ladder (bench_scale.py: 1K/4K/16K groups per process,
    # real appends -> fsync -> quorum -> apply) rides along the same way
    scale = load_sidecar("BENCH_SCALE.json")
    # the KV region-density record (bench_region_density.py: >=1K
    # regions through the full RheaKV stack)
    regions = load_sidecar("BENCH_REGIONS.json")

    print(json.dumps({
        "metric": "multiraft_batched_commits_per_sec_16k_groups",
        "value": round(commits_per_sec, 1),
        "unit": "commits/s",
        "vs_baseline": round(commits_per_sec / 1e6, 3),
        "extra": {
            "e2e": e2e,
            "scale": scale,
            "regions": regions,
            "quorum_impl": quorum_impl,
            "groups": G, "peer_slots": P, "voters": VOTERS,
            # PRIMARY regression signals (VERDICT r2 #8): both are
            # tunnel-independent — commits/s above is DERIVED and swings
            # 6-22M with tunnel congestion at zero code change
            # (BASELINE.md).  r02 recorded commits_per_tick_per_group =
            # 24.05 (8.24M cps / 20.9 tps / 16384 G) and dispatch_ms
            # 4.84; gate regressions on these two.
            "commits_per_tick_per_group": round(
                commits_per_sec / max(med["tps"], 1e-9) / G, 3),
            "r02_primary_signals": {"commits_per_tick_per_group": 24.05,
                                    "dispatch_ms": 4.84},
            "pipeline_depth": DEPTH,
            "dispatch_ms": round(dispatch_s * 1000, 2),
            "ticks_per_sec": round(med["tps"], 1),
            # all raw passes reported so the aggregation is explicit
            "aggregation": "median_of_3_passes",
            "pass_commits_per_sec": [round(r["cps"], 1) for r in passes],
            "ack_p50_ms": round(p50, 3), "ack_p99_ms": round(p99, 3),
            "completion_rtt_ms": completion_rtt_ms,
            "device": str(jax.devices()[0]),
            "baseline": "north-star 1e6 commits/s (BASELINE.md; reference publishes none)",
        },
    }))


if __name__ == "__main__":
    main()
