"""Counter: the canonical single-group raft application.

Reference parity: ``example:counter/CounterServer`` / ``CounterClient`` /
``CounterStateMachine`` / ``CounterServiceImpl`` + its request processors
(SURVEY.md §3.3) — a replicated 64-bit counter where ``increment_and_get``
goes through ``Node#apply`` and ``get`` uses the linearizable readIndex
barrier instead of the log.

Run a member (3-process cluster over TCP):
    python -m examples.counter --serve 127.0.0.1:8081 \
        --peers 127.0.0.1:8081,127.0.0.1:8082,127.0.0.1:8083 --data /tmp/c1
Run the client against it:
    python -m examples.counter --incr 5 \
        --peers 127.0.0.1:8081,127.0.0.1:8082,127.0.0.1:8083
Or the self-contained demo (3 nodes in one process, leader kill included):
    python -m examples.counter
"""

from __future__ import annotations

import argparse
import asyncio
import struct
from tpuraft.conf import Configuration
from tpuraft.core.cli_service import CliProcessors, CliService
from tpuraft.core.node import Node
from tpuraft.core.node_manager import NodeManager
from tpuraft.core.raft_group_service import RaftGroupService
from tpuraft.core.state_machine import Iterator, StateMachine
from tpuraft.entity import PeerId, Task
from tpuraft.errors import RaftError, Status
from tpuraft.options import NodeOptions
from tpuraft.route_table import RouteTable
from tpuraft.rpc.messages import register_message


def _msg(tid: int):
    def deco(cls):
        from dataclasses import dataclass as dc
        return register_message(tid, dc(cls))
    return deco
from tpuraft.rpc.tcp import TcpRpcServer, TcpTransport
from tpuraft.rpc.transport import RpcError

GROUP = "counter"


# -- wire messages (example type-id range 240+) ------------------------------

@_msg(240)
class IncrementAndGetRequest:
    delta: int = 1


@_msg(241)
class GetValueRequest:
    linearizable: bool = True


@_msg(242)
class ValueResponse:
    success: bool = False
    value: int = 0
    redirect: str = ""


class CounterStateMachine(StateMachine):
    """Applies 8-byte little-endian deltas; snapshots the running value."""

    def __init__(self) -> None:
        self.value = 0
        self.leader_term = -1

    async def on_apply(self, it: Iterator) -> None:
        while it.valid():
            (delta,) = struct.unpack("<q", it.data())
            self.value += delta
            done = it.done()
            if done is not None:
                # closures take Status only; the computed value rides as an
                # attribute (reference: CounterClosure#setValue before run)
                done.result_value = self.value
                done(Status.OK())
            it.next()

    async def on_leader_start(self, term: int) -> None:
        self.leader_term = term

    async def on_leader_stop(self, status: Status) -> None:
        self.leader_term = -1

    async def on_snapshot_save(self, writer, done) -> None:
        writer.write_file("counter", struct.pack("<q", self.value))
        done(Status.OK())

    async def on_snapshot_load(self, reader) -> bool:
        blob = reader.read_file("counter")
        if blob is None:
            return False
        (self.value,) = struct.unpack("<q", blob)
        return True


class CounterServer:
    """One cluster member: raft node + the counter RPC service on one port
    (reference: CounterServer boots RaftGroupService and registers the
    counter processors on the shared RpcServer)."""

    def __init__(self, me: PeerId, conf: Configuration, data_dir: str | None,
                 config_yaml: str | None = None):
        self.me = me
        self.conf = conf
        self.fsm = CounterStateMachine()
        self.server = TcpRpcServer(me.endpoint)
        self.manager = NodeManager(self.server)
        self.transport = TcpTransport(endpoint=me.endpoint)
        self.node: Node | None = None
        self.data_dir = data_dir
        self.config_yaml = config_yaml

    async def start(self) -> None:
        await self.server.start()
        CliProcessors(self.manager)
        if self.config_yaml:
            # tunables from YAML (SURVEY §6 config layer); topology and
            # storage placement come from the CLI here, so a YAML that
            # also sets them is a CONFLICT, not a silent override
            from tpuraft.config import load_node_options

            opts = load_node_options(self.config_yaml)
            # storage placement and topology always come from the CLI
            # here (--data / --peers), so YAML settings for them would
            # be silently clobbered below — reject them loudly instead
            conflicts = [name for name, dflt in [
                ("initial_conf", Configuration()),
                ("fsm", None)] if getattr(opts, name) != dflt]
            conflicts += [n for n in ("log_uri", "raft_meta_uri",
                                      "snapshot_uri")
                          if getattr(opts, n)]
            if conflicts:
                raise SystemExit(
                    f"--config sets {conflicts}, which --peers/--data "
                    f"control on the counter CLI — remove them from "
                    f"the YAML or drop the flags")
        else:
            opts = NodeOptions()
        opts.initial_conf = self.conf.copy()
        opts.fsm = self.fsm
        if self.data_dir:
            opts.log_uri = f"file://{self.data_dir}/log"
            opts.raft_meta_uri = f"file://{self.data_dir}/meta"
            opts.snapshot_uri = f"file://{self.data_dir}/snapshot"
        else:
            opts.log_uri = "memory://"
            opts.raft_meta_uri = "memory://"
        svc = RaftGroupService(GROUP, self.me, opts, self.manager,
                               self.transport)
        self.node = await svc.start()
        self.server.register("counter_incr", self._handle_incr)
        self.server.register("counter_get", self._handle_get)

    async def stop(self) -> None:
        if self.node:
            await self.node.shutdown()
        await self.transport.close()
        await self.server.stop()

    # -- service handlers (reference: IncrementAndGetRequestProcessor etc) --

    def _redirect(self) -> ValueResponse:
        leader = self.node.leader_id if self.node else None
        return ValueResponse(success=False, value=0,
                             redirect=str(leader) if leader else "")

    async def _handle_incr(self, req: IncrementAndGetRequest) -> ValueResponse:
        if self.node is None or not self.node.is_leader():
            return self._redirect()
        fut: asyncio.Future = asyncio.get_running_loop().create_future()

        def done(st: Status):
            if not fut.done():
                fut.set_result((st, getattr(done, "result_value", None)))

        await self.node.apply(Task(data=struct.pack("<q", req.delta),
                                   done=done))
        st, value = await fut
        if not st.is_ok():
            return self._redirect()
        return ValueResponse(success=True, value=value)

    async def _handle_get(self, req: GetValueRequest) -> ValueResponse:
        if self.node is None:
            return self._redirect()
        if not req.linearizable:
            return ValueResponse(success=True, value=self.fsm.value)
        try:
            await self.node.read_index()  # waits until applied >= readIndex
        except Exception:  # noqa: BLE001 — no quorum / not leader
            return self._redirect()
        return ValueResponse(success=True, value=self.fsm.value)


class CounterClient:
    """Leader-finding client with redirect-following retry (reference:
    CounterClient over CliClientService + RouteTable)."""

    def __init__(self, conf: Configuration, transport=None):
        self.conf = conf
        self.transport = transport or TcpTransport()
        self.route_table = RouteTable()
        self.route_table.update_configuration(GROUP, conf)
        self.cli = CliService(self.transport)
        self._leader: PeerId | None = None

    async def _find_leader(self) -> PeerId:
        if self._leader is not None:
            return self._leader
        st = await self.route_table.refresh_leader(self.cli, GROUP)
        leader = self.route_table.select_leader(GROUP)
        if not st.is_ok() or leader is None:
            raise RpcError(Status.error(RaftError.EPERM, f"no leader: {st}"))
        self._leader = leader
        return leader

    async def _call(self, method: str, req, retries: int = 40):
        last: Exception | None = None
        for _ in range(retries):
            try:
                leader = await self._find_leader()
                resp = await self.transport.call(leader.endpoint, method, req,
                                                 2000)
            except RpcError as e:
                # dead/electing cluster: a re-election takes a few election
                # timeouts, so the retry budget must span several seconds
                last = e
                self._leader = None
                await asyncio.sleep(0.15)
                continue
            if resp.success:
                return resp.value
            self._leader = (PeerId.parse(resp.redirect)
                            if resp.redirect else None)
            await asyncio.sleep(0.05 if resp.redirect else 0.2)
        raise last or TimeoutError(f"{method}: retries exhausted")

    async def increment_and_get(self, delta: int = 1) -> int:
        return await self._call("counter_incr", IncrementAndGetRequest(delta))

    async def get(self, linearizable: bool = True) -> int:
        return await self._call("counter_get", GetValueRequest(linearizable))


# -- demo / main -------------------------------------------------------------

async def demo(n: int = 3, increments: int = 10, data_root: str | None = None,
               verbose: bool = True) -> int:
    """Self-contained: n servers in one process over TCP, client traffic,
    leader crash, recovery. Returns the final counter value."""
    servers: list[CounterServer] = []
    for _ in range(n):
        srv = TcpRpcServer("127.0.0.1:0")
        await srv.start()
        srv.endpoint = f"127.0.0.1:{srv.bound_port}"
        await srv.stop()
        servers.append(srv)  # placeholder for port reservation
    peers = [PeerId.parse(s.endpoint) for s in servers]
    conf = Configuration(list(peers))
    members = []
    for i, p in enumerate(peers):
        m = CounterServer(
            p, conf, f"{data_root}/{p.port}" if data_root else None)
        await m.start()
        members.append(m)

    def say(*a):
        if verbose:
            print(*a)

    # wait for the first election before driving traffic
    for _ in range(400):
        if any(m.node and m.node.is_leader() for m in members):
            break
        await asyncio.sleep(0.025)

    client = CounterClient(conf)
    try:
        for i in range(increments):
            v = await client.increment_and_get()
            say(f"increment -> {v}")
        v = await client.get()
        say(f"linearizable get -> {v}")
        assert v == increments
        # crash the leader; the cluster recovers and serves again
        leader = next(m for m in members if m.node and m.node.is_leader())
        say(f"crashing leader {leader.me} ...")
        await leader.stop()
        members.remove(leader)
        client._leader = None
        v = await client.increment_and_get(5)
        say(f"after failover: increment 5 -> {v}")
        assert v == increments + 5
        return v
    finally:
        await client.transport.close()
        for m in members:
            await m.stop()


async def _serve(args) -> None:
    conf = Configuration.parse(args.peers)
    server = CounterServer(PeerId.parse(args.serve), conf, args.data,
                           config_yaml=args.config)
    await server.start()
    print(f"counter member {args.serve} up (group={GROUP})")
    try:
        while True:
            await asyncio.sleep(3600)
    finally:
        await server.stop()


async def _client(args) -> None:
    conf = Configuration.parse(args.peers)
    client = CounterClient(conf)
    try:
        if args.incr:
            print(await client.increment_and_get(args.incr))
        else:
            print(await client.get())
    finally:
        await client.transport.close()


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--serve", help="ip:port to serve as a cluster member")
    ap.add_argument("--peers", help="comma-separated cluster conf")
    ap.add_argument("--data", help="data dir (omit for in-memory)")
    ap.add_argument("--config", help="YAML options file (tpuraft.config)")
    ap.add_argument("--incr", type=int, help="client: increment by N")
    ap.add_argument("--get", action="store_true", help="client: read value")
    args = ap.parse_args()
    if args.serve:
        asyncio.run(_serve(args))
    elif args.incr or args.get:
        asyncio.run(_client(args))
    else:
        asyncio.run(demo())


if __name__ == "__main__":
    main()
