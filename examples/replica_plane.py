"""Replica-axis collective plane, bootable (VERDICT r2 #2).

Boots R replica endpoints x G raft groups on ONE
ReplicatedClusterPlane — commit points for ALL groups come from the
replica-axis all_gather + order statistic (XLA collectives on a mesh,
numpy twin without one) computed from each replica's DURABLE log state.
Then drives writes, crashes a replica mid-load (chaos), keeps writing
on the surviving quorum, and verifies convergence.

    python -m examples.replica_plane                    # numpy plane
    python -m examples.replica_plane --mesh             # 2D device mesh
    python -m examples.replica_plane --replicas 4 --groups 8 --chaos

Reference role: the NCCL/MPI math plane of ``core:ReplicatorGroup`` ack
aggregation (SURVEY.md §6 comms backend), as a deployable mode.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import time


def build_mesh(n_replicas: int, n_groups_axis: int):
    """2D (replica, groups) mesh from available devices, or None."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    need = n_replicas * n_groups_axis
    devs = jax.devices()
    if len(devs) < need:
        raise SystemExit(
            f"--mesh needs {need} devices, have {len(devs)} "
            f"(hint: JAX_PLATFORMS=cpu XLA_FLAGS="
            f"--xla_force_host_platform_device_count={need})")
    return Mesh(np.array(devs[:need]).reshape(n_replicas, n_groups_axis),
                ("replica", "groups"))


async def main(args) -> None:
    from tpuraft.parallel.replica_cluster import ReplicaPlaneCluster

    mesh = None
    if args.mesh:
        mesh = build_mesh(args.replicas, args.mesh_groups_axis)
    c = ReplicaPlaneCluster(args.replicas, args.groups, mesh=mesh,
                            election_timeout_ms=args.election_timeout_ms,
                            transport=args.transport,
                            base_port=args.base_port)
    await c.start_all()
    acked = 0
    try:
        leaders = {g: await c.wait_leader(g) for g in c.groups}
        t0 = time.monotonic()
        for wave in range(args.waves):
            await asyncio.gather(*(
                c.apply_ok(leaders[g], b"%s-w%d-%d" % (g.encode(), wave, i))
                for g in c.groups for i in range(args.writes_per_wave)))
            acked += len(c.groups) * args.writes_per_wave

        if args.chaos:
            # crash the replica leading the fewest groups: the plane's
            # order statistic still finds an (R-1)/R quorum, its groups
            # fail over, and commits keep flowing
            lead_count = {ep.endpoint: 0 for ep in c.endpoints}
            for g in c.groups:
                lead_count[leaders[g].server_id.endpoint] += 1
            victim = min(c.endpoints, key=lambda ep: lead_count[ep.endpoint])
            await c.stop_replica(victim)
            for g in c.groups:
                leaders[g] = await c.wait_leader(g, timeout_s=20)
            await asyncio.gather(*(
                c.apply_ok(leaders[g], b"%s-post-chaos" % g.encode())
                for g in c.groups))
            acked += len(c.groups)

        dt = time.monotonic() - t0
        # convergence on the surviving replicas
        want = args.waves * args.writes_per_wave + (1 if args.chaos else 0)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if all(len(c.fsms[k].logs) >= want for k in c.nodes):
                break
            await asyncio.sleep(0.05)
        for g in c.groups:
            logs = [c.fsms[(g, ep)].logs for ep in c.endpoints
                    if (g, ep) in c.nodes]
            assert logs and all(lg == logs[0] for lg in logs), \
                f"group {g} diverged"
        print(json.dumps({
            "replicas": args.replicas, "groups": args.groups,
            "transport": args.transport,
            "mesh": bool(mesh), "acked": acked,
            "plane_ticks": c.plane.ticks,
            "commit_advances": c.plane.commit_advances,
            "chaos": args.chaos, "elapsed_s": round(dt, 2)}))
    finally:
        await c.stop_all()


if __name__ == "__main__":
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--replicas", type=int, default=4)
    ap.add_argument("--groups", type=int, default=8)
    ap.add_argument("--waves", type=int, default=3)
    ap.add_argument("--writes-per-wave", type=int, default=5)
    ap.add_argument("--election-timeout-ms", type=int, default=600)
    ap.add_argument("--mesh", action="store_true",
                    help="shard the plane over a 2D device mesh")
    ap.add_argument("--mesh-groups-axis", type=int, default=4)
    ap.add_argument("--transport", default="inproc",
                    choices=["inproc", "tcp", "native"],
                    help="protocol-plane transport: in-proc loopback, "
                         "asyncio TCP sockets, or the C++ epoll engine")
    ap.add_argument("--base-port", type=int, default=7700)
    ap.add_argument("--chaos", action="store_true",
                    help="crash one replica mid-run")
    asyncio.run(main(ap.parse_args()))
