"""RheaKV multi-region store + YCSB-style benchmark driver.

Reference parity: ``example:rheakv/*`` benchmark (SURVEY.md §3.3) — boots
an N-store, R-region RheaKV cluster in one process (the reference's
benchmark yaml topology), loads keys, then runs a mixed workload and
reports throughput + latency percentiles.

    python -m examples.rheakv_bench                 # defaults: 3x4, quick
    python -m examples.rheakv_bench --regions 16 --keys 20000 --ops 50000 \
        --workload a    # 50/50 read-update (YCSB-A); b = 95/5
"""

from __future__ import annotations

import argparse
import asyncio
import struct
import time

import numpy as np

from tpuraft.rheakv.client import RheaKVStore
from tpuraft.rheakv.metadata import Region
from tpuraft.rheakv.pd_client import FakePlacementDriverClient
from tpuraft.options import ReadOnlyOption
from tpuraft.rheakv.store_engine import StoreEngine, StoreEngineOptions
from tpuraft.rpc.transport import InProcNetwork, InProcTransport, RpcServer


def make_regions(n_regions: int, n_keys_space: int = 1 << 32) -> list[Region]:
    """Pre-split the 4-byte big-endian key space into n_regions ranges
    (the reference benchmark pre-splits via PD before loading)."""
    bounds = [int(i * n_keys_space / n_regions) for i in range(n_regions + 1)]
    regions = []
    for i in range(n_regions):
        start = struct.pack(">I", bounds[i]) if i else b""
        end = struct.pack(">I", bounds[i + 1]) if i < n_regions - 1 else b""
        regions.append(Region(id=i + 1, start_key=start, end_key=end))
    return regions


class BenchCluster:
    """N stores x R regions over the in-proc loopback fabric."""

    def __init__(self, n_stores: int, regions: list[Region],
                 election_timeout_ms: int = 1000, lease_reads: bool = False):
        self.lease_reads = lease_reads
        self.net = InProcNetwork()
        self.endpoints = [f"127.0.0.1:{6100 + i}" for i in range(n_stores)]
        for r in regions:
            r.peers = list(self.endpoints)
        self.regions = regions
        self.election_timeout_ms = election_timeout_ms
        self.stores: dict[str, StoreEngine] = {}

    async def start(self) -> None:
        for ep in self.endpoints:
            server = RpcServer(ep)
            self.net.bind(server)
            opts = StoreEngineOptions(
                server_id=ep,
                initial_regions=[r.copy() for r in self.regions],
                election_timeout_ms=self.election_timeout_ms,
                read_only_option=(ReadOnlyOption.LEASE_BASED
                                  if self.lease_reads
                                  else ReadOnlyOption.SAFE))
            store = StoreEngine(opts, server, InProcTransport(self.net, ep))
            await store.start()
            self.stores[ep] = store

    async def wait_leaders(self, timeout_s: float = 30.0) -> None:
        deadline = time.monotonic() + timeout_s
        want = {r.id for r in self.regions}
        while time.monotonic() < deadline:
            led = set()
            for s in self.stores.values():
                for r in s.list_regions():
                    eng = s.get_region_engine(r.id)
                    if eng and eng.is_leader():
                        led.add(r.id)
            if led >= want:
                return
            await asyncio.sleep(0.05)
        raise TimeoutError("regions without leaders")

    async def client(self) -> RheaKVStore:
        pd = FakePlacementDriverClient(
            [r.copy() for r in next(iter(self.stores.values())).list_regions()])
        kv = RheaKVStore(pd, InProcTransport(self.net, "bench-client:0"))
        await kv.start()
        return kv

    async def stop(self) -> None:
        for ep, s in list(self.stores.items()):
            self.net.unbind(ep)
            await s.shutdown()
        self.stores.clear()


def _key(i: int) -> bytes:
    # spread keys uniformly over the pre-split >I space
    return struct.pack(">I", (i * 2654435761) & 0xFFFFFFFF)


async def run_bench(n_stores: int = 3, n_regions: int = 4,
                    n_keys: int = 2000, n_ops: int = 5000,
                    value_size: int = 100, workload: str = "b",
                    concurrency: int = 64, lease_reads: bool = False,
                    verbose: bool = True) -> dict:
    read_frac = {"a": 0.5, "b": 0.95, "c": 1.0}[workload]
    cluster = BenchCluster(n_stores, make_regions(n_regions),
                           lease_reads=lease_reads)
    await cluster.start()
    await cluster.wait_leaders()
    kv = await cluster.client()
    value = b"v" * value_size
    rng = np.random.default_rng(0)

    def say(*a):
        if verbose:
            print(*a)

    try:
        # -- load phase ----------------------------------------------------
        t0 = time.perf_counter()
        sem = asyncio.Semaphore(concurrency)

        async def put_one(i: int):
            async with sem:
                assert await kv.put(_key(i), value)

        await asyncio.gather(*(put_one(i) for i in range(n_keys)))
        load_s = time.perf_counter() - t0
        say(f"load: {n_keys} keys across {n_regions} regions "
            f"in {load_s:.2f}s ({n_keys / load_s:,.0f} ops/s)")

        # -- mixed phase (YCSB-{a,b,c}: zipf-less uniform picks) ----------
        ops = rng.random(n_ops) < read_frac
        picks = rng.integers(0, n_keys, n_ops)
        lat: list[float] = []
        t0 = time.perf_counter()

        async def one(i: int):
            async with sem:
                s = time.perf_counter()
                if ops[i]:
                    await kv.get(_key(int(picks[i])))
                else:
                    await kv.put(_key(int(picks[i])), value)
                lat.append(time.perf_counter() - s)

        await asyncio.gather(*(one(i) for i in range(n_ops)))
        run_s = time.perf_counter() - t0
        lat_ms = np.sort(np.asarray(lat)) * 1e3
        result = {
            "workload": workload,
            "stores": n_stores, "regions": n_regions,
            "ops_per_s": n_ops / run_s,
            "p50_ms": float(lat_ms[int(0.50 * len(lat_ms))]),
            "p99_ms": float(lat_ms[int(0.99 * len(lat_ms)) - 1]),
        }
        say(f"workload-{workload}: {n_ops} ops ({read_frac:.0%} reads) "
            f"in {run_s:.2f}s -> {result['ops_per_s']:,.0f} ops/s, "
            f"p50 {result['p50_ms']:.2f}ms, p99 {result['p99_ms']:.2f}ms")
        return result
    finally:
        await kv.shutdown()
        await cluster.stop()


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--stores", type=int, default=3)
    ap.add_argument("--regions", type=int, default=4)
    ap.add_argument("--keys", type=int, default=2000)
    ap.add_argument("--ops", type=int, default=5000)
    ap.add_argument("--value-size", type=int, default=100)
    ap.add_argument("--workload", choices=["a", "b", "c"], default="b")
    ap.add_argument("--concurrency", type=int, default=64)
    ap.add_argument("--lease-reads", action="store_true",
                    help="LEASE_BASED readIndex (no per-read quorum round)")
    args = ap.parse_args()
    asyncio.run(run_bench(args.stores, args.regions, args.keys, args.ops,
                          args.value_size, args.workload, args.concurrency,
                          args.lease_reads))


if __name__ == "__main__":
    main()
