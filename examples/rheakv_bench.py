"""RheaKV multi-region store + YCSB-style benchmark driver.

Reference parity: ``example:rheakv/*`` benchmark (SURVEY.md §3.3) — boots
an N-store, R-region RheaKV cluster in one process (the reference's
benchmark yaml topology), loads keys, then runs a mixed workload and
reports throughput + latency percentiles.

    python -m examples.rheakv_bench                 # defaults: 3x4, quick
    python -m examples.rheakv_bench --regions 16 --keys 20000 --ops 50000 \
        --workload a    # 50/50 read-update (YCSB-A); b = 95/5
    python -m examples.rheakv_bench --transport native --store native \
        --data /tmp/rkv # real epoll sockets + C++ KV engine
"""

from __future__ import annotations

import argparse
import asyncio
import struct
import time

import numpy as np

from tpuraft.rheakv.client import RheaKVStore
from tpuraft.rheakv.metadata import Region
from tpuraft.rheakv.pd_client import FakePlacementDriverClient
from tpuraft.options import ReadOnlyOption
from tpuraft.rheakv.store_engine import StoreEngine, StoreEngineOptions
from tpuraft.rpc.transport import InProcNetwork, InProcTransport, RpcServer


def make_regions(n_regions: int, n_keys_space: int = 1 << 32) -> list[Region]:
    """Pre-split the 4-byte big-endian key space into n_regions ranges
    (the reference benchmark pre-splits via PD before loading)."""
    bounds = [int(i * n_keys_space / n_regions) for i in range(n_regions + 1)]
    regions = []
    for i in range(n_regions):
        start = struct.pack(">I", bounds[i]) if i else b""
        end = struct.pack(">I", bounds[i + 1]) if i < n_regions - 1 else b""
        regions.append(Region(id=i + 1, start_key=start, end_key=end))
    return regions


class BenchCluster:
    """N stores x R regions, over in-proc loopback or real sockets
    (``transport``: "inproc" | "tcp" | "native" — the latter two bind
    ephemeral localhost ports; "native" is the C++ epoll engine).
    ``store``: "memory" or "native" (C++ KV engine; needs data_path)."""

    def __init__(self, n_stores: int, regions: list[Region],
                 election_timeout_ms: int = 1000, lease_reads: bool = False,
                 transport: str = "inproc", store: str = "memory",
                 data_path: str = ""):
        self.lease_reads = lease_reads
        self.transport_kind = transport
        self.store_kind = store
        self.data_path = data_path
        self.net = InProcNetwork() if transport == "inproc" else None
        self.n_stores = n_stores
        self.endpoints: list[str] = []
        self.regions = regions
        self.election_timeout_ms = election_timeout_ms
        self.stores: dict[str, StoreEngine] = {}
        self._servers = []
        self._transports = []

    def _transport_classes(self):
        """(server_cls, transport_cls) for the socket fabrics."""
        if self.transport_kind == "tcp":
            from tpuraft.rpc.tcp import TcpRpcServer, TcpTransport
            return TcpRpcServer, TcpTransport
        from tpuraft.rpc.native_tcp import (
            NativeTcpRpcServer,
            NativeTcpTransport,
        )
        return NativeTcpRpcServer, NativeTcpTransport

    async def _make_server(self, i: int):
        if self.transport_kind == "inproc":
            ep = f"127.0.0.1:{6100 + i}"
            server = RpcServer(ep)
            self.net.bind(server)
            return ep, server, InProcTransport(self.net, ep)
        srv_cls, t_cls = self._transport_classes()
        server = srv_cls("127.0.0.1:0")
        await server.start()
        ep = f"127.0.0.1:{server.bound_port}"
        server.endpoint = ep
        return ep, server, t_cls(endpoint=ep)

    def _raw_store_factory(self, ep: str):
        if self.store_kind != "native":
            return None
        import os
        import tempfile

        from tpuraft.rheakv.native_store import NativeRawKVStore
        if not self.data_path:
            # per-run unique: a fixed default would replay a previous
            # run's WAL when the OS reuses an ephemeral port
            self.data_path = tempfile.mkdtemp(prefix="rheakv_bench_")
        base = self.data_path
        os.makedirs(base, exist_ok=True)  # engine mkdirs only the leaf
        return lambda: NativeRawKVStore(f"{base}/{ep.replace(':', '_')}")

    async def start(self) -> None:
        made = []
        for i in range(self.n_stores):
            ep, server, transport = await self._make_server(i)
            # register for cleanup AS EACH is made, so a failure midway
            # (or during store.start below) can't strand io threads/fds
            self._servers.append(server)
            self._transports.append(transport)
            made.append((ep, server, transport))
        self.endpoints = [ep for ep, _, _ in made]
        for r in self.regions:
            r.peers = list(self.endpoints)
        for ep, server, transport in made:
            opts = StoreEngineOptions(
                server_id=ep,
                initial_regions=[r.copy() for r in self.regions],
                election_timeout_ms=self.election_timeout_ms,
                read_only_option=(ReadOnlyOption.LEASE_BASED
                                  if self.lease_reads
                                  else ReadOnlyOption.SAFE))
            factory = self._raw_store_factory(ep)
            if factory is not None:
                opts.raw_store_factory = factory
            store = StoreEngine(opts, server, transport)
            await store.start()
            self.stores[ep] = store

    async def wait_leaders(self, timeout_s: float = 30.0) -> None:
        deadline = time.monotonic() + timeout_s
        want = {r.id for r in self.regions}
        while time.monotonic() < deadline:
            led = set()
            for s in self.stores.values():
                for r in s.list_regions():
                    eng = s.get_region_engine(r.id)
                    if eng and eng.is_leader():
                        led.add(r.id)
            if led >= want:
                return
            await asyncio.sleep(0.05)
        raise TimeoutError("regions without leaders")

    async def client(self, read_preference: str = "leader") -> RheaKVStore:
        pd = FakePlacementDriverClient(
            [r.copy() for r in next(iter(self.stores.values())).list_regions()])
        if self.transport_kind == "inproc":
            t = InProcTransport(self.net, "bench-client:0")
        else:
            t = self._transport_classes()[1]()
        self._client_transport = t
        kv = RheaKVStore(pd, t, read_preference=read_preference)
        await kv.start()
        return kv

    async def stop(self) -> None:
        for ep, s in list(self.stores.items()):
            if self.net is not None:
                self.net.unbind(ep)
            await s.shutdown()
        self.stores.clear()
        for server in self._servers:
            stop = getattr(server, "stop", None)
            if stop is not None:
                await stop()
        self._servers.clear()
        for t in self._transports:
            close = getattr(t, "close", None)
            if close is not None:
                await close()
        self._transports.clear()
        ct = getattr(self, "_client_transport", None)
        if ct is not None and hasattr(ct, "close"):
            await ct.close()


def _key(i: int) -> bytes:
    # spread keys uniformly over the pre-split >I space
    return struct.pack(">I", (i * 2654435761) & 0xFFFFFFFF)


async def run_bench(n_stores: int = 3, n_regions: int = 4,
                    n_keys: int = 2000, n_ops: int = 5000,
                    value_size: int = 100, workload: str = "b",
                    concurrency: int = 64, lease_reads: bool = False,
                    transport: str = "inproc", store: str = "memory",
                    data_path: str = "", verbose: bool = True,
                    read_preference: str = "leader",
                    zipf_theta: float = 0.0) -> dict:
    read_frac = {"a": 0.5, "b": 0.95, "c": 1.0}[workload]
    cluster = BenchCluster(n_stores, make_regions(n_regions),
                           lease_reads=lease_reads, transport=transport,
                           store=store, data_path=data_path)
    value = b"v" * value_size
    rng = np.random.default_rng(0)
    kv = None

    def say(*a):
        if verbose:
            print(*a)

    try:
        # setup inside the try: a wait_leaders timeout must still tear
        # the native io threads / sockets / WAL fds down via finally
        await cluster.start()
        await cluster.wait_leaders()
        kv = await cluster.client(read_preference)
        # -- load phase ----------------------------------------------------
        t0 = time.perf_counter()
        sem = asyncio.Semaphore(concurrency)

        async def put_one(i: int):
            async with sem:
                assert await kv.put(_key(i), value)

        await asyncio.gather(*(put_one(i) for i in range(n_keys)))
        load_s = time.perf_counter() - t0
        say(f"load: {n_keys} keys across {n_regions} regions "
            f"in {load_s:.2f}s ({n_keys / load_s:,.0f} ops/s)")

        # -- mixed phase (YCSB-{a,b,c}; uniform or scrambled-zipfian
        # request distribution, as in the YCSB core workloads) -----------
        ops = rng.random(n_ops) < read_frac
        if zipf_theta > 0:
            ranks = np.arange(1, n_keys + 1, dtype=np.float64)
            weights = ranks ** -zipf_theta
            weights /= weights.sum()
            hot = rng.choice(n_keys, size=n_ops, p=weights)
            # scramble: hot ranks spread over the keyspace (YCSB's
            # ScrambledZipfian), so the hotspot isn't one region
            perm = rng.permutation(n_keys)
            picks = perm[hot]
        else:
            picks = rng.integers(0, n_keys, n_ops)
        lat: list[float] = []
        t0 = time.perf_counter()

        async def one(i: int):
            async with sem:
                s = time.perf_counter()
                if ops[i]:
                    await kv.get(_key(int(picks[i])))
                else:
                    await kv.put(_key(int(picks[i])), value)
                lat.append(time.perf_counter() - s)

        await asyncio.gather(*(one(i) for i in range(n_ops)))
        run_s = time.perf_counter() - t0
        lat_ms = np.sort(np.asarray(lat)) * 1e3
        result = {
            "workload": workload, "transport": transport, "store": store,
            "stores": n_stores, "regions": n_regions,
            "read_preference": read_preference,
            "zipf_theta": zipf_theta,
            "ops_per_s": n_ops / run_s,
            "p50_ms": float(lat_ms[int(0.50 * len(lat_ms))]),
            "p99_ms": float(lat_ms[int(0.99 * len(lat_ms)) - 1]),
        }
        say(f"workload-{workload}: {n_ops} ops ({read_frac:.0%} reads) "
            f"in {run_s:.2f}s -> {result['ops_per_s']:,.0f} ops/s, "
            f"p50 {result['p50_ms']:.2f}ms, p99 {result['p99_ms']:.2f}ms")
        return result
    finally:
        if kv is not None:
            await kv.shutdown()
        await cluster.stop()


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--stores", type=int, default=3)
    ap.add_argument("--regions", type=int, default=4)
    ap.add_argument("--keys", type=int, default=2000)
    ap.add_argument("--ops", type=int, default=5000)
    ap.add_argument("--value-size", type=int, default=100)
    ap.add_argument("--workload", choices=["a", "b", "c"], default="b")
    ap.add_argument("--concurrency", type=int, default=64)
    ap.add_argument("--lease-reads", action="store_true",
                    help="LEASE_BASED readIndex (no per-read quorum round)")
    ap.add_argument("--transport", choices=["inproc", "tcp", "native"],
                    default="inproc",
                    help="RPC fabric: in-proc loopback, asyncio TCP, or "
                         "the native C++ epoll engine")
    ap.add_argument("--store", choices=["memory", "native"],
                    default="memory",
                    help="data engine: in-memory or the native C++ engine")
    ap.add_argument("--data", default="",
                    help="data dir for --store native")
    ap.add_argument("--zipf", type=float, default=0.0, metavar="THETA",
                    help="scrambled-zipfian request skew (YCSB default "
                         "0.99; 0 = uniform)")
    ap.add_argument("--read-preference", choices=["leader", "any"],
                    default="leader",
                    help="'any' spreads linearizable reads over ALL "
                         "replicas (followers/learners serve via the "
                         "readIndex barrier). NOTE: pays off when "
                         "replicas own separate CPUs (multi-host); in "
                         "this single-process harness the forwarding "
                         "hop only adds latency")
    args = ap.parse_args()
    asyncio.run(run_bench(args.stores, args.regions, args.keys, args.ops,
                          args.value_size, args.workload, args.concurrency,
                          args.lease_reads, args.transport, args.store,
                          args.data, read_preference=args.read_preference,
                          zipf_theta=args.zipf))


if __name__ == "__main__":
    main()
