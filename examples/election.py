"""Leader election only — no replicated data.

Reference parity: ``example:election/*`` (SURVEY.md §3.3, ``[1.3+]``): use
a raft group purely as an election service; the state machine only cares
about ``on_leader_start`` / ``on_leader_stop``.  Common pattern for HA
singletons (schedulers, PD-style controllers).

    python -m examples.election          # in-process demo w/ leader kill
    python -m examples.election --serve 127.0.0.1:8081 \
        --peers 127.0.0.1:8081,127.0.0.1:8082,127.0.0.1:8083
"""

from __future__ import annotations

import argparse
import asyncio
from typing import Callable, Optional

from tpuraft.conf import Configuration
from tpuraft.core.cli_service import CliProcessors
from tpuraft.core.node import Node
from tpuraft.core.node_manager import NodeManager
from tpuraft.core.raft_group_service import RaftGroupService
from tpuraft.core.state_machine import Iterator, StateMachine
from tpuraft.entity import PeerId
from tpuraft.options import NodeOptions
from tpuraft.rpc.tcp import TcpRpcServer, TcpTransport

GROUP = "election"


class ElectionOnlyStateMachine(StateMachine):
    """Only the leadership callbacks matter (reference:
    ElectionOnlyStateMachine)."""

    def __init__(self,
                 on_start: Optional[Callable[[int], None]] = None,
                 on_stop: Optional[Callable[[], None]] = None):
        self.is_leader = False
        self.leader_term = -1
        self._on_start = on_start
        self._on_stop = on_stop

    async def on_apply(self, it: Iterator) -> None:
        while it.valid():  # only no-op/conf entries ever arrive
            it.next()

    async def on_leader_start(self, term: int) -> None:
        self.is_leader = True
        self.leader_term = term
        if self._on_start:
            self._on_start(term)

    async def on_leader_stop(self, status) -> None:
        self.is_leader = False
        if self._on_stop:
            self._on_stop()


class ElectionNode:
    """One election-service member on a TCP endpoint."""

    def __init__(self, me: PeerId, conf: Configuration,
                 fsm: Optional[ElectionOnlyStateMachine] = None,
                 election_timeout_ms: int = 1000):
        self.me = me
        self.conf = conf
        self.fsm = fsm or ElectionOnlyStateMachine()
        self.election_timeout_ms = election_timeout_ms
        self.server = TcpRpcServer(me.endpoint)
        self.transport = TcpTransport(endpoint=me.endpoint)
        self.node: Node | None = None

    async def start(self) -> None:
        await self.server.start()
        manager = NodeManager(self.server)
        CliProcessors(manager)
        opts = NodeOptions(
            election_timeout_ms=self.election_timeout_ms,
            initial_conf=self.conf.copy(), fsm=self.fsm,
            log_uri="memory://", raft_meta_uri="memory://")
        svc = RaftGroupService(GROUP, self.me, opts, manager, self.transport)
        self.node = await svc.start()

    async def stop(self) -> None:
        if self.node:
            await self.node.shutdown()
        await self.transport.close()
        await self.server.stop()


async def demo(n: int = 3, verbose: bool = True) -> tuple[str, str]:
    """Start n members, observe a leader emerge, kill it, observe the
    next. Returns (first_leader, second_leader) endpoints."""
    def say(*a):
        if verbose:
            print(*a)

    ports = []
    for _ in range(n):
        srv = TcpRpcServer("127.0.0.1:0")
        await srv.start()
        ports.append(srv.bound_port)
        await srv.stop()
    peers = [PeerId.parse(f"127.0.0.1:{p}") for p in ports]
    conf = Configuration(list(peers))
    members = []
    for p in peers:
        fsm = ElectionOnlyStateMachine(
            on_start=lambda term, p=p: say(f"  {p} became leader (term {term})"),
            on_stop=lambda p=p: say(f"  {p} lost leadership"))
        m = ElectionNode(p, conf, fsm, election_timeout_ms=400)
        await m.start()
        members.append(m)

    async def wait_leader() -> ElectionNode:
        for _ in range(600):
            live = [m for m in members if m.node]
            leaders = [m for m in live if m.node.is_leader()]
            if len(leaders) == 1:
                return leaders[0]
            await asyncio.sleep(0.02)
        raise TimeoutError("no leader")

    try:
        first = await wait_leader()
        say(f"leader: {first.me}")
        say("killing it ...")
        dead = first.me
        await first.stop()
        members.remove(first)
        second = await wait_leader()
        say(f"new leader: {second.me}")
        assert second.me != dead
        return str(dead), str(second.me)
    finally:
        for m in members:
            await m.stop()


async def _serve(args) -> None:
    conf = Configuration.parse(args.peers)
    me = PeerId.parse(args.serve)
    fsm = ElectionOnlyStateMachine(
        on_start=lambda term: print(f"*** I ({me}) am leader, term={term}"),
        on_stop=lambda: print(f"*** I ({me}) lost leadership"))
    node = ElectionNode(me, conf, fsm)
    await node.start()
    print(f"election member {me} up")
    try:
        while True:
            await asyncio.sleep(3600)
    finally:
        await node.stop()


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--serve", help="ip:port to serve as a member")
    ap.add_argument("--peers", help="comma-separated cluster conf")
    args = ap.parse_args()
    if args.serve:
        asyncio.run(_serve(args))
    else:
        asyncio.run(demo())


if __name__ == "__main__":
    main()
