"""Multi-process RheaKV cluster supervisor: real OS processes per store.

The process-fabric half of the serving plane: every store (and,
optionally, every PD member) runs as its own OS process — its own
CPython, its own GIL, its own event loop — started from
``examples.rheakv_server`` / ``examples.pd_server`` mains.  This is the
topology the paper's deployment section assumes (one store per host),
and the one every committed cross-process bench row uses: a
single-process multi-store loop shares one interpreter, so its numbers
carry a "client and servers contend for one core" asterisk that this
fabric retires.

Pieces:

- :class:`StoreProcess` — one supervised child: spawn, READY-line
  readiness probe, SIGTERM drain / SIGKILL crash, exit reaping,
  ``/proc/<pid>/stat`` CPU attribution, ``/metrics`` scrape.
- :class:`ProcSupervisor` — a set of StoreProcesses with crash
  detection and supervised restart (exponential backoff), plus
  cluster-wide readiness / drain / stop.
- ``--soak`` CLI — a short chaos soak: concurrent client load, leader
  SIGKILL mid-run, supervised restart, and the recorded client history
  checked linearizable (``tpuraft.util.linearizability``).

Tests wrap this through ``tests/proc_cluster.py`` (ephemeral ports +
pytest teardown); benches through ``examples/rheakv_bench_multiproc``.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.request
from collections import deque
from typing import Optional

_CLK_TCK = os.sysconf("SC_CLK_TCK") if hasattr(os, "sysconf") else 100


def free_endpoints(n: int, host: str = "127.0.0.1") -> list[str]:
    """Reserve ``n`` distinct free ports and return host:port endpoints.

    The sockets are closed before the children bind — the usual
    best-effort race every multi-process test harness accepts (ports
    come from the ephemeral range; collisions surface as a failed
    READY probe, not silent misbehavior)."""
    socks = []
    try:
        for _ in range(n):
            s = socket.socket()
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind((host, 0))
            socks.append(s)
        return [f"{host}:{s.getsockname()[1]}" for s in socks]
    finally:
        for s in socks:
            s.close()


# graftcheck: loop-confined — the reader thread only ever touches the
# threading primitives (ready Event, tail deque, info dict assignment);
# all process control and asyncio integration happen on the caller's
# loop via run_in_executor
class StoreProcess:
    """One supervised server child (a store, or a PD member).

    ``argv`` is the full child command line (``sys.executable -m ...``
    is prepended by the caller via :func:`server_argv` /
    :func:`pd_argv`).  stdout is line-buffered into a diagnostic tail;
    a ``READY {json}`` line arms the readiness event, ``DRAINED
    {json}`` records the drain verdict.
    """

    def __init__(self, endpoint: str, argv: list[str],
                 name: Optional[str] = None, tail_lines: int = 60):
        self.endpoint = endpoint
        self.name = name or endpoint
        self.argv = list(argv)
        self.proc: Optional[subprocess.Popen] = None
        self.ready = threading.Event()
        self.info: dict = {}          # parsed READY payload
        self.drained: Optional[dict] = None   # parsed DRAINED payload
        self.tail: deque[str] = deque(maxlen=tail_lines)
        self.spawns = 0
        self._reader: Optional[threading.Thread] = None
        self._t0 = 0.0

    # -- lifecycle -------------------------------------------------------

    def spawn(self) -> None:
        assert self.proc is None or self.proc.poll() is not None
        self.ready.clear()
        self.drained = None
        self.info = {}
        self.spawns += 1
        self._t0 = time.monotonic()
        self.proc = subprocess.Popen(
            self.argv, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, bufsize=1, cwd=os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))))
        self._reader = threading.Thread(
            target=self._read_stdout, args=(self.proc,),
            name=f"stdout-{self.name}", daemon=True)
        self._reader.start()

    def _read_stdout(self, proc: subprocess.Popen) -> None:
        for line in proc.stdout:   # EOF on child exit
            line = line.rstrip("\n")
            self.tail.append(line)
            if line.startswith("READY "):
                try:
                    self.info = json.loads(line[len("READY "):])
                except ValueError:
                    self.info = {}
                self.ready.set()
            elif line.startswith("DRAINED "):
                try:
                    self.drained = json.loads(line[len("DRAINED "):])
                except ValueError:
                    self.drained = {"clean": False}
        proc.stdout.close()

    @property
    def pid(self) -> Optional[int]:
        return self.proc.pid if self.proc is not None else None

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def returncode(self) -> Optional[int]:
        return self.proc.poll() if self.proc is not None else None

    async def wait_ready(self, timeout_s: float = 30.0) -> dict:
        """Await the child's READY line (readiness probe: client traffic
        must not be pointed at a store that has not printed it)."""
        loop = asyncio.get_running_loop()
        ok = await loop.run_in_executor(
            None, self.ready.wait, timeout_s)
        if not ok:
            raise TimeoutError(
                f"{self.name}: no READY within {timeout_s}s "
                f"(rc={self.returncode()}, tail={list(self.tail)[-5:]})")
        return self.info

    def terminate(self) -> None:
        """SIGTERM: the child drains (in-flight acks, new work bounced)
        and exits 0."""
        if self.alive():
            self.proc.send_signal(signal.SIGTERM)

    def kill(self) -> None:
        """SIGKILL: crash-stop, no drain — the supervised-restart path."""
        if self.alive():
            self.proc.kill()

    async def wait_exit(self, timeout_s: float = 30.0) -> int:
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            None, self.proc.wait, timeout_s)

    # -- observability ---------------------------------------------------

    def cpu_seconds(self) -> Optional[float]:
        """utime+stime burned by THIS child (``/proc/<pid>/stat``) —
        the per-store CPU attribution the committed bench rows carry."""
        if not self.alive():
            return None
        try:
            with open(f"/proc/{self.proc.pid}/stat") as f:
                fields = f.read().rsplit(") ", 1)[1].split()
            # fields[11]/[12] are utime/stime (post-comm offsets 14/15)
            return (int(fields[11]) + int(fields[12])) / _CLK_TCK
        except (OSError, IndexError, ValueError):
            return None

    def scrape_metrics(self) -> dict[str, float]:
        """Blocking GET /metrics on the child's ephemeral metrics port
        (from its READY payload), parsed into {name: value}.  Call via
        run_in_executor from async code."""
        port = self.info.get("metrics_port")
        if not port:
            return {}
        out: dict[str, float] = {}
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5.0) as resp:
            for raw in resp.read().decode().splitlines():
                if not raw or raw.startswith("#"):
                    continue
                name, _, val = raw.rpartition(" ")
                try:
                    out[name] = float(val)
                except ValueError:
                    continue
        return out


def server_argv(endpoint: str, stores: list[str], regions: int, data: str,
                transport: str = "tcp", store: str = "memory",
                log_scheme: str = "file", pd: str = "",
                eto_ms: int = 1000, apply_lane: bool = False,
                engine: bool = False,
                drain_timeout_s: float = 10.0, boot_delay_s: float = 0.0,
                metrics_port: Optional[int] = 0) -> list[str]:
    """Command line for one ``examples.rheakv_server`` child."""
    argv = [sys.executable, "-m", "examples.rheakv_server",
            "--serve", endpoint, "--stores", ",".join(stores),
            "--regions", str(regions), "--data", data,
            "--transport", transport, "--store", store,
            "--log-scheme", log_scheme,
            "--eto-ms", str(eto_ms),
            "--drain-timeout", str(drain_timeout_s)]
    if pd:
        argv += ["--pd", pd]
    if apply_lane:
        argv += ["--apply-lane"]
    if engine:
        argv += ["--engine"]
    if boot_delay_s:
        argv += ["--boot-delay", str(boot_delay_s)]
    if metrics_port is not None:
        argv += ["--metrics-port", str(metrics_port)]
    return argv


def pd_argv(endpoint: str, pd_endpoints: list[str], data: str,
            transport: str = "tcp", seed_regions: int = 0,
            split_keys: int = 0) -> list[str]:
    """Command line for one ``examples.pd_server`` child."""
    argv = [sys.executable, "-m", "examples.pd_server",
            "--serve", endpoint, "--pd", ",".join(pd_endpoints),
            "--data", data, "--transport", transport]
    if seed_regions:
        argv += ["--seed-regions", str(seed_regions)]
    if split_keys:
        argv += ["--split-keys", str(split_keys)]
    return argv


# graftcheck: loop-confined — procs list and restart bookkeeping are
# touched only from the supervising event loop; the children are OS
# processes reached via signals
class ProcSupervisor:
    """A set of :class:`StoreProcess` children under one supervisor:
    spawn-all / ready-all / drain-all, crash detection, and supervised
    restart with exponential backoff (0.2s doubling to 2s) — the
    fabric's answer to SIGKILL: the store comes back, replays its raft
    log, and rejoins; nothing acked is lost.

    A restart-storm circuit breaker guards the respawn path: a child
    that crashes ``storm_threshold`` times inside a rolling
    ``storm_window_s`` window is marked FAILED (``self.failed``) and
    left down — crash loops (poisoned journal, full volume) need an
    operator, not another respawn."""

    #: restart-storm circuit breaker defaults: a child that crashes
    #: STORM_THRESHOLD times inside a rolling STORM_WINDOW_S window is
    #: marked FAILED and no longer respawned — a store crash-looping on
    #: a poisoned journal or a full volume otherwise burns CPU forever
    #: while masquerading as "supervised" in every scrape.
    STORM_THRESHOLD = 5
    STORM_WINDOW_S = 30.0

    def __init__(self, procs: list[StoreProcess],
                 storm_threshold: Optional[int] = None,
                 storm_window_s: Optional[float] = None):
        self.procs = list(procs)
        self.restarts = 0
        self.storm_threshold = (self.STORM_THRESHOLD
                                if storm_threshold is None
                                else storm_threshold)
        self.storm_window_s = (self.STORM_WINDOW_S
                               if storm_window_s is None
                               else storm_window_s)
        self.failed: dict[str, str] = {}   # endpoint -> reason
        self._watch: Optional[asyncio.Task] = None
        self._stopping = False
        self._backoff: dict[str, float] = {}
        self._crash_times: dict[str, deque[float]] = {}

    def by_endpoint(self, endpoint: str) -> StoreProcess:
        for p in self.procs:
            if p.endpoint == endpoint:
                return p
        raise KeyError(endpoint)

    async def start(self, ready_timeout_s: float = 30.0) -> None:
        for p in self.procs:
            p.spawn()
        await self.wait_all_ready(ready_timeout_s)

    async def wait_all_ready(self, timeout_s: float = 30.0) -> None:
        await asyncio.gather(*(p.wait_ready(timeout_s)
                               for p in self.procs))

    def supervise(self) -> None:
        """Arm the crash watcher: any child that exits while the
        supervisor is not stopping gets respawned after backoff."""
        if self._watch is None or self._watch.done():
            self._watch = asyncio.ensure_future(self._watch_loop())

    async def _watch_loop(self) -> None:
        try:
            while not self._stopping:
                for p in self.procs:
                    if p.endpoint in self.failed:
                        continue
                    if p.proc is not None and not p.alive():
                        now = time.monotonic()
                        crashes = self._crash_times.setdefault(
                            p.endpoint, deque())
                        crashes.append(now)
                        while crashes and \
                                now - crashes[0] > self.storm_window_s:
                            crashes.popleft()
                        if len(crashes) >= self.storm_threshold:
                            reason = (f"{len(crashes)} crashes in "
                                      f"{self.storm_window_s:.0f}s "
                                      f"(last rc={p.returncode()})")
                            self.failed[p.endpoint] = reason
                            print(f"supervisor: {p.name} FAILED — "
                                  f"restart storm: {reason}; not "
                                  f"respawning", flush=True)
                            continue
                        delay = self._backoff.get(p.endpoint, 0.2)
                        self._backoff[p.endpoint] = min(delay * 2, 2.0)
                        self.restarts += 1
                        print(f"supervisor: {p.name} exited "
                              f"rc={p.returncode()}; restarting in "
                              f"{delay:.1f}s", flush=True)
                        await asyncio.sleep(delay)
                        if self._stopping:
                            return
                        p.spawn()
                await asyncio.sleep(0.1)
        except asyncio.CancelledError:
            return

    async def stop(self, drain_timeout_s: float = 15.0) -> None:
        """SIGTERM everything (clean drain), SIGKILL stragglers."""
        self._stopping = True
        if self._watch is not None:
            self._watch.cancel()
            self._watch = None
        for p in self.procs:
            p.terminate()
        deadline = time.monotonic() + drain_timeout_s

        async def reap(p: StoreProcess) -> None:
            if p.proc is None:
                return
            try:
                await p.wait_exit(max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
                await p.wait_exit(5.0)

        await asyncio.gather(*(reap(p) for p in self.procs))

    def cpu_seconds(self) -> dict[str, Optional[float]]:
        return {p.name: p.cpu_seconds() for p in self.procs}

    async def scrape_all(self) -> dict[str, dict[str, float]]:
        loop = asyncio.get_running_loop()

        async def one(p: StoreProcess):
            try:
                return p.name, await loop.run_in_executor(
                    None, p.scrape_metrics)
            except Exception:  # noqa: BLE001 — scrape is best-effort
                return p.name, {}

        out = dict(await asyncio.gather(
            *(one(p) for p in self.procs if p.alive())))
        # circuit-broken children are still part of the fleet view: a
        # FAILED store scrapes as a sentinel row, not a silent absence
        for p in self.procs:
            if p.endpoint in self.failed:
                out[p.name] = {"proc_supervisor_failed": 1.0}
        return out


# ---------------------------------------------------------------------------
# --soak: short multi-process chaos soak (leader SIGKILL + supervised
# restart under concurrent load, history checked linearizable)
# ---------------------------------------------------------------------------

async def _soak(seconds: float, stores_n: int, regions: int, data: str,
                transport: str, apply_lane: bool,
                engine: bool = False) -> int:
    from examples.rheakv_server import client_for
    from tpuraft.util.linearizability import History, check_history

    endpoints = free_endpoints(stores_n)
    sup = ProcSupervisor([
        StoreProcess(ep, server_argv(
            ep, endpoints, regions, data, transport=transport,
            eto_ms=500, apply_lane=apply_lane, engine=engine,
            metrics_port=None))
        for ep in endpoints])
    await sup.start()
    sup.supervise()
    if transport == "native":
        from tpuraft.rpc.native_tcp import NativeTcpTransport
        tp = NativeTcpTransport()
    else:
        from tpuraft.rpc.tcp import TcpTransport
        tp = TcpTransport()
    kv = client_for(endpoints, regions, transport=tp, max_retries=12)
    await kv.start()

    h = History()
    stop = asyncio.Event()
    keys = [b"soak-%d" % i for i in range(4)]

    async def worker(cid: int) -> None:
        n = 0
        while not stop.is_set():
            n += 1
            key = keys[n % len(keys)]
            if n % 2 == 0:
                val = b"c%d-%d" % (cid, n)
                tok = h.invoke(cid, "w", (key, val))
                try:
                    await asyncio.wait_for(kv.put(key, val), 6.0)
                    h.complete(tok, True)
                except Exception:  # noqa: BLE001 — indeterminate op
                    pass
            else:
                tok = h.invoke(cid, "r", (key,))
                try:
                    v = await asyncio.wait_for(kv.get(key), 6.0)
                    h.complete(tok, v)
                except Exception:  # noqa: BLE001 — indeterminate op
                    pass
            await asyncio.sleep(0.003)

    workers = [asyncio.ensure_future(worker(i)) for i in range(4)]
    await asyncio.sleep(max(1.0, seconds / 3))
    # SIGKILL whichever store the client believes leads region 1 (fall
    # back to the first store): crash-stop, then the supervisor's
    # restart brings it back and raft-log replay restores it
    victim_peer = kv._leaders.get(1)
    victim_ep = ":".join(victim_peer.split("/", 1)[0].split(":")[:2]) \
        if victim_peer else endpoints[0]
    victim = sup.by_endpoint(victim_ep)
    print(f"soak: SIGKILL leader store {victim_ep}", flush=True)
    victim.kill()
    await asyncio.sleep(max(1.0, seconds / 3))
    await victim.wait_ready(30.0)      # supervised restart came back
    await asyncio.sleep(max(1.0, seconds / 3))
    stop.set()
    await asyncio.gather(*workers)

    ops = h.ops()
    done = sum(1 for o in ops if o.ret is not None)
    rep = check_history(h)
    cpu = sup.cpu_seconds()
    await kv.shutdown()
    await tp.close()
    await sup.stop()
    print(json.dumps({
        "soak_seconds": seconds, "stores": stores_n, "regions": regions,
        "ops_total": len(ops), "ops_done": done,
        "restarts": sup.restarts,
        "failed_stores": dict(sup.failed),
        "linearizable": bool(rep.ok),
        "cpu_seconds": cpu}, indent=2), flush=True)
    if not rep.ok:
        print(f"HISTORY NOT LINEARIZABLE: {rep}", file=sys.stderr)
        return 1
    if done < 50:
        print(f"too few completed ops: {done}", file=sys.stderr)
        return 1
    return 0


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--soak", action="store_true",
                    help="run the multi-process chaos soak")
    ap.add_argument("--seconds", type=float, default=9.0)
    ap.add_argument("--stores", type=int, default=3)
    ap.add_argument("--regions", type=int, default=2)
    ap.add_argument("--data", default="/tmp/tpuraft-proc-soak")
    ap.add_argument("--transport", choices=["tcp", "native"],
                    default="tcp")
    ap.add_argument("--apply-lane", action="store_true")
    ap.add_argument("--engine", action="store_true",
                    help="children drive their region nodes from ONE "
                         "MultiRaftEngine each (fused [G] tick) instead "
                         "of per-node timers")
    args = ap.parse_args()
    if not args.soak:
        ap.error("nothing to do (pass --soak)")
    import shutil
    shutil.rmtree(args.data, ignore_errors=True)
    rc = asyncio.run(_soak(args.seconds, args.stores, args.regions,
                           args.data, args.transport, args.apply_lane,
                           engine=args.engine))
    sys.exit(rc)


if __name__ == "__main__":
    main()
