"""Admin CLI: cluster operations against a live raft group over TCP.

Reference parity: the operator surface of ``CliService`` (SURVEY.md
§3.1 "CLI service & processors") as a command-line tool, the way the
reference's jraft-example tooling drives CliServiceImpl.

    python -m examples.admin --group counter \\
        --peers 127.0.0.1:8081,127.0.0.1:8082,127.0.0.1:8083 <command>

Commands:
    leader                    print the current leader
    peers                     print voters (and learners)
    snapshot <peer>           trigger an on-demand snapshot on <peer>
    transfer <peer>           transfer leadership to <peer>
    add-peer <peer>           add a voter
    remove-peer <peer>        remove a voter
    add-witness <peer>        add a WITNESS voter (votes, stores no
                              log payload, never leads — geo 2+1)
    remove-witness <peer>     remove a witness voter
    change-peers <p1,p2,...>  arbitrary membership change (tokens may
                              carry /witness or /learner suffixes)
    add-learners <p1,...>     add read-only replicas
    remove-learners <p1,...>  remove read-only replicas
    reset-learners <p1,...>   replace the learner set atomically
    reset-learners none       clear the learner set
    metrics [endpoint]        scrape live metrics (Prometheus text)
                              from one store (default: first peer that
                              answers) over the admin transport
"""

from __future__ import annotations

import argparse
import asyncio
import sys

from tpuraft.conf import Configuration
from tpuraft.core.cli_service import CliService, describe_status
from tpuraft.entity import PeerId
from tpuraft.errors import RaftError
from tpuraft.rpc.tcp import TcpTransport


def _report(st) -> int:
    """Print the op outcome; exit 0 = done, 3 = busy (safe to just
    retry), 1 = definite failure (inspect before retrying)."""
    if st.is_ok():
        print("OK")
        return 0
    print(describe_status(st), file=sys.stderr)
    return 3 if st.raft_error == RaftError.EBUSY else 1


async def run(args) -> int:
    from tpuraft.rpc.transport import RpcError

    try:
        conf = Configuration.parse(args.peers)
    except ValueError as e:
        print(f"error: bad --peers: {e}", file=sys.stderr)
        return 2
    transport = TcpTransport()
    cli = CliService(transport)
    rc = 0
    try:
        cmd = args.command[0]
        if cmd == "leader":
            leader = await cli.get_leader(args.group, conf)
            if leader is None:
                print("error: no leader found")
                return 1
            print(leader)
        elif cmd == "peers":
            full = await cli.get_configuration(args.group, conf)
            print("voters:", ",".join(
                f"{p}/witness" if full.is_witness(p) else str(p)
                for p in full.peers))
            if full.learners:
                print("learners:", ",".join(str(p) for p in full.learners))
        elif cmd == "metrics":
            targets = ([args.command[1]] if len(args.command) > 1
                       else [p.endpoint for p in conf.list_all()])
            last_err = None
            for ep in targets:
                # a bare endpoint or a PeerId string both work
                ep = ":".join(ep.split("/", 1)[0].split(":")[:2])
                try:
                    print(await cli.describe_metrics(ep), end="")
                    break
                except RpcError as e:
                    last_err = e
            else:
                print(f"error: no store answered describe_metrics: "
                      f"{last_err.status if last_err else '?'}",
                      file=sys.stderr)
                rc = 1
        elif cmd in ("snapshot", "transfer", "add-peer", "remove-peer",
                     "add-witness", "remove-witness"):
            if len(args.command) < 2:
                print(f"{cmd} needs a peer argument", file=sys.stderr)
                return 2
            peer = PeerId.parse(args.command[1])
            if cmd == "snapshot":
                st = await cli.snapshot(args.group, peer)
            elif cmd == "transfer":
                st = await cli.transfer_leader(args.group, conf, peer)
            elif cmd == "add-peer":
                st = await cli.add_peer(args.group, conf, peer)
            elif cmd == "add-witness":
                st = await cli.add_witness(args.group, conf, peer)
            elif cmd == "remove-witness":
                st = await cli.remove_witness(args.group, conf, peer)
            else:
                st = await cli.remove_peer(args.group, conf, peer)
            rc = _report(st)
        elif cmd == "change-peers":
            if len(args.command) < 2:
                print("change-peers needs a conf argument", file=sys.stderr)
                return 2
            new_conf = Configuration.parse(args.command[1])
            st = await cli.change_peers(args.group, conf, new_conf)
            rc = _report(st)
        elif cmd in ("add-learners", "remove-learners", "reset-learners"):
            if len(args.command) < 2:
                print(f"{cmd} needs a peer-list argument "
                      "('none' clears the set for reset-learners)",
                      file=sys.stderr)
                return 2
            arg = args.command[1]
            clear = arg in ("none", "") and cmd == "reset-learners"
            learners = ([] if clear else
                        [PeerId.parse(t) for t in arg.split(",") if t])
            if not learners and not clear:
                print(f"{cmd} needs at least one peer", file=sys.stderr)
                return 2
            op = {"add-learners": cli.add_learners,
                  "remove-learners": cli.remove_learners,
                  "reset-learners": cli.reset_learners}[cmd]
            st = await op(args.group, conf, learners)
            rc = _report(st)
        else:
            print(f"unknown command: {cmd}", file=sys.stderr)
            rc = 2
    except RpcError as e:
        print(f"error: {e.status}", file=sys.stderr)
        rc = 1
    except ValueError as e:  # malformed peer argument
        print(f"error: {e}", file=sys.stderr)
        rc = 2
    finally:
        await transport.close()
    return rc


def main() -> None:
    ap = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--group", required=True, help="raft group id")
    ap.add_argument("--peers", required=True,
                    help="comma-separated cluster conf (ip:port,...)")
    ap.add_argument("command", nargs="+",
                    help="leader | peers | snapshot <peer> | transfer <peer>"
                         " | add-peer <peer> | remove-peer <peer>"
                         " | add-witness <peer> | remove-witness <peer>"
                         " | change-peers <p1,p2,...>"
                         " | add-learners <p1,...> | remove-learners <p1,...>"
                         " | reset-learners <p1,...> | metrics [endpoint]")
    sys.exit(asyncio.run(run(ap.parse_args())))


if __name__ == "__main__":
    main()
