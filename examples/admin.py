"""Admin CLI: cluster operations against a live raft group over TCP.

Reference parity: the operator surface of ``CliService`` (SURVEY.md
§3.1 "CLI service & processors") as a command-line tool, the way the
reference's jraft-example tooling drives CliServiceImpl.

    python -m examples.admin --group counter \\
        --peers 127.0.0.1:8081,127.0.0.1:8082,127.0.0.1:8083 <command>

Commands:
    leader                    print the current leader
    peers                     print voters (and learners)
    snapshot <peer>           trigger an on-demand snapshot on <peer>
    transfer <peer>           transfer leadership to <peer>
    add-peer <peer>           add a voter
    remove-peer <peer>        remove a voter
    add-witness <peer>        add a WITNESS voter (votes, stores no
                              log payload, never leads — geo 2+1)
    remove-witness <peer>     remove a witness voter
    change-peers <p1,p2,...>  arbitrary membership change (tokens may
                              carry /witness or /learner suffixes)
    add-learners <p1,...>     add read-only replicas
    remove-learners <p1,...>  remove read-only replicas
    reset-learners <p1,...>   replace the learner set atomically
    reset-learners none       clear the learner set
    metrics [endpoint]        scrape live metrics (Prometheus text)
                              from one store (default: first peer that
                              answers) over the admin transport
    storage [endpoint]        disk-pressure dashboard: per-store disk
                              usage, pressure level, ENOSPC count and
                              the reclaim/shed/resume counters, parsed
                              from the same metrics plane (default:
                              every peer; docs/operations.md
                              "Disk-pressure runbook")
    clocks [endpoint]         clock-discipline dashboard: per-store
                              sentinel verdict (OK / SUSPECT), worst
                              estimated peer skew, fenced-lease count
                              and the per-peer skew estimates the beat
                              probes produced (default: every peer;
                              docs/operations.md "Clock discipline
                              runbook")

PD (fleet) commands take --pd instead of --group/--peers:
    cluster [K]               print the PD leader's ClusterView: top-K
                              hot/cold regions, per-zone rates, store
                              health roster, hibernation fraction
    regions                   per-region lifecycle view: keyspace range,
                              epoch (version/conf_ver), leader, heat
                              score and replica placement for EVERY
                              region, plus pending merges and the PD's
                              recent lifecycle decisions (heat splits /
                              cold merges / cross-store moves;
                              docs/operations.md "Region lifecycle
                              runbook")
    pd-metrics                scrape the PD leader's Prometheus text
"""

from __future__ import annotations

import argparse
import asyncio
import sys

from tpuraft.conf import Configuration
from tpuraft.core.cli_service import CliService, describe_status
from tpuraft.entity import PeerId
from tpuraft.errors import RaftError
from tpuraft.rpc.tcp import TcpTransport


def _report(st) -> int:
    """Print the op outcome; exit 0 = done, 3 = busy (safe to just
    retry), 1 = definite failure (inspect before retrying)."""
    if st.is_ok():
        print("OK")
        return 0
    print(describe_status(st), file=sys.stderr)
    return 3 if st.raft_error == RaftError.EBUSY else 1


def _print_cluster_view(view: dict) -> None:
    hib = view.get("hibernation", {})
    print(f"cluster: {view.get('regions', 0)} regions, "
          f"{len(view.get('stores', []))} stores, "
          f"hibernation {hib.get('quiescent', 0)}/"
          f"{hib.get('replicas', 0)} "
          f"({100.0 * hib.get('fraction', 0.0):.1f}%), "
          f"pd term {view.get('term', 0)}")
    for s in view.get("stores", []):
        health = s.get("health") or "healthy?"
        zone = s.get("zone") or "-"
        print(f"  store {s['endpoint']:<22} zone={zone:<10} "
              f"health={health:<9} leaders={s.get('leaders', 0):<5} "
              f"quiescent={s.get('replicas_quiescent', 0)}/"
              f"{s.get('replicas', 0)}")
    for z, zr in sorted(view.get("zone_rates", {}).items()):
        print(f"  zone {z or '-':<10} writes/s={zr.get('writes_s', 0)} "
              f"reads/s={zr.get('reads_s', 0)}")
    if view.get("sick_stores"):
        print("  SICK stores:", ", ".join(view["sick_stores"]))
    for title, key in (("hot", "hot"), ("cold", "cold")):
        rows = view.get(key, [])
        if not rows:
            continue
        print(f"  {title} regions:")
        for r in rows:
            flag = " HOT" if r["region"] in view.get("hot_flagged", []) \
                else ""
            print(f"    region {r['region']:<8} score={r['score']:<8} "
                  f"w/s={r['writes_s']:<7} r/s={r['reads_s']:<7} "
                  f"keys={r['keys']:<8} leader={r['leader']}{flag}")


def _prom_values(text: str) -> dict:
    """Flatten Prometheus exposition text to {metric_name: value},
    ignoring labels (the admin scrape targets one store at a time)."""
    vals: dict = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        try:
            name_part, value = line.rsplit(None, 1)
            vals[name_part.split("{", 1)[0]] = float(value)
        except ValueError:
            continue
    return vals


def _fmt_key(k: bytes, end: bool = False) -> str:
    if not k:
        # an empty key means -inf as a start bound, +inf as an end bound
        return "+inf" if end else "-inf"
    try:
        return k.decode("ascii")
    except UnicodeDecodeError:
        return k.hex()


def _print_regions_view(regions: list, view: dict) -> None:
    heat = {r["region"]: r
            for r in view.get("hot", []) + view.get("cold", [])}
    hot_flagged = set(view.get("hot_flagged", []))
    leaders = {r["region"]: r.get("leader", "") for r in heat.values()}
    lc = view.get("lifecycle")
    pending = (lc or {}).get("pending_merges", {})
    print(f"regions: {len(regions)} "
          f"(lifecycle {'ON' if lc is not None else 'off'}, "
          f"{len(pending)} pending merge(s))")
    for r in sorted(regions, key=lambda r: r.start_key):
        h = heat.get(r.id)
        score = f"{h['score']:<6}" if h else "-     "
        rates = (f"w/s={h['writes_s']:<7} r/s={h['reads_s']:<7} "
                 f"keys={h['keys']:<7}" if h
                 else "w/s=-       r/s=-       keys=-      ")
        flags = ""
        if r.id in hot_flagged:
            flags += " HOT"
        if str(r.id) in pending or r.id in pending:
            flags += f" MERGING->{pending.get(str(r.id), pending.get(r.id))}"
        print(f"  region {r.id:<8} "
              f"[{_fmt_key(r.start_key)} .. "
              f"{_fmt_key(r.end_key, end=True)}) "
              f"v{r.epoch.version}/c{r.epoch.conf_ver} "
              f"leader={leaders.get(r.id, '') or '?':<22} "
              f"score={score} {rates}{flags}")
        print(f"    peers: {', '.join(r.peers) or '-'}")
    if lc is None:
        print("  (lifecycle engine off or pre-lifecycle PD: no "
              "placement decisions to show)")
        return
    print(f"  actuations: heat_splits={lc.get('heat_splits_ordered', 0)} "
          f"merges={lc.get('merges_completed', 0)}"
          f"/{lc.get('merges_ordered', 0)} ordered "
          f"moves={lc.get('moves_ordered', 0)} "
          f"retired={lc.get('retired_regions', 0)}")
    recent = lc.get("recent", [])
    if recent:
        print("  recent decisions (oldest first):")
        for d in recent:
            extra = {k: v for k, v in d.items()
                     if k not in ("kind", "term", "region")}
            detail = " ".join(f"{k}={v}" for k, v in extra.items())
            print(f"    term {d.get('term', '?'):<4} "
                  f"{d.get('kind', '?'):<11} region {d.get('region', '?')}"
                  f"  {detail}")


_PRESSURE_NAMES = {0: "OK", 1: "NEAR_FULL", 2: "FULL"}


def _print_storage_row(ep: str, vals: dict) -> None:
    def v(name, default=0.0):
        return vals.get("tpuraft_" + name, default)

    if "tpuraft_disk_capacity_bytes" not in vals:
        print(f"  store {ep:<22} disk guard off (no disk_* metrics)")
        return
    used = v("disk_used_bytes")
    cap = v("disk_capacity_bytes")
    pct = f"{100.0 * used / cap:.1f}%" if cap > 0 else "-"
    level = _PRESSURE_NAMES.get(int(v("disk_pressure_level")), "?")
    print(f"  store {ep:<22} {level:<9} "
          f"used={int(used)}/{int(cap)}B ({pct})")
    print(f"    enospc={int(v('disk_enospc_events')):<6} "
          f"reclaims={int(v('disk_reclaims')):<5} "
          f"reclaim_rounds={int(v('disk_reclaim_rounds')):<5} "
          f"shed_writes={int(v('kv_disk_shed_items')):<6} "
          f"resumes={int(v('disk_pressure_resumes'))}")
    print(f"    rounds: near_full={int(v('disk_near_full_rounds'))} "
          f"full={int(v('disk_full_rounds'))} "
          f"reconciles={int(v('disk_reconciles'))}  bytes: "
          f"appended={int(v('disk_appended_bytes'))} "
          f"reclaimed={int(v('disk_reclaimed_bytes'))}")


_PEER_SKEW_PREFIX = "tpuraft_clock_peer_skew_s_"


def _print_clock_row(ep: str, vals: dict) -> None:
    if "tpuraft_clock_suspect" not in vals:
        print(f"  store {ep:<22} no clock sentinel "
              f"(pre-time-chaos build)")
        return
    suspect = vals["tpuraft_clock_suspect"] > 0
    verdict = "SUSPECT" if suspect else "OK"
    print(f"  store {ep:<22} {verdict:<8} "
          f"max|skew|={vals.get('tpuraft_clock_max_abs_skew_s', 0.0):.3f}s "
          f"leases_fenced={int(vals.get('tpuraft_clock_lease_fenced', 0))}")
    # per-peer estimates (gauge names carry the sanitized peer
    # endpoint: tpuraft_clock_peer_skew_s_127_0_0_1_6301)
    for name, v in sorted(vals.items()):
        if name.startswith(_PEER_SKEW_PREFIX):
            peer = name[len(_PEER_SKEW_PREFIX):]
            print(f"    peer {peer:<24} skew={v:+.3f}s")


async def _run_pd(args) -> int:
    """PD-targeted commands (``--pd`` endpoints, no raft group conf)."""
    import json

    from tpuraft.rheakv.pd_client import RemotePlacementDriverClient
    from tpuraft.rpc.transport import RpcError

    transport = TcpTransport()
    pd = RemotePlacementDriverClient(
        transport, [e for e in args.pd.split(",") if e])
    cmd = args.command[0]
    try:
        if cmd == "cluster":
            top_k = int(args.command[1]) if len(args.command) > 1 else 8
            view = await pd.cluster_describe(top_k=top_k)
            if view is None:
                print("error: PD does not serve pd_cluster_describe "
                      "(pre-observability build)", file=sys.stderr)
                return 1
            if args.json:
                print(json.dumps(view, indent=1))
            else:
                _print_cluster_view(view)
        elif cmd == "regions":
            view = await pd.cluster_describe(top_k=64)
            if view is None:
                print("error: PD does not serve pd_cluster_describe "
                      "(pre-observability build)", file=sys.stderr)
                return 1
            regions = await pd.list_regions()
            if args.json:
                print(json.dumps({
                    "regions": [{
                        "id": r.id,
                        "start_key": _fmt_key(r.start_key),
                        "end_key": _fmt_key(r.end_key, end=True),
                        "version": r.epoch.version,
                        "conf_ver": r.epoch.conf_ver,
                        "peers": list(r.peers),
                    } for r in sorted(regions,
                                      key=lambda r: r.start_key)],
                    "lifecycle": view.get("lifecycle"),
                }, indent=1))
            else:
                _print_regions_view(regions, view)
        else:  # pd-metrics
            text = await pd.describe_metrics()
            if text is None:
                print("error: PD does not serve pd_describe_metrics "
                      "(pre-observability build)", file=sys.stderr)
                return 1
            print(text, end="")
        return 0
    except (RpcError, RuntimeError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    finally:
        await transport.close()


async def run(args) -> int:
    from tpuraft.rpc.transport import RpcError

    cmd0 = args.command[0]
    if cmd0 in ("cluster", "regions", "pd-metrics"):
        if not args.pd:
            print(f"{cmd0} needs --pd (comma-separated PD endpoints)",
                  file=sys.stderr)
            return 2
        return await _run_pd(args)
    if not args.group or not args.peers:
        print(f"{cmd0} needs --group and --peers", file=sys.stderr)
        return 2
    try:
        conf = Configuration.parse(args.peers)
    except ValueError as e:
        print(f"error: bad --peers: {e}", file=sys.stderr)
        return 2
    transport = TcpTransport()
    cli = CliService(transport)
    rc = 0
    try:
        cmd = args.command[0]
        if cmd == "leader":
            leader = await cli.get_leader(args.group, conf)
            if leader is None:
                print("error: no leader found")
                return 1
            print(leader)
        elif cmd == "peers":
            full = await cli.get_configuration(args.group, conf)
            print("voters:", ",".join(
                f"{p}/witness" if full.is_witness(p) else str(p)
                for p in full.peers))
            if full.learners:
                print("learners:", ",".join(str(p) for p in full.learners))
        elif cmd == "metrics":
            targets = ([args.command[1]] if len(args.command) > 1
                       else [p.endpoint for p in conf.list_all()])
            last_err = None
            for ep in targets:
                # a bare endpoint or a PeerId string both work
                ep = ":".join(ep.split("/", 1)[0].split(":")[:2])
                try:
                    print(await cli.describe_metrics(ep), end="")
                    break
                except RpcError as e:
                    last_err = e
            else:
                print(f"error: no store answered describe_metrics: "
                      f"{last_err.status if last_err else '?'}",
                      file=sys.stderr)
                rc = 1
        elif cmd == "storage":
            # disk-pressure dashboard: unlike `metrics` (first peer
            # that answers) this renders EVERY reachable store — the
            # operator question is "which store is under pressure",
            # not "what does one store say"
            targets = ([args.command[1]] if len(args.command) > 1
                       else [p.endpoint for p in conf.list_all()])
            answered = 0
            print(f"storage pressure ({len(targets)} store(s)):")
            for ep in targets:
                ep = ":".join(ep.split("/", 1)[0].split(":")[:2])
                try:
                    text = await cli.describe_metrics(ep)
                except RpcError as e:
                    print(f"  store {ep:<22} unreachable "
                          f"({e.status.raft_error.name})")
                    continue
                answered += 1
                _print_storage_row(ep, _prom_values(text))
            if not answered:
                print("error: no store answered describe_metrics",
                      file=sys.stderr)
                rc = 1
        elif cmd == "clocks":
            # clock-discipline dashboard: like `storage`, every
            # reachable store renders — the operator question is
            # "whose clock is off and by how much", answered by each
            # store's OWN sentinel estimate of its peers
            targets = ([args.command[1]] if len(args.command) > 1
                       else [p.endpoint for p in conf.list_all()])
            answered = 0
            print(f"clock discipline ({len(targets)} store(s)):")
            for ep in targets:
                ep = ":".join(ep.split("/", 1)[0].split(":")[:2])
                try:
                    text = await cli.describe_metrics(ep)
                except RpcError as e:
                    print(f"  store {ep:<22} unreachable "
                          f"({e.status.raft_error.name})")
                    continue
                answered += 1
                _print_clock_row(ep, _prom_values(text))
            if not answered:
                print("error: no store answered describe_metrics",
                      file=sys.stderr)
                rc = 1
        elif cmd in ("snapshot", "transfer", "add-peer", "remove-peer",
                     "add-witness", "remove-witness"):
            if len(args.command) < 2:
                print(f"{cmd} needs a peer argument", file=sys.stderr)
                return 2
            peer = PeerId.parse(args.command[1])
            if cmd == "snapshot":
                st = await cli.snapshot(args.group, peer)
            elif cmd == "transfer":
                st = await cli.transfer_leader(args.group, conf, peer)
            elif cmd == "add-peer":
                st = await cli.add_peer(args.group, conf, peer)
            elif cmd == "add-witness":
                st = await cli.add_witness(args.group, conf, peer)
            elif cmd == "remove-witness":
                st = await cli.remove_witness(args.group, conf, peer)
            else:
                st = await cli.remove_peer(args.group, conf, peer)
            rc = _report(st)
        elif cmd == "change-peers":
            if len(args.command) < 2:
                print("change-peers needs a conf argument", file=sys.stderr)
                return 2
            new_conf = Configuration.parse(args.command[1])
            st = await cli.change_peers(args.group, conf, new_conf)
            rc = _report(st)
        elif cmd in ("add-learners", "remove-learners", "reset-learners"):
            if len(args.command) < 2:
                print(f"{cmd} needs a peer-list argument "
                      "('none' clears the set for reset-learners)",
                      file=sys.stderr)
                return 2
            arg = args.command[1]
            clear = arg in ("none", "") and cmd == "reset-learners"
            learners = ([] if clear else
                        [PeerId.parse(t) for t in arg.split(",") if t])
            if not learners and not clear:
                print(f"{cmd} needs at least one peer", file=sys.stderr)
                return 2
            op = {"add-learners": cli.add_learners,
                  "remove-learners": cli.remove_learners,
                  "reset-learners": cli.reset_learners}[cmd]
            st = await op(args.group, conf, learners)
            rc = _report(st)
        else:
            print(f"unknown command: {cmd}", file=sys.stderr)
            rc = 2
    except RpcError as e:
        print(f"error: {e.status}", file=sys.stderr)
        rc = 1
    except ValueError as e:  # malformed peer argument
        print(f"error: {e}", file=sys.stderr)
        rc = 2
    finally:
        await transport.close()
    return rc


def main() -> None:
    ap = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--group", default="", help="raft group id")
    ap.add_argument("--peers", default="",
                    help="comma-separated cluster conf (ip:port,...)")
    ap.add_argument("--pd", default="",
                    help="comma-separated PD endpoints (for the "
                         "cluster / pd-metrics commands)")
    ap.add_argument("--json", action="store_true",
                    help="print the cluster view as raw JSON")
    ap.add_argument("command", nargs="+",
                    help="leader | peers | snapshot <peer> | transfer <peer>"
                         " | add-peer <peer> | remove-peer <peer>"
                         " | add-witness <peer> | remove-witness <peer>"
                         " | change-peers <p1,p2,...>"
                         " | add-learners <p1,...> | remove-learners <p1,...>"
                         " | reset-learners <p1,...> | metrics [endpoint]"
                         " | storage [endpoint] | clocks [endpoint]"
                         " | cluster [K] | pd-metrics")
    sys.exit(asyncio.run(run(ap.parse_args())))


if __name__ == "__main__":
    main()
