"""Standalone placement-driver server: one OS process per PD member.

Reference parity: ``pd:PlacementDriverServer`` bootable as its own
process (SURVEY.md §3.2 "PD server") — a 1-group raft app holding
cluster metadata, answering routing, and emitting split /
leader-balancing instructions from store heartbeats.

    python -m examples.pd_server --serve 127.0.0.1:9101 \\
        --pd 127.0.0.1:9101,127.0.0.1:9102,127.0.0.1:9103 \\
        --data /tmp/pd1 --split-keys 4096 [--balance-leaders]

Pair with ``examples.rheakv_server --pd ...`` stores: they heartbeat
region meta + stats here and execute the returned instructions.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import signal
import sys

from examples.rheakv_bench import make_regions
from tpuraft.rheakv.pd_server import (
    PlacementDriverOptions,
    PlacementDriverServer,
)


async def serve(endpoint: str, pd_endpoints: list[str], data_path: str,
                split_threshold_keys: int = 0,
                balance_leaders: bool = False,
                seed_regions: int = 0,
                transport_kind: str = "tcp",
                metrics_port: int | None = None,
                lifecycle: bool = False,
                lifecycle_min_regions: int = 4,
                lifecycle_merge_cooldown_s: float = 10.0) -> None:
    if transport_kind == "native":
        from tpuraft.rpc.native_tcp import NativeTcpRpcServer as Server
        from tpuraft.rpc.native_tcp import NativeTcpTransport as Transport
    else:
        from tpuraft.rpc.tcp import TcpRpcServer as Server
        from tpuraft.rpc.tcp import TcpTransport as Transport

    server = Server(endpoint)
    await server.start()
    transport = Transport(endpoint=endpoint)
    opts = PlacementDriverOptions(
        endpoints=list(pd_endpoints),
        data_path=data_path,
        split_threshold_keys=split_threshold_keys,
        balance_leaders=balance_leaders,
        initial_regions=make_regions(seed_regions) if seed_regions else [],
        metrics_port=metrics_port,
        lifecycle=lifecycle,
        lifecycle_min_regions=lifecycle_min_regions,
        lifecycle_merge_cooldown_s=lifecycle_merge_cooldown_s,
    )
    pd = PlacementDriverServer(opts, endpoint, server, transport)
    await pd.start()
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    try:
        loop.add_signal_handler(signal.SIGTERM, stop.set)
        loop.add_signal_handler(signal.SIGINT, stop.set)
    except NotImplementedError:   # non-unix event loop
        pass
    # machine-readable readiness line first (same supervisor contract as
    # examples.rheakv_server), the human line after
    print("READY " + json.dumps({
        "endpoint": endpoint, "pid": os.getpid(),
        "metrics_port": getattr(pd, "metrics_http_port", None)}),
        flush=True)
    print(f"pd member {endpoint} up ({len(pd_endpoints)}-member cluster)",
          flush=True)
    try:
        await stop.wait()
    finally:
        await pd.shutdown()
        await server.stop()
        await transport.close()


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--serve", required=True, help="this member's ip:port")
    ap.add_argument("--pd", required=True,
                    help="comma-separated PD cluster endpoints")
    ap.add_argument("--data", required=True)
    ap.add_argument("--split-keys", type=int, default=0,
                    help="auto-split threshold (0 = off)")
    ap.add_argument("--balance-leaders", action="store_true")
    ap.add_argument("--seed-regions", type=int, default=0,
                    help="pre-split the keyspace into N regions on first "
                         "boot (metadata only; stores attach via "
                         "heartbeats)")
    ap.add_argument("--transport", choices=["tcp", "native"], default="tcp")
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="serve PD Prometheus text at GET /metrics on "
                         "this port (0 = ephemeral; default off)")
    ap.add_argument("--lifecycle", action="store_true",
                    help="run the region lifecycle engine (heat splits, "
                         "cold merges, cross-store moves)")
    ap.add_argument("--lifecycle-min-regions", type=int, default=4,
                    help="never merge the fleet below this many regions")
    ap.add_argument("--lifecycle-merge-cooldown-s", type=float,
                    default=10.0,
                    help="per-region pause between ordered merges")
    args = ap.parse_args()
    pds = [e for e in args.pd.split(",") if e]
    if args.serve not in pds:
        print("error: --serve must be one of --pd", file=sys.stderr)
        sys.exit(2)
    try:
        asyncio.run(serve(
            args.serve, pds, args.data, args.split_keys,
            args.balance_leaders, args.seed_regions,
            args.transport, metrics_port=args.metrics_port,
            lifecycle=args.lifecycle,
            lifecycle_min_regions=args.lifecycle_min_regions,
            lifecycle_merge_cooldown_s=args.lifecycle_merge_cooldown_s))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
