"""Chaos soak runner: boots a KV cluster in-process, drives paced
client load under a nemesis fault schedule, then PROVES the recorded
history linearizable.

The reference's chaos tests assert convergence latches; this tool
records real invoke/return windows and checks them against a register
model (tpuraft.util.linearizability) — the strongest black-box verdict
a consensus store can get.

    python -m examples.soak --duration 60 --seed 7
    python -m examples.soak --duration 120 --stores 5 --keys 8 \\
        --data /tmp/soak --verbose

Faults: rolling store kill/restart, one-way partitions, packet
drops+delays — and, with ``--power-loss``, storage-plane crashes: a
store is killed at a random instant and restarted from its
durable-only on-disk image, with torn writes / lost fsyncs / bit flips
injected into the unsynced tails (tpuraft/storage/fault.py).  Durable
state dirs are required implicitly — a voter restarted without its
disk is amnesiac, which Raft does not tolerate (the divergence
detector would fail it loudly).
"""

from __future__ import annotations

import argparse
import asyncio
import random
import tempfile
import time

from tpuraft.rheakv.client import RheaKVStore
from tpuraft.rheakv.metadata import Region
from tpuraft.rheakv.pd_client import FakePlacementDriverClient
from tpuraft.rheakv.store_engine import StoreEngine, StoreEngineOptions
from tpuraft.rpc.transport import InProcNetwork, InProcTransport, RpcServer
from tpuraft.util.linearizability import History, check_history
from tpuraft.util.nemesis import NemesisAction, SkipFault, run_nemesis


class _BaseSoakCluster:
    """Shared cluster shape for both fabrics: a stores map, the region
    layout, option plumbing, and leader lookup."""

    read_only_option = None   # set by run_soak for lease-read mode
    snapshot_interval_secs = 0  # set by run_soak (power-loss soaks
    #                             snapshot so compaction runs under crashes)

    def __init__(self, data_path: str):
        self.data_path = data_path
        self.endpoints: list[str] = []
        self.regions: list[Region] = []
        self.stores: dict[str, StoreEngine] = {}

    def _store_opts(self, ep: str, election_timeout_ms: int,
                    **extra) -> StoreEngineOptions:
        opts = StoreEngineOptions(
            server_id=ep,
            initial_regions=[r.copy() for r in self.regions],
            data_path=self.data_path,
            election_timeout_ms=election_timeout_ms,
            snapshot_interval_secs=self.snapshot_interval_secs,
            **extra)
        if self.read_only_option is not None:
            opts.read_only_option = self.read_only_option
        return opts

    def leader_endpoint(self, region_id: int = 1):
        for ep, s in self.stores.items():
            eng = s.get_region_engine(region_id)
            if eng is not None and eng.is_leader():
                return ep
        return None


class SoakCluster(_BaseSoakCluster):
    """In-proc fabric: InProcNetwork supplies partitions/drops/delays.

    n_regions > 1 splits the keyspace into that many raft groups per
    store (region k owns [k%06d, (k+1)%06d)); engine=True gives every
    store a MultiRaftEngine protocol plane + multilog shared journal —
    the configuration the G>=1K chaos soak (VERDICT r3 #6) runs."""

    def __init__(self, n_stores: int, data_path: str, n_regions: int = 1,
                 engine: bool = False, election_timeout_ms: int = 400):
        super().__init__(data_path)
        self.net = InProcNetwork()
        self.endpoints = [f"127.0.0.1:{6300 + i}" for i in range(n_stores)]
        self.election_timeout_ms = election_timeout_ms
        self.engine = engine
        if n_regions <= 1:
            self.regions = [Region(id=1, peers=list(self.endpoints))]
        else:
            def bkey(k):
                return b"k%06d" % k

            self.regions = [
                Region(id=k + 1, start_key=bkey(k) if k else b"",
                       end_key=bkey(k + 1) if k + 1 < n_regions else b"",
                       peers=list(self.endpoints))
                for k in range(n_regions)]

    async def start_store(self, ep: str) -> None:
        server = RpcServer(ep)
        self.net.bind(server)
        self.net.start_endpoint(ep)
        transport = InProcTransport(self.net, ep)
        extra = {}
        raft_engine = None
        if self.engine:
            from tpuraft.core.engine import MultiRaftEngine
            from tpuraft.options import TickOptions

            cap = 1 << max(4, (len(self.regions) + 3).bit_length())
            raft_engine = MultiRaftEngine(TickOptions(
                max_groups=cap, max_peers=4, tick_interval_ms=20))
            extra["log_scheme"] = "multilog"
        store = StoreEngine(
            self._store_opts(ep, self.election_timeout_ms, **extra),
            server, transport, multi_raft_engine=raft_engine)
        await store.start()
        self.stores[ep] = store

    async def stop_store(self, ep: str) -> None:
        self.net.stop_endpoint(ep)
        store = self.stores.pop(ep, None)
        if store:
            self.net.unbind(ep)
            await store.shutdown()

    def client_transport(self):
        self._client_t = InProcTransport(self.net, "soak-client:0")
        return self._client_t

    # fault surface (same verbs on both fabrics)
    def one_way_partition(self, a: str, b: str) -> None:
        self.net.partition_one_way({a}, {b})

    def heal_partitions(self) -> None:
        self.net.heal()

    def set_noise(self, drop: float, delay_ms: float) -> None:
        self.net.set_drop_rate(drop)
        self.net.set_delay_ms(delay_ms)


class NativeSoakCluster(_BaseSoakCluster):
    """Full native stack: C++ epoll sockets + C++ KV engines, faults
    injected at each store's FaultInjectingTransport."""

    def __init__(self, n_stores: int, data_path: str):
        from tpuraft.rpc.native_tcp import ensure_built

        ensure_built()
        super().__init__(data_path)
        self.n = n_stores
        self._servers: dict[str, object] = {}
        self._faults: dict[str, object] = {}
        # active fault state survives store restarts (the in-proc fabric
        # gets this for free from its shared network object)
        self._noise: tuple[float, float] = (0.0, 0.0)
        self._blocks: set[tuple[str, str]] = set()

    async def boot(self) -> None:
        from tpuraft.rpc.native_tcp import NativeTcpRpcServer

        servers = []
        for _ in range(self.n):
            srv = NativeTcpRpcServer("127.0.0.1:0")
            await srv.start()
            srv.endpoint = f"127.0.0.1:{srv.bound_port}"
            servers.append(srv)
        self.endpoints = [s.endpoint for s in servers]
        self.regions = [Region(id=1, peers=list(self.endpoints))]
        for srv in servers:
            await self._start(srv.endpoint, srv)

    async def _start(self, ep: str, server=None) -> None:
        from tpuraft.rheakv.native_store import NativeRawKVStore
        from tpuraft.rpc.fault import FaultInjectingTransport
        from tpuraft.rpc.native_tcp import (
            NativeTcpRpcServer,
            NativeTcpTransport,
        )

        if server is None:
            server = NativeTcpRpcServer(ep)
            await server.start()
        transport = FaultInjectingTransport(NativeTcpTransport(endpoint=ep))
        opts = self._store_opts(
            ep, 600,
            raw_store_factory=lambda ep=ep: NativeRawKVStore(
                f"{self.data_path}/nkv_{ep.replace(':', '_')}"))
        store = StoreEngine(opts, server, transport)
        await store.start()
        self.stores[ep] = store
        self._servers[ep] = server
        self._faults[ep] = transport
        # re-apply the fault state active at (re)start time
        transport.set_drop_rate(self._noise[0])
        transport.set_delay_ms(self._noise[1])
        for src, dst in self._blocks:
            if src == ep:
                transport.block(dst)

    async def start_store(self, ep: str) -> None:
        await self._start(ep)

    async def stop_store(self, ep: str) -> None:
        store = self.stores.pop(ep, None)
        server = self._servers.pop(ep, None)
        ft = self._faults.pop(ep, None)
        if store:
            await store.shutdown()
        if server:
            await server.stop()
        if ft:
            await ft.close()

    def client_transport(self):
        from tpuraft.rpc.fault import FaultInjectingTransport
        from tpuraft.rpc.native_tcp import NativeTcpTransport

        # the client rides the SAME noise as the stores (in-proc mode
        # gets this for free from InProcNetwork): maybe-applied client
        # ops are exactly what the checker exists to exercise
        self._client_t = FaultInjectingTransport(NativeTcpTransport())
        self._faults["__client__"] = self._client_t
        return self._client_t

    def one_way_partition(self, a: str, b: str) -> None:
        self._blocks.add((a, b))
        ft = self._faults.get(a)
        if ft is not None:
            ft.block(b)

    def heal_partitions(self) -> None:
        self._blocks.clear()
        for ft in self._faults.values():
            ft.heal()

    def set_noise(self, drop: float, delay_ms: float) -> None:
        self._noise = (drop, delay_ms)
        for ft in self._faults.values():
            ft.set_drop_rate(drop)
            ft.set_delay_ms(delay_ms)


async def run_soak(duration_s: float, n_stores: int, n_keys: int,
                   seed: int, data_path: str, verbose: bool,
                   transport: str = "inproc",
                   dump_history: str = "",
                   lease_reads: bool = False,
                   n_regions: int = 1,
                   engine: bool = False,
                   election_timeout_ms: int = 400,
                   power_loss: bool = False) -> dict:
    rng = random.Random(seed)
    if power_loss and (transport != "inproc" or engine):
        raise ValueError(
            "--power-loss interposes on the Python storage planes "
            "(per-region file:// log/meta/snapshot), so it runs on the "
            "in-proc fabric without --engine; the native multilog's "
            "fd-level I/O is crash-imaged by the dedicated harness "
            "(tests/test_storage_fault.py) instead")
    if transport == "native":
        if n_regions > 1 or engine:
            raise ValueError("region-density soak runs on the in-proc "
                             "fabric (--transport inproc)")
        c = NativeSoakCluster(n_stores, data_path)
    else:
        c = SoakCluster(n_stores, data_path, n_regions=n_regions,
                        engine=engine,
                        election_timeout_ms=election_timeout_ms)
    chaos = {}
    try:
        if power_loss:
            import os as _os

            from tpuraft.storage.fault import ChaosDir

            # snapshots on: prefix compaction + snapshot commit must
            # run UNDER the crash schedule, not just appends
            c.snapshot_interval_secs = 10
            for ep in c.endpoints:
                ip, port = ep.rsplit(":", 1)
                chaos[ep] = ChaosDir(
                    _os.path.join(data_path, f"{ip}_{port}")).install()
        return await _run_soak_inner(
            duration_s, n_keys, verbose, transport, dump_history,
            lease_reads, n_regions, rng, c, chaos)
    finally:
        # uninstall on EVERY exit path, startup failures included: a
        # leaked install leaves builtins.open/os.fsync patched process-
        # wide, turning later fsyncs under the roots into silent no-ops
        for cd in chaos.values():
            cd.uninstall()


async def _run_soak_inner(duration_s, n_keys, verbose, transport,
                          dump_history, lease_reads, n_regions, rng, c,
                          chaos) -> dict:
    if lease_reads:
        from tpuraft.options import ReadOnlyOption

        c.read_only_option = ReadOnlyOption.LEASE_BASED
    if transport == "native":
        await c.boot()
    else:
        for ep in c.endpoints:
            await c.start_store(ep)
    pd = FakePlacementDriverClient([r.copy() for r in c.regions])
    kv = RheaKVStore(pd, c.client_transport(), max_retries=1)
    await kv.start()

    def say(*a):
        if verbose:
            print(*a, flush=True)

    h = History()
    stop = asyncio.Event()
    if n_regions > 1:
        # sample keys from n_keys DISTINCT regions spread over the
        # range: linearizability is checked per key, so each sampled
        # key exercises its own raft group under the shared faults
        step = max(1, n_regions // n_keys)
        sampled = [min(i * step, n_regions - 1) for i in range(n_keys)]
        keys = [b"k%06d/s" % j for j in sampled]
        sampled_regions = [j + 1 for j in sampled]
    else:
        keys = [b"soak-%d" % i for i in range(n_keys)]
        sampled_regions = [1]

    async def worker(cid: int):
        n = 0
        while not stop.is_set():
            n += 1
            key = rng.choice(keys)
            if n % 2 == 0:
                val = b"c%d-%d" % (cid, n)
                tok = h.invoke(cid, "w", (key, val))
                try:
                    await asyncio.wait_for(kv.put(key, val), 4.0)
                    h.complete(tok, True)
                except Exception:
                    pass            # pending: maybe applied
            else:
                tok = h.invoke(cid, "r", (key,))
                try:
                    v = await asyncio.wait_for(kv.get(key), 4.0)
                    h.complete(tok, v)
                except Exception:
                    pass
            await asyncio.sleep(0.005)

    # -- nemesis menu -------------------------------------------------------
    killed: list[str] = []

    async def kill_leader():
        ep = c.leader_endpoint(rng.choice(sampled_regions))
        if ep is None:
            raise SkipFault
        killed.append(ep)
        await c.stop_store(ep)

    async def restart_killed():
        while killed:
            await c.start_store(killed.pop())

    async def one_way():
        a, b = rng.sample(c.endpoints, 2)
        c.one_way_partition(a, b)

    async def heal_net():
        c.heal_partitions()

    async def noise_on():
        c.set_noise(0.05, 2)

    async def noise_off():
        c.set_noise(0.0, 0)

    # power loss: capture the durable-only on-disk image at the crash
    # instant (torn/lost/bit-flipped unsynced tails included), shut the
    # store down, discard everything the shutdown wrote by materializing
    # the captured image, and restart FROM that image — the recovery
    # path must come back clean or the check aborts the drive
    power_lost: list[str] = []
    dead_after_power_loss: list[str] = []

    async def power_loss_kill():
        up = [ep for ep in c.endpoints if ep in c.stores]
        if not up:
            raise SkipFault
        ep = rng.choice(up)
        plan = chaos[ep].capture_crash(rng)   # the instant power dies
        power_lost.append(ep)
        await c.stop_store(ep)
        chaos[ep].apply_crash(plan)

    async def power_loss_restart():
        while power_lost:
            ep = power_lost.pop()
            try:
                await c.start_store(ep)
            except Exception:
                dead_after_power_loss.append(ep)
                raise

    async def power_loss_ok():
        assert not dead_after_power_loss, \
            f"stores failed power-loss recovery: {dead_after_power_loss}"

    actions = [
        NemesisAction("leader-kill", kill_leader, restart_killed,
                      dwell_s=0.7, weight=1.5),
        NemesisAction("one-way-partition", one_way, heal_net, dwell_s=0.5),
        NemesisAction("drops+delays", noise_on, noise_off, dwell_s=0.8),
    ]
    if chaos:
        actions.append(
            NemesisAction("power-loss", power_loss_kill,
                          power_loss_restart, dwell_s=0.6, weight=1.5,
                          check=power_loss_ok))

    workers = [asyncio.ensure_future(worker(i)) for i in range(5)]
    try:
        await run_nemesis(actions, duration_s, rng,
                          on_tick=lambda n: say("  nemesis:", n))
        stop.set()
        await asyncio.gather(*workers)
        ops = h.ops()
        completed = sum(1 for o in ops if o.ret is not None)
        say(f"workload done: {len(ops)} ops ({completed} completed); "
            f"checking linearizability…")
        t0 = time.monotonic()
        rep = check_history(h)
        check_s = time.monotonic() - t0
        result = {
            "linearizable": rep.ok,
            "ops": len(ops),
            "completed": completed,
            "maybe_applied": len(ops) - completed,
            "faults": {a.name: a.applied for a in actions},
            "checker_s": round(check_s, 1),
        }
        if chaos:
            injected: dict[str, int] = {}
            for cd in chaos.values():
                for k, v in cd.injected.items():
                    injected[k] = injected.get(k, 0) + v
            result["power_loss_crashes"] = sum(
                cd.crash_count for cd in chaos.values())
            result["storage_injections"] = injected
        if not rep.ok:
            result["violation"] = str(rep)
        if dump_history and not rep.ok:
            import json as _json
            with open(dump_history, "w") as f:
                for o in ops:
                    f.write(_json.dumps({
                        "id": o.op_id, "client": o.client, "kind": o.kind,
                        "args": [a.hex() if isinstance(a, bytes) else a
                                 for a in o.args],
                        "invoke": o.invoke, "ret": o.ret,
                        "result": (o.result.hex()
                                   if isinstance(o.result, bytes)
                                   else o.result)}) + "\n")
            result["history_dump"] = dump_history
        return result
    finally:
        # also on checker errors / cancellation: no leaked workers or
        # still-running stores
        stop.set()
        for w in workers:
            w.cancel()
        await asyncio.gather(*workers, return_exceptions=True)
        await kv.shutdown()
        for ep in list(c.stores):
            await c.stop_store(ep)
        ct = getattr(c, "_client_t", None)
        if ct is not None and hasattr(ct, "close"):
            await ct.close()
        # chaos uninstall happens in run_soak's outer finally (it must
        # cover startup failures before this block exists too)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--duration", type=float, default=30)
    ap.add_argument("--stores", type=int, default=3)
    ap.add_argument("--keys", type=int, default=6,
                    help="distinct keys (fewer = more contention; "
                         "checker cost grows with ops/key)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--data", default="",
                    help="durable state dir (default: a temp dir)")
    ap.add_argument("--transport", choices=["inproc", "native"],
                    default="inproc",
                    help="'native': C++ epoll sockets + C++ KV engines, "
                         "faults injected per-store")
    ap.add_argument("--lease-reads", action="store_true",
                    help="LEASE_BASED readIndex (no per-read quorum "
                         "round; assumes bounded clock drift)")
    ap.add_argument("--dump-history", default="",
                    help="on violation, write the full op history "
                         "(JSON lines) here for offline analysis")
    ap.add_argument("--regions", type=int, default=1,
                    help=">1: split the keyspace into this many raft "
                         "groups per store (in-proc fabric only) — the "
                         "G>=1K chaos configuration")
    ap.add_argument("--engine", action="store_true",
                    help="MultiRaftEngine protocol plane + multilog "
                         "journal per store (required reading at "
                         "region density)")
    ap.add_argument("--election-timeout-ms", type=int, default=400)
    ap.add_argument("--power-loss", action="store_true",
                    help="add power-loss crashes to the nemesis menu: "
                         "a store is killed at a random instant and "
                         "restarted from its durable-only on-disk image "
                         "(torn writes / lost fsyncs / bit flips in the "
                         "unsynced tails; tpuraft/storage/fault.py)")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args()
    data = args.data or tempfile.mkdtemp(prefix="tpuraft-soak-")
    result = asyncio.run(run_soak(args.duration, args.stores, args.keys,
                                  args.seed, data, args.verbose,
                                  transport=args.transport,
                                  dump_history=args.dump_history,
                                  lease_reads=args.lease_reads,
                                  n_regions=args.regions,
                                  engine=args.engine,
                                  election_timeout_ms=args.election_timeout_ms,
                                  power_loss=args.power_loss))
    import json

    print(json.dumps(result))
    raise SystemExit(0 if result["linearizable"] else 1)


if __name__ == "__main__":
    main()
