"""Chaos soak runner: boots a KV cluster in-process, drives paced
client load under a nemesis fault schedule, then PROVES the recorded
history linearizable.

The reference's chaos tests assert convergence latches; this tool
records real invoke/return windows and checks them against a register
model (tpuraft.util.linearizability) — the strongest black-box verdict
a consensus store can get.

    python -m examples.soak --duration 60 --seed 7
    python -m examples.soak --duration 120 --stores 5 --keys 8 \\
        --data /tmp/soak --verbose

Faults: rolling store kill/restart, one-way partitions, packet
drops+delays+duplication+bounded-reordering — and, with
``--power-loss``, storage-plane crashes: a store is killed at a random
instant and restarted from its durable-only on-disk image, with torn
writes / lost fsyncs / bit flips injected into the unsynced tails
(tpuraft/storage/fault.py).  Durable state dirs are required
implicitly — a voter restarted without its disk is amnesiac, which
Raft does not tolerate (the divergence detector would fail it loudly).

``--churn`` adds continuous elastic-membership churn (add/remove
voters, add/promote/remove learners, leadership transfers) running
CONCURRENTLY with the fault schedule, plus a stage-trap nemesis action
that lands seeded crashes inside each joint-consensus stage; after
every fault the committed conf of every live node must be one of
{old, joint, new} of an attempted change.

``--quiesce`` (with ``--engine``) lets idle groups hibernate
(RaftOptions.quiesce_after_rounds) and adds a
store-kill-while-quiescent nemesis action: a store leading hibernating
groups is killed, and its dependents must wake on store-lease expiry
and elect within the normal fault-detection envelope — with the
history still linearizable.

``--gray`` adds FAIL-SLOW faults: a store's fsyncs stall or crawl
(tpuraft/storage/fault.py latency injection), or one endpoint's links
limp (NetworkTopology.degrade_endpoint) — the victim stays alive to
every classic liveness check while everything it leads detonates in
latency.  Store health scoring (tpuraft/util/health.py) must detect it
from hot-path signals and EVACUATE leadership at a bounded rate; the
run record counts evacuations, and a long drive with zero of them
fails (gray_detection_ok).

``--disk-pressure`` adds CAPACITY faults: every store runs under a
standing ChaosDir byte quota (with a matching DiskBudget inside the
store), and the nemesis menu gains quota-shrink (clamp the victim's
quota to just above live usage) and seeded-ENOSPC-burst actions.  The
pressure ladder (tpuraft/util/health.DiskBudget + StoreEngine reclaim
/ shed) must snapshot-reclaim at NEAR_FULL, shed writes retryably at
FULL while reads keep serving, and RESUME writes after reclaim without
a restart — a long drive that never completes the whole arc fails
(disk_pressure_ok).

``--geo N`` shapes the fabric through a seeded NetworkTopology
(tpuraft/rpc/topology.py): stores tag round-robin into N zones,
inter-zone links get ASYMMETRIC WAN latency + jitter + loss, and the
nemesis menu gains zone-partition (one-way half the time),
wan-degrade (latency x6, +1% loss) and link-flap actions — which heal
via heal_topology() and so compose with (never stomp) the noise
actions' heal().  ``--witness`` additionally makes the last store a
WITNESS member of every region: it votes and acks payload-stripped
appends, never campaigns, never serves reads; after EVERY fault (and
at the end) the soak asserts witness safety — no witness ever led,
opened a ballot window, or journaled a payload byte.
"""

from __future__ import annotations

import argparse
import asyncio
import random
import tempfile
import time

import itertools

from tpuraft.entity import PeerId
from tpuraft.errors import RaftError
from tpuraft.rheakv.client import RheaKVStore
from tpuraft.rheakv.metadata import Region
from tpuraft.rheakv.pd_client import FakePlacementDriverClient
from tpuraft.rheakv.store_engine import StoreEngine, StoreEngineOptions
from tpuraft.rpc.topology import build_geo_topology
from tpuraft.rpc.transport import InProcNetwork, InProcTransport, RpcServer
from tpuraft.util.linearizability import (History, check_history,
                                          check_stale_reads)
from tpuraft.util.nemesis import (
    NemesisAction,
    SkipFault,
    StageTrap,
    run_nemesis,
)
from tpuraft.util.quorum import joint_quorums_intersect as \
    _joint_quorums_intersect  # shared with tests/oracle.py — one oracle

# --disk-pressure: the standing per-store byte quota (ChaosDir) AND the
# store's own DiskBudget ceiling — kept equal so the budget's thresholds
# describe the same disk the fault plane enforces.  Sized so a few
# seconds of soak write load crosses NEAR_FULL (reclaim must then keep
# the store alive for the rest of the drive).
_DISK_QUOTA_BYTES = 384 * 1024


class _BaseSoakCluster:
    """Shared cluster shape for both fabrics: a stores map, the region
    layout, option plumbing, and leader lookup."""

    read_only_option = None   # set by run_soak for lease-read mode
    snapshot_interval_secs = 0  # set by run_soak (power-loss soaks
    #                             snapshot so compaction runs under crashes)

    def __init__(self, data_path: str):
        self.data_path = data_path
        self.endpoints: list[str] = []
        self.regions: list[Region] = []
        self.stores: dict[str, StoreEngine] = {}
        # extra StoreEngineOptions applied to EVERY store (restarts
        # included) — how scenario modes (--disk-pressure) retune
        # budgets/cadences without forking the option plumbing
        self.store_extra: dict = {}
        # --clock-chaos: endpoint -> injected ChaosClock.  Owned by the
        # CLUSTER, not the store: a killed store restarts on the SAME
        # skewed timebase (real machines do not reset their oscillator
        # on process restart)
        self.clocks: dict[str, object] = {}
        # counters of RETIRED engines: a killed/restarted store gets a
        # fresh StoreEngine, and summing only live engines would erase
        # e.g. every gray evacuation a later leader-kill happened to
        # land on — exactly the composition --gray exists to test
        self.retired_counters: dict[str, int] = {}

    def _retire_counters(self, store: StoreEngine) -> None:
        rc = self.retired_counters
        rc["evacuations"] = rc.get("evacuations", 0) + store.evacuations
        if store.append_batcher is not None:
            # write-plane rounds survive store kill/restart in the run
            # record (the PR 11 retired-counter lesson)
            for k, v in store.append_batcher.counters().items():
                rc[k] = rc.get(k, 0) + v
        eager = sum(re_.node.fsm_caller.eager_acked
                    for re_ in store._regions.values()
                    if re_.node is not None)
        if eager:
            rc["fsm_eager_acked"] = rc.get("fsm_eager_acked", 0) + eager
        rc["shed_items"] = rc.get("shed_items", 0) \
            + store.kv_processor.shed_items
        if store.health is not None:
            rc["health_evaluations"] = rc.get("health_evaluations", 0) \
                + store.health.evaluations
            rc["sick_rounds"] = rc.get("sick_rounds", 0) \
                + store.health.level_counts["sick"]
        sentinel = getattr(store, "clock_sentinel", None)
        if sentinel is not None:
            # clock-plane counters (anomalies, fenced leases) must
            # survive kill/restart in the run record too
            for k, v in sentinel.counters().items():
                rc[k] = rc.get(k, 0) + v
        for re_ in store._regions.values():
            if re_.node is not None:
                rc["lease_fallbacks"] = rc.get("lease_fallbacks", 0) \
                    + re_.node.read_only_service.lease_fallbacks
        if store.disk_budget is not None:
            # disk-pressure ladder counters must survive kill/restart
            # in the run record, same as evacuations above
            rc["disk_reclaims"] = rc.get("disk_reclaims", 0) \
                + store.disk_reclaims
            rc["disk_shed_items"] = rc.get("disk_shed_items", 0) \
                + store.disk_shed_items
            bc = store.disk_budget.counters()
            for k in ("disk_pressure_resumes", "disk_enospc_events",
                      "disk_full_rounds", "disk_near_full_rounds"):
                rc[k] = rc.get(k, 0) + bc[k]

    def _store_opts(self, ep: str, election_timeout_ms: int,
                    **extra) -> StoreEngineOptions:
        extra = {**self.store_extra, **extra}
        if ep in self.clocks:
            extra.setdefault("clock", self.clocks[ep])
        opts = StoreEngineOptions(
            server_id=ep,
            initial_regions=[r.copy() for r in self.regions],
            data_path=self.data_path,
            election_timeout_ms=election_timeout_ms,
            snapshot_interval_secs=self.snapshot_interval_secs,
            **extra)
        if self.read_only_option is not None:
            opts.read_only_option = self.read_only_option
        return opts

    def leader_endpoint(self, region_id: int = 1):
        for ep, s in self.stores.items():
            eng = s.get_region_engine(region_id)
            if eng is not None and eng.is_leader():
                return ep
        return None


class SoakCluster(_BaseSoakCluster):
    """In-proc fabric: InProcNetwork supplies partitions/drops/delays.

    n_regions > 1 splits the keyspace into that many raft groups per
    store (region k owns [k%06d, (k+1)%06d)); engine=True gives every
    store a MultiRaftEngine protocol plane + multilog shared journal —
    the configuration the G>=1K chaos soak (VERDICT r3 #6) runs.

    geo_zones > 0 tags stores round-robin into that many zones and
    shapes every link through a seeded NetworkTopology (intra-zone
    near-zero, inter-zone WAN latency+jitter+loss) — the CD-Raft
    regime.  witness=True makes the LAST store a witness member of
    every region (2 data + 1 witness at 3 stores)."""

    def __init__(self, n_stores: int, data_path: str, n_regions: int = 1,
                 engine: bool = False, election_timeout_ms: int = 400,
                 quiesce_after_rounds: int = 0, geo_zones: int = 0,
                 witness: bool = False, geo_seed: int = 0,
                 pd_endpoint: str = "",
                 heartbeat_interval_ms: int = 0):
        super().__init__(data_path)
        self.net = InProcNetwork()
        self.endpoints = [f"127.0.0.1:{6300 + i}" for i in range(n_stores)]
        self.election_timeout_ms = election_timeout_ms
        self.engine = engine
        # --hotspot: stores heartbeat to a REAL in-proc PD at this
        # endpoint (heat rows + cluster view) instead of running PD-less
        self.pd_endpoint = pd_endpoint
        self.heartbeat_interval_ms = heartbeat_interval_ms
        # --lifecycle: splits mint NEW groups mid-run, so the engine's
        # [G] capacity must leave headroom beyond len(regions) (0 =
        # size from the static region count as before)
        self.engine_group_cap = 0
        self.quiesce_after_rounds = quiesce_after_rounds
        self.geo_zones = geo_zones
        self.witness = witness
        self.topology = None
        if geo_zones > 0:
            self.topology = build_geo_topology(
                self.endpoints, geo_zones, seed=geo_seed)
            self.net.set_topology(self.topology)
            from tpuraft.util import describer

            describer.register(self.topology)
        peers = list(self.endpoints)
        if witness:
            # last store = witness voter of every region (metadata-only)
            peers = peers[:-1] + [peers[-1] + "/witness"]
        if n_regions <= 1:
            self.regions = [Region(id=1, peers=peers)]
        else:
            def bkey(k):
                return b"k%06d" % k

            self.regions = [
                Region(id=k + 1, start_key=bkey(k) if k else b"",
                       end_key=bkey(k + 1) if k + 1 < n_regions else b"",
                       peers=list(peers))
                for k in range(n_regions)]

    def zone_of(self, ep: str) -> str:
        if self.topology is None:
            return ""
        return self.topology.zone_of(ep)

    async def start_store(self, ep: str) -> None:
        server = RpcServer(ep)
        self.net.bind(server)
        self.net.start_endpoint(ep)
        transport = InProcTransport(self.net, ep)
        extra = {}
        if self.quiesce_after_rounds:
            extra["quiesce_after_rounds"] = self.quiesce_after_rounds
        if self.geo_zones:
            extra["zone"] = self.zone_of(ep)
        if self.heartbeat_interval_ms:
            extra["heartbeat_interval_ms"] = self.heartbeat_interval_ms
        pd_client = None
        if self.pd_endpoint:
            from tpuraft.rheakv.pd_client import RemotePlacementDriverClient

            pd_client = RemotePlacementDriverClient(
                transport, [self.pd_endpoint])
        raft_engine = None
        if self.engine:
            from tpuraft.core.engine import MultiRaftEngine
            from tpuraft.options import TickOptions

            cap = self.engine_group_cap \
                or 1 << max(4, (len(self.regions) + 3).bit_length())
            raft_engine = MultiRaftEngine(TickOptions(
                max_groups=cap, max_peers=4, tick_interval_ms=20))
            extra["log_scheme"] = "multilog"
        store = StoreEngine(
            self._store_opts(ep, self.election_timeout_ms, **extra),
            server, transport, multi_raft_engine=raft_engine,
            pd_client=pd_client)
        await store.start()
        self.stores[ep] = store

    async def stop_store(self, ep: str) -> None:
        self.net.stop_endpoint(ep)
        store = self.stores.pop(ep, None)
        if store:
            self._retire_counters(store)
            self.net.unbind(ep)
            await store.shutdown()

    def client_transport(self):
        self._client_t = InProcTransport(self.net, "soak-client:0")
        return self._client_t

    # fault surface (same verbs on both fabrics)
    def one_way_partition(self, a: str, b: str) -> None:
        self.net.partition_one_way({a}, {b})

    def heal_partitions(self) -> None:
        self.net.heal()

    def set_noise(self, drop: float, delay_ms: float, dup: float = 0.0,
                  reorder: float = 0.0, reorder_ms: float = 8.0) -> None:
        self.net.set_drop_rate(drop)
        self.net.set_delay_ms(delay_ms)
        self.net.set_duplicate_rate(dup)
        self.net.set_reorder(reorder, reorder_ms)

    def heal_topology(self) -> None:
        self.net.heal_topology()


class NativeSoakCluster(_BaseSoakCluster):
    """Full native stack: C++ epoll sockets + C++ KV engines, faults
    injected at each store's FaultInjectingTransport."""

    def __init__(self, n_stores: int, data_path: str):
        from tpuraft.rpc.native_tcp import ensure_built

        ensure_built()
        super().__init__(data_path)
        self.n = n_stores
        self._servers: dict[str, object] = {}
        self._faults: dict[str, object] = {}
        # active fault state survives store restarts (the in-proc fabric
        # gets this for free from its shared network object)
        self._noise: tuple[float, float, float, float, float] = (
            0.0, 0.0, 0.0, 0.0, 8.0)
        self._blocks: set[tuple[str, str]] = set()

    async def boot(self) -> None:
        from tpuraft.rpc.native_tcp import NativeTcpRpcServer

        servers = []
        for _ in range(self.n):
            srv = NativeTcpRpcServer("127.0.0.1:0")
            await srv.start()
            srv.endpoint = f"127.0.0.1:{srv.bound_port}"
            servers.append(srv)
        self.endpoints = [s.endpoint for s in servers]
        self.regions = [Region(id=1, peers=list(self.endpoints))]
        for srv in servers:
            await self._start(srv.endpoint, srv)

    async def _start(self, ep: str, server=None) -> None:
        from tpuraft.rheakv.native_store import NativeRawKVStore
        from tpuraft.rpc.fault import FaultInjectingTransport
        from tpuraft.rpc.native_tcp import (
            NativeTcpRpcServer,
            NativeTcpTransport,
        )

        if server is None:
            server = NativeTcpRpcServer(ep)
            await server.start()
        transport = FaultInjectingTransport(NativeTcpTransport(endpoint=ep))
        opts = self._store_opts(
            ep, 600,
            raw_store_factory=lambda ep=ep: NativeRawKVStore(
                f"{self.data_path}/nkv_{ep.replace(':', '_')}"))
        store = StoreEngine(opts, server, transport)
        await store.start()
        self.stores[ep] = store
        self._servers[ep] = server
        self._faults[ep] = transport
        # re-apply the fault state active at (re)start time
        transport.set_drop_rate(self._noise[0])
        transport.set_delay_ms(self._noise[1])
        transport.set_duplicate_rate(self._noise[2])
        transport.set_reorder(self._noise[3], self._noise[4])
        for src, dst in self._blocks:
            if src == ep:
                transport.block(dst)

    async def start_store(self, ep: str) -> None:
        await self._start(ep)

    async def stop_store(self, ep: str) -> None:
        store = self.stores.pop(ep, None)
        server = self._servers.pop(ep, None)
        ft = self._faults.pop(ep, None)
        if store:
            self._retire_counters(store)
            await store.shutdown()
        if server:
            await server.stop()
        if ft:
            await ft.close()

    def client_transport(self):
        from tpuraft.rpc.fault import FaultInjectingTransport
        from tpuraft.rpc.native_tcp import NativeTcpTransport

        # the client rides the SAME noise as the stores (in-proc mode
        # gets this for free from InProcNetwork): maybe-applied client
        # ops are exactly what the checker exists to exercise
        self._client_t = FaultInjectingTransport(NativeTcpTransport())
        self._faults["__client__"] = self._client_t
        return self._client_t

    def one_way_partition(self, a: str, b: str) -> None:
        self._blocks.add((a, b))
        ft = self._faults.get(a)
        if ft is not None:
            ft.block(b)

    def heal_partitions(self) -> None:
        self._blocks.clear()
        for ft in self._faults.values():
            ft.heal()

    def set_noise(self, drop: float, delay_ms: float, dup: float = 0.0,
                  reorder: float = 0.0, reorder_ms: float = 8.0) -> None:
        self._noise = (drop, delay_ms, dup, reorder, reorder_ms)
        for ft in self._faults.values():
            ft.set_drop_rate(drop)
            ft.set_delay_ms(delay_ms)
            ft.set_duplicate_rate(dup)
            ft.set_reorder(reorder, reorder_ms)


class MembershipChurn:
    """Continuous elastic-membership churn against one region of an
    in-proc soak cluster: add/remove voters, add/promote/remove
    learners, transfer leadership — running CONCURRENTLY with the
    nemesis schedule, so every seeded crash may land mid-joint-config,
    mid-catch-up, or mid-transfer.

    Tracks the committed-configuration history and asserts, after every
    fault heals, that each live node's conf is one of {old, joint, new}
    of some attempted change and that consecutive stable confs kept
    quorum intersection (through the joint's dual quorum).
    """

    def __init__(self, cluster, region_id: int, rng, say):
        self.c = cluster
        self.rid = region_id
        self.rng = rng
        self.say = say
        self.trap = StageTrap()
        self.completed = 0
        self.transfers = 0
        self.busy_retries = 0
        self.failures: dict[str, int] = {}
        self.stage_crashes: dict[str, int] = {}
        # committed stable voter sets, in completion order
        initial = frozenset(PeerId.parse(p) if isinstance(p, str) else p
                            for p in self._region_peers())
        self.conf_history: list[frozenset] = [initial]
        # every (old, new) pair ever attempted: lagging nodes may hold a
        # joint from a change several rounds back
        self.attempted: list[tuple[frozenset, frozenset]] = []
        self._stop = asyncio.Event()
        self._task = None

    def _region_peers(self) -> list:
        for r in self.c.regions:
            if r.id == self.rid:
                return list(r.peers)
        raise ValueError(f"region {self.rid} not in cluster layout")

    # -- plumbing ------------------------------------------------------------

    def _nodes(self):
        out = {}
        for ep, s in self.c.stores.items():
            eng = s.get_region_engine(self.rid)
            if eng is not None and eng.node is not None:
                out[ep] = eng.node
        return out

    def leader_node(self):
        for ep, node in self._nodes().items():
            if node.is_leader():
                return ep, node
        return None, None

    def _install_listeners(self) -> None:
        """(Re)hook the stage trap on every live node — idempotent, and
        repeated each round so restarted stores rejoin the trap."""
        for node in self._nodes().values():
            node.conf_stage_listener = self.trap.listener

    # -- the churn loop ------------------------------------------------------

    def start(self) -> None:
        self._task = asyncio.ensure_future(self._run())

    async def stop(self) -> None:
        self._stop.set()
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None

    async def _run(self) -> None:
        while not self._stop.is_set():
            try:
                await self._one_change()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # a churn-op crash must not stop churn
                self._note_failure(f"driver:{type(e).__name__}")
            await asyncio.sleep(0.05 + self.rng.random() * 0.15)

    def _note_failure(self, key: str) -> None:
        self.failures[key] = self.failures.get(key, 0) + 1

    async def _one_change(self) -> None:
        """Pick one membership op against the current conf and drive it
        through with bounded EBUSY backoff-retry (the operator loop)."""
        self._install_listeners()
        for attempt in range(12):
            if self._stop.is_set():
                return
            ep, node = self.leader_node()
            if node is None:
                await asyncio.sleep(0.2)
                continue
            plan = self._plan_op(node)
            if plan is None:
                await asyncio.sleep(0.2)
                continue
            op, coro, old_set, new_set = plan
            # record the attempt BEFORE the call: a crash window may
            # commit the change without us seeing the ack, and the
            # invariant check must know the pair was legal.  Definite
            # pre-append rejections un-record it below so the oracle's
            # allowed set doesn't silently widen with changes that
            # never touched any log.
            pair = (old_set, new_set)
            recorded = op != "transfer" and new_set != old_set
            if recorded:
                self.attempted.append(pair)

            def unrecord():
                if recorded and pair in self.attempted:
                    self.attempted.remove(pair)

            try:
                st = await asyncio.wait_for(coro, 20.0)
            except asyncio.TimeoutError:
                self._note_failure(f"{op}:timeout")
                return
            except Exception as e:
                # node shut down mid-call (a crash landed on it) — the
                # change may or may not complete; the invariant check
                # reconciles either way
                self._note_failure(f"{op}:{type(e).__name__}")
                return
            if st.is_ok():
                if op == "transfer":
                    self.transfers += 1
                else:
                    self.completed += 1
                    if new_set != self.conf_history[-1]:
                        self.conf_history.append(new_set)
                self.say(f"  churn: {op} ok "
                         f"(voters={len(new_set)})")
                return
            code = st.raft_error
            if code == RaftError.EBUSY:
                # rejected before anything was appended
                unrecord()
                self.busy_retries += 1
                await asyncio.sleep(0.15 + self.rng.random() * 0.1)
                continue
            if code in (RaftError.EINVAL, RaftError.EPERM):
                unrecord()  # rejected at propose time, nothing appended
            # transient outcomes under chaos (deposed leader, catch-up
            # against a killed store, shutdown): note and move on —
            # the invariant check decides whether the change took
            self._note_failure(f"{op}:{code.name}")
            return

    def _plan_op(self, node):
        """Build (op, coroutine, old_voters, new_voters) for one change
        against the leader's CURRENT conf."""
        voters = list(node.conf_entry.conf.peers)
        learners = list(node.conf_entry.conf.learners)
        all_peers = [PeerId.parse(e) for e in self.c.endpoints]
        spare = [p for p in all_peers
                 if p not in voters and p not in learners]
        menu: list[str] = []
        if spare:
            menu += ["add_voter", "add_learner"]
        if learners:
            menu += ["promote_learner", "remove_learner"]
        if len(voters) > 2:
            menu += ["remove_voter", "remove_voter"]
        if len(voters) > 1:
            menu += ["transfer"]
        if not menu:
            return None
        op = self.rng.choice(menu)
        old_set = frozenset(voters)
        new_conf = node.conf_entry.conf.copy()
        if op == "add_voter":
            new_conf.peers.append(self.rng.choice(spare))
        elif op == "add_learner":
            new_conf.learners.append(self.rng.choice(spare))
        elif op == "promote_learner":
            p = self.rng.choice(learners)
            new_conf.learners.remove(p)
            new_conf.peers.append(p)
        elif op == "remove_learner":
            new_conf.learners.remove(self.rng.choice(learners))
        elif op == "remove_voter":
            victim = self.rng.choice(voters)
            new_conf.peers.remove(victim)
        elif op == "transfer":
            target = self.rng.choice(
                [p for p in voters if p != node.server_id] or voters)
            return (op, node.transfer_leadership_to(target),
                    old_set, old_set)
        new_set = frozenset(new_conf.peers)
        return (op, node.change_peers(new_conf), old_set, new_set)

    # -- invariants (run as the nemesis post-heal check) ---------------------

    async def check_invariants(self) -> None:
        """After a fault heals: every live node's conf must be one of
        {old, joint, new} of some attempted change, and the stable-conf
        chain must keep quorum intersection.  An ok-status the driver
        missed (leader died after committing) is reconciled here."""
        history = set(self.conf_history)
        for ep, node in self._nodes().items():
            conf = frozenset(node.conf_entry.conf.peers)
            old = frozenset(node.conf_entry.old_conf.peers)
            if old:
                assert (old, conf) in self.attempted, (
                    f"{ep}: joint conf {sorted(map(str, old))} -> "
                    f"{sorted(map(str, conf))} matches no attempted "
                    f"change (history={self.conf_history})")
                # quorum intersection across the change, verified by
                # enumerating the joint's dual quorums against both
                # sides' majorities
                assert _joint_quorums_intersect(old, conf), (
                    f"{ep}: joint {sorted(map(str, old))} -> "
                    f"{sorted(map(str, conf))} lacks quorum intersection")
            else:
                if conf in history:
                    continue
                # a stable conf the driver never saw complete: legal iff
                # it is the target of an attempted change leaving a
                # known stable conf (the leader died between commit and
                # ack) — adopt it as completed
                adopted = False
                for o, n in self.attempted:
                    if n == conf and o in history:
                        self.conf_history.append(conf)
                        history.add(conf)
                        self.completed += 1
                        adopted = True
                        self.say(f"  churn: adopted conf completed "
                                 f"under crash (voters={len(conf)})")
                        break
                assert adopted, (
                    f"{ep}: stable conf {sorted(map(str, conf))} is "
                    f"neither a committed conf nor an attempted target "
                    f"(history={self.conf_history})")

    def summary(self) -> dict:
        return {
            "completed_conf_changes": self.completed,
            "transfers": self.transfers,
            "busy_retries": self.busy_retries,
            "stage_crashes": dict(self.stage_crashes),
            "failures": dict(self.failures),
            "conf_history_len": len(self.conf_history),
        }


async def run_soak(duration_s: float, n_stores: int, n_keys: int,
                   seed: int, data_path: str, verbose: bool,
                   transport: str = "inproc",
                   dump_history: str = "",
                   lease_reads: bool = False,
                   n_regions: int = 1,
                   engine: bool = False,
                   election_timeout_ms: int = 400,
                   power_loss: bool = False,
                   churn: bool = False,
                   quiesce: bool = False,
                   kv_batching: bool = False,
                   geo: int = 0,
                   witness: bool = False,
                   read_mix: float = 0.0,
                   read_from: str = "leader",
                   gray: bool = False,
                   write_burst: bool = False,
                   disk_pressure: bool = False,
                   clock_chaos: bool = False,
                   trace: str = "") -> dict:
    rng = random.Random(seed)
    if geo and transport != "inproc":
        raise ValueError(
            "--geo shapes the in-proc fabric's NetworkTopology; the "
            "native fabric takes per-store FaultInjectingTransport "
            "topologies (wire them explicitly)")
    if geo == 1:
        raise ValueError(
            "--geo needs at least 2 zones (zone partitions and "
            "link flaps are inter-zone faults)")
    if witness and not geo:
        raise ValueError("--witness rides the geo scenario (--geo N)")
    if witness and churn:
        raise ValueError(
            "--witness fixes the last store as a witness member; "
            "--churn's random add/remove would fight that placement — "
            "run them separately")
    if quiesce and (transport != "inproc" or not engine):
        raise ValueError(
            "--quiesce hibernates engine-driven groups (TimerControl "
            "nodes never quiesce): run with --engine on the in-proc "
            "fabric")
    if churn and transport != "inproc":
        raise ValueError(
            "--churn drives membership ops and stage traps through "
            "direct node access, so it runs on the in-proc fabric")
    if power_loss and (transport != "inproc" or engine):
        raise ValueError(
            "--power-loss interposes on the Python storage planes "
            "(per-region file:// log/meta/snapshot), so it runs on the "
            "in-proc fabric without --engine; the native multilog's "
            "fd-level I/O is crash-imaged by the dedicated harness "
            "(tests/test_storage_fault.py) instead")
    if gray and (transport != "inproc" or engine):
        raise ValueError(
            "--gray injects fail-slow disk faults through the same "
            "storage interposition as --power-loss: in-proc fabric, "
            "no --engine (the multilog's fd-level fsyncs are out of "
            "Python's reach)")
    if disk_pressure and (transport != "inproc" or engine):
        raise ValueError(
            "--disk-pressure drives capacity faults through the Python "
            "storage interposition (ChaosDir quotas): in-proc fabric, "
            "no --engine (the native multilog's quota mirror is "
            "exercised by tests/test_storage_fault.py via "
            "NativeJournalTracker.attach_quota)")
    if clock_chaos and (transport != "inproc" or engine):
        raise ValueError(
            "--clock-chaos installs per-store injected ChaosClocks "
            "through StoreEngineOptions.clock, which drives timer-mode "
            "nodes: in-proc fabric, no --engine (the engine's device "
            "TickClock takes its own TickOptions.clock — wire it "
            "explicitly for an engine-mode clock soak)")
    if transport == "native":
        if n_regions > 1 or engine:
            raise ValueError("region-density soak runs on the in-proc "
                             "fabric (--transport inproc)")
        c = NativeSoakCluster(n_stores, data_path)
    else:
        c = SoakCluster(n_stores, data_path, n_regions=n_regions,
                        engine=engine,
                        election_timeout_ms=election_timeout_ms,
                        quiesce_after_rounds=4 if quiesce else 0,
                        geo_zones=geo, witness=witness, geo_seed=seed)
    if clock_chaos:
        from tpuraft.util.clock import ChaosClock

        # every store gets its OWN seeded virtual clock, installed for
        # the whole drive (restarts keep it — see _BaseSoakCluster);
        # every store also pads its leases for a declared 5% worst-case
        # drift, the bound the nemesis menu deliberately exceeds so the
        # sentinel fence / SAFE fallback paths must carry safety
        for i, ep in enumerate(c.endpoints):
            c.clocks[ep] = ChaosClock(seed=seed * 1000 + i)
        c.store_extra.setdefault("clock_drift_bound", 0.05)
    chaos = {}
    try:
        if power_loss or gray or disk_pressure:
            import os as _os

            from tpuraft.storage.fault import ChaosDir

            if power_loss or disk_pressure:
                # snapshots on: prefix compaction + snapshot commit must
                # run UNDER the crash schedule, not just appends (and
                # they are the disk-pressure reclaim unit)
                c.snapshot_interval_secs = 10
            for ep in c.endpoints:
                ip, port = ep.rsplit(":", 1)
                chaos[ep] = ChaosDir(
                    _os.path.join(data_path, f"{ip}_{port}")).install()
            if disk_pressure:
                # every store lives under a standing byte quota, and its
                # OWN DiskBudget gets the same ceiling; small segments +
                # a fast health cadence make reclaim prompt at soak scale
                for cd in chaos.values():
                    cd.set_quota(_DISK_QUOTA_BYTES)
                c.store_extra.update(
                    disk_budget_bytes=_DISK_QUOTA_BYTES,
                    health_eval_interval_ms=100,
                    log_segment_max_bytes=32 * 1024,
                    disk_reclaim_cooldown_rounds=4)
        if gray and getattr(c, "topology", None) is None:
            # slow-endpoint events need a topology even zoneless: a
            # bare one shapes nothing until degrade_endpoint fires
            from tpuraft.rpc.topology import NetworkTopology

            c.topology = NetworkTopology(seed=seed)
            c.net.set_topology(c.topology)
        return await _run_soak_inner(
            duration_s, n_keys, verbose, transport, dump_history,
            lease_reads, n_regions, rng, c, chaos, churn, quiesce,
            kv_batching, geo, witness, read_mix, read_from,
            gray=gray, power_loss=power_loss, write_burst=write_burst,
            disk_pressure=disk_pressure, clock_chaos=clock_chaos,
            trace=trace)
    finally:
        # uninstall on EVERY exit path, startup failures included: a
        # leaked install leaves builtins.open/os.fsync patched process-
        # wide, turning later fsyncs under the roots into silent no-ops
        for cd in chaos.values():
            cd.uninstall()


async def _run_soak_inner(duration_s, n_keys, verbose, transport,
                          dump_history, lease_reads, n_regions, rng, c,
                          chaos, churn=False, quiesce=False,
                          kv_batching=False, geo=0, witness=False,
                          read_mix=0.0, read_from="leader", gray=False,
                          power_loss=False, write_burst=False,
                          disk_pressure=False, clock_chaos=False,
                          trace="") -> dict:
    if trace:
        # sampled product tracing through the whole drive; exported as
        # perfetto-loadable JSON next to the result
        from tpuraft.util.trace import TRACER

        TRACER.configure(enabled=True, sample_rate=0.05, seed=0)
    if lease_reads:
        from tpuraft.options import ReadOnlyOption

        c.read_only_option = ReadOnlyOption.LEASE_BASED
    if transport == "native":
        await c.boot()
    else:
        for ep in c.endpoints:
            await c.start_store(ep)
    pd = FakePlacementDriverClient([r.copy() for r in c.regions])
    # --kv-batching: the store-grouped kv_command_batch serving plane —
    # the oracle history must stay linearizable with ops riding batches
    # (each batched op acks individually, applies atomically per item)
    from tpuraft.rheakv.client import BatchingOptions

    kv = RheaKVStore(pd, c.client_transport(), max_retries=1,
                     batching=BatchingOptions(enabled=True)
                     if kv_batching else None,
                     read_from=read_from,
                     jitter_seed=rng.randrange(1 << 30))
    await kv.start()

    def say(*a):
        if verbose:
            print(*a, flush=True)

    h = History()
    stop = asyncio.Event()
    if n_regions > 1:
        # sample keys from n_keys DISTINCT regions spread over the
        # range: linearizability is checked per key, so each sampled
        # key exercises its own raft group under the shared faults
        step = max(1, n_regions // n_keys)
        sampled = [min(i * step, n_regions - 1) for i in range(n_keys)]
        keys = [b"k%06d/s" % j for j in sampled]
        sampled_regions = [j + 1 for j in sampled]
    else:
        keys = [b"soak-%d" % i for i in range(n_keys)]
        sampled_regions = [1]

    # read-mix mode (--read-mix FRAC): reads with probability FRAC,
    # writes carry per-key MONOTONE sequence values with exactly ONE
    # writer per key issuing in order — the shape the targeted
    # no-stale-read assertion (check_stale_reads) requires on top of
    # the full linearizability check
    n_workers = 5
    seq_counters = {k: itertools.count(1) for k in keys}
    key_owner = {k: i % n_workers for i, k in enumerate(keys)}

    def _seq_of(value) -> int:
        if isinstance(value, bytes) and value[:1] == b"s":
            try:
                return int(value[1:])
            except ValueError:
                return -1
        return -1

    async def worker(cid: int):
        n = 0
        own_keys = [k for k in keys if key_owner[k] == cid]
        while not stop.is_set():
            n += 1
            if write_burst:
                # write-heavy shape (ISSUE 15): a burst of 4 concurrent
                # puts — the store-wide append rounds and ack-at-commit
                # path run loaded while the nemeses fire — plus ~10%
                # reads so acked-at-commit writes are read back under
                # the same history
                if rng.random() < 0.1:
                    key = rng.choice(keys)
                    tok = h.invoke(cid, "r", (key,))
                    try:
                        v = await asyncio.wait_for(kv.get(key), 4.0)
                        h.complete(tok, v)
                    except Exception:
                        pass
                else:
                    async def one_put(j: int):
                        key = rng.choice(keys)
                        val = b"c%d-%d-%d" % (cid, n, j)
                        tok = h.invoke(cid, "w", (key, val))
                        try:
                            await asyncio.wait_for(kv.put(key, val), 4.0)
                            h.complete(tok, True)
                        except Exception:
                            pass        # pending: maybe applied
                    await asyncio.gather(*(one_put(j) for j in range(4)))
                await asyncio.sleep(0.005)
                continue
            if read_mix > 0:
                do_read = not own_keys or rng.random() < read_mix
                key = rng.choice(keys if do_read else own_keys)
            else:
                do_read = n % 2 == 1
                key = rng.choice(keys)
            if not do_read:
                val = (b"s%08d" % next(seq_counters[key])
                       if read_mix > 0 else b"c%d-%d" % (cid, n))
                tok = h.invoke(cid, "w", (key, val))
                try:
                    await asyncio.wait_for(kv.put(key, val), 4.0)
                    h.complete(tok, True)
                except Exception:
                    pass            # pending: maybe applied
            else:
                tok = h.invoke(cid, "r", (key,))
                try:
                    v = await asyncio.wait_for(kv.get(key), 4.0)
                    h.complete(tok, v)
                except Exception:
                    pass
            await asyncio.sleep(0.005)

    # -- nemesis menu -------------------------------------------------------
    killed: list[str] = []

    async def kill_leader():
        ep = c.leader_endpoint(rng.choice(sampled_regions))
        if ep is None:
            raise SkipFault
        killed.append(ep)
        await c.stop_store(ep)

    async def restart_killed():
        while killed:
            await c.start_store(killed.pop())

    async def one_way():
        a, b = rng.sample(c.endpoints, 2)
        c.one_way_partition(a, b)

    async def heal_net():
        c.heal_partitions()

    async def noise_on():
        # drops + delays + the two other classic network faults:
        # duplication (receiver executes twice) and bounded reordering
        c.set_noise(0.05, 2, dup=0.03, reorder=0.05, reorder_ms=8.0)

    async def noise_off():
        c.set_noise(0.0, 0)

    # power loss: capture the durable-only on-disk image at the crash
    # instant (torn/lost/bit-flipped unsynced tails included), shut the
    # store down, discard everything the shutdown wrote by materializing
    # the captured image, and restart FROM that image — the recovery
    # path must come back clean or the check aborts the drive
    power_lost: list[str] = []
    dead_after_power_loss: list[str] = []

    async def power_loss_kill():
        up = [ep for ep in c.endpoints if ep in c.stores]
        if not up:
            raise SkipFault
        ep = rng.choice(up)
        plan = chaos[ep].capture_crash(rng)   # the instant power dies
        power_lost.append(ep)
        await c.stop_store(ep)
        chaos[ep].apply_crash(plan)

    async def power_loss_restart():
        while power_lost:
            ep = power_lost.pop()
            try:
                await c.start_store(ep)
            except Exception:
                dead_after_power_loss.append(ep)
                raise

    async def power_loss_ok():
        assert not dead_after_power_loss, \
            f"stores failed power-loss recovery: {dead_after_power_loss}"

    # store-kill-while-quiescent (--quiesce): wait for hibernation to
    # actually take hold on some store, then kill THAT store — its
    # dependent quiescent follower groups (on other stores) must wake on
    # store-lease expiry and elect within the normal fault-detection
    # envelope, and the history must stay linearizable
    quiesce_killed: list[str] = []
    quiesce_kill_counts: list[int] = []

    def _quiescent_leader_count(ep: str) -> int:
        store = c.stores.get(ep)
        if store is None or store.multi_raft_engine is None:
            return 0
        from tpuraft.ops.tick import ROLE_LEADER

        eng = store.multi_raft_engine
        return int((eng.quiescent & (eng.role == ROLE_LEADER)).sum())

    async def quiescent_store_kill():
        # give hibernation a moment to take hold, then pick the store
        # leading the most QUIESCENT groups
        deadline = asyncio.get_running_loop().time() + 6.0
        victim, best = None, 0
        while asyncio.get_running_loop().time() < deadline:
            counts = {ep: _quiescent_leader_count(ep)
                      for ep in list(c.stores)}
            victim = max(counts, key=counts.get) if counts else None
            best = counts.get(victim, 0)
            if best > 0:
                break
            await asyncio.sleep(0.2)
        if victim is None or best == 0:
            raise SkipFault   # the workload kept everything awake
        say(f"  nemesis: killing store {victim} with {best} "
            f"quiescent leader groups")
        quiesce_kill_counts.append(best)
        quiesce_killed.append(victim)
        await c.stop_store(victim)

    async def quiescent_store_restart():
        while quiesce_killed:
            await c.start_store(quiesce_killed.pop())

    # -- membership churn (--churn): continuous conf changes under the
    # fault schedule + a stage-trap action that lands seeded crashes
    # INSIDE each _ConfigurationCtx stage ------------------------------------
    churn_driver = None
    crash_stage_cycle = itertools.cycle(["catching_up", "joint", "stable"])
    churn_lost: list[str] = []
    churn_dead: list[str] = []

    async def churn_crash():
        """Arm the stage trap for the next target stage; when a change
        enters it, crash THAT node's store mid-stage (power-loss image
        when --power-loss is on, plain kill otherwise)."""
        target = next(crash_stage_cycle)
        churn_driver.trap.arm(target)
        try:
            hit = await churn_driver.trap.wait(12.0)
        finally:
            churn_driver.trap.disarm()
        if not hit:
            raise SkipFault
        node = churn_driver.trap.node
        ep = node.server_id.endpoint
        if ep not in c.stores:
            raise SkipFault
        churn_driver.stage_crashes[target] = \
            churn_driver.stage_crashes.get(target, 0) + 1
        say(f"  nemesis: churn-crash landing in stage={target} on {ep}")
        if chaos and power_loss:
            plan = chaos[ep].capture_crash(rng)
            churn_lost.append(ep)
            await c.stop_store(ep)
            chaos[ep].apply_crash(plan)
        else:
            churn_lost.append(ep)
            await c.stop_store(ep)

    async def churn_crash_restart():
        while churn_lost:
            ep = churn_lost.pop()
            try:
                await c.start_store(ep)
            except Exception:
                churn_dead.append(ep)
                raise

    async def churn_ok():
        assert not churn_dead, \
            f"stores failed churn-crash recovery: {churn_dead}"
        await churn_driver.check_invariants()

    def with_conf_check(existing):
        """Compose an action's own recovery probe with the membership
        invariant check — under churn, EVERY fault's heal must leave
        each node's conf in {old, joint, new}."""
        if churn_driver is None:
            return existing

        async def _check():
            if existing is not None:
                await existing()
            await churn_driver.check_invariants()
        return _check

    # -- geo fault surface (--geo): topology-shaped events that compose
    # with (and heal independently of) the nemesis noise above ----------------
    topo = getattr(c, "topology", None)

    async def zone_partition():
        """Cut one whole zone off (one-way half the time — the classic
        asymmetric WAN failure)."""
        zone = rng.choice(topo.zones())
        one_way = rng.random() < 0.5
        say(f"  nemesis: zone-partition {zone} "
            f"({'one-way' if one_way else 'both ways'})")
        topo.partition_zone(zone, one_way=one_way)

    async def wan_degrade():
        """Brown out every inter-zone link: latency x6, +1% loss."""
        topo.degrade_wan(latency_x=6.0, extra_loss=0.01, bandwidth_x=1.0)

    async def link_flap():
        zones = topo.zones() if topo is not None else []
        if len(zones) < 2:
            raise SkipFault
        za, zb = rng.sample(zones, 2)
        topo.flap(za, zb, period_s=0.4, duty=0.6)

    async def heal_topology():
        c.heal_topology()

    def witness_nodes():
        if not witness:
            return []
        wep = c.endpoints[-1]
        store = c.stores.get(wep)
        if store is None:
            return []
        return [eng.node for eng in
                (store.get_region_engine(r.id) for r in c.regions)
                if eng is not None and eng.node is not None]

    async def witness_safety_check():
        """After every fault heals: a witness must never have led or
        advanced a ballot of its own — the witness-majority-must-not-
        commit invariant, asserted live through the whole drive."""
        for node in witness_nodes():
            assert not node.is_leader(), \
                f"witness {node} became leader under chaos"
            assert node.ballot_box.pending_index == 0, \
                f"witness {node} opened a leader ballot window"

    def with_witness_check(existing):
        if not witness:
            return existing

        async def _check():
            if existing is not None:
                await existing()
            await witness_safety_check()
        return _check

    # -- gray-failure fault surface (--gray): fail-slow, never fail-stop.
    # One store's disk stalls / limps, or one endpoint's links crawl,
    # while the store stays "alive" to every classic check — detection
    # (HealthTracker) must score it, evacuation must move its leases,
    # and the history must stay linearizable through it all. -----------------
    gray_slowed: list[str] = []       # stores with an active disk fault
    gray_limped: list[str] = []       # endpoints with an active limp

    def _gray_victim():
        up = [ep for ep in c.endpoints if ep in c.stores]
        if not up:
            raise SkipFault
        # prefer a store that currently LEADS something — slowing an
        # idle follower proves nothing about evacuation
        leaders = [ep for ep in up
                   if c.stores[ep].leader_region_ids()]
        return rng.choice(leaders or up)

    async def gray_disk_stall():
        """Burst disk stall: every fsync pays 60-150ms on its thread."""
        ep = _gray_victim()
        say(f"  nemesis: gray disk-stall on {ep}")
        chaos[ep].set_slow(fsync_ms=60, write_ms=5, jitter_ms=90,
                           seed=rng.randrange(1 << 30))
        gray_slowed.append(ep)

    async def gray_slow_store():
        """Sustained slow store: moderate disk latency + limping links
        (the saturated-CPU shape — everything it does is a bit slow)."""
        ep = _gray_victim()
        say(f"  nemesis: gray slow-store on {ep}")
        chaos[ep].set_slow(fsync_ms=25, write_ms=4, jitter_ms=20,
                           seed=rng.randrange(1 << 30))
        c.topology.degrade_endpoint(ep, latency_ms=20, jitter_ms=15)
        gray_slowed.append(ep)
        gray_limped.append(ep)

    async def gray_stalled_fsync():
        """Full fsync hang: nothing durably completes on the victim
        until heal — the worst gray failure."""
        ep = _gray_victim()
        say(f"  nemesis: gray stalled-fsync on {ep}")
        chaos[ep].stall_fsync()
        gray_slowed.append(ep)

    async def gray_slow_endpoint():
        """One store's links limp while its zone stays healthy."""
        up = [ep for ep in c.endpoints if ep in c.stores]
        if not up:
            raise SkipFault
        ep = rng.choice(up)
        say(f"  nemesis: gray slow-endpoint on {ep}")
        c.topology.degrade_endpoint(ep, latency_ms=60, jitter_ms=40,
                                    loss=0.01)
        gray_limped.append(ep)

    async def gray_heal():
        while gray_slowed:
            cd = chaos.get(gray_slowed.pop())
            if cd is not None:
                cd.heal_slow()
        while gray_limped:
            c.topology.heal_endpoint(gray_limped.pop())

    # -- disk-pressure fault surface (--disk-pressure): capacity faults.
    # The standing per-store quota (installed by run_soak) already makes
    # the budget/reclaim machinery work for a living; these actions push
    # a store over the edge — clamping its quota to just above live
    # usage, or bursting seeded ENOSPC into its writes — and the ladder
    # must shed writes retryably, reclaim, and RESUME with no restart. --------
    disk_squeezed: list[str] = []
    disk_bursting: list[str] = []

    def _disk_victim():
        up = [ep for ep in c.endpoints if ep in c.stores]
        if not up:
            raise SkipFault
        # prefer a store that currently LEADS something — a full
        # follower sheds nothing and reclaims nothing
        leaders = [ep for ep in up
                   if c.stores[ep].leader_region_ids()]
        return rng.choice(leaders or up)

    async def disk_quota_shrink():
        """Clamp the victim's quota to live usage + a sliver: the next
        seconds of appends hit the wall, ENOSPC latches the budget FULL,
        and reclaim has just enough headroom to free its way out."""
        ep = _disk_victim()
        limit, used = chaos[ep].quota_state()
        if limit is None:
            raise SkipFault
        target = used + 24 * 1024
        if target >= limit:
            raise SkipFault        # already squeezed near usage
        chaos[ep].shrink_quota(limit - target)
        # the store SEES the resize (its DiskBudget ceiling follows the
        # emulated volume, as statvfs capacity would on a real disk) —
        # used/target lands in NEAR_FULL territory, so the reclaim
        # ladder fires inside the reserved headroom instead of riding
        # blind into the hard wall
        st = c.stores.get(ep)
        if st is not None and st.disk_budget is not None:
            st.disk_budget.set_budget(target)
        disk_squeezed.append(ep)
        say(f"  nemesis: disk-quota-shrink on {ep} -> {target}b")

    async def disk_quota_restore():
        while disk_squeezed:
            ep = disk_squeezed.pop()
            cd = chaos.get(ep)
            if cd is not None:
                cd.set_quota(_DISK_QUOTA_BYTES)
            st = c.stores.get(ep)
            if st is not None and st.disk_budget is not None:
                st.disk_budget.set_budget(_DISK_QUOTA_BYTES)

    async def disk_enospc_burst():
        """Intermittent ENOSPC: ~25% of the victim's writes/renames fail
        while real usage sits under quota — the flaky-filesystem shape;
        flush failures must fail pending writes retryably (leader steps
        down, nothing acks) and never wedge the store."""
        ep = _disk_victim()
        say(f"  nemesis: disk-enospc-burst on {ep}")
        chaos[ep].set_enospc_burst(0.25, seed=rng.randrange(1 << 30))
        disk_bursting.append(ep)

    async def disk_burst_heal():
        while disk_bursting:
            cd = chaos.get(disk_bursting.pop())
            if cd is not None:
                cd.set_enospc_burst(0.0)

    # -- time-chaos fault surface (--clock-chaos): per-store injected
    # clocks drift/jump/freeze — composed with the leader kills and
    # partitions above — while lease reads keep flowing.  Safety must
    # come from the drift-bound lease shrink and the sentinel fence
    # (SAFE fallbacks), never from the clocks behaving. ------------------------
    clock_frozen: list[object] = []

    async def clock_chaos_step():
        """One seeded fault (drift / forward jump / freeze) on a random
        store's clock; a frozen clock unfreezes on the next hit."""
        clocks = list(getattr(c, "clocks", {}).items())
        if not clocks:
            raise SkipFault
        ep, ck = rng.choice(clocks)
        what = ck.chaos_step()
        say(f"  nemesis: clock {what} on {ep}")
        if ck.frozen:
            clock_frozen.append(ck)

    async def clock_leader_fast():
        """The classic lease hazard, aimed: the LEADER's clock runs 25%
        fast — past the declared 5% bound — so its unshrunk lease would
        outlive what followers granted in real time.  The shrunk window
        plus the sentinel fence must keep every lease read honest."""
        ep = c.leader_endpoint(rng.choice(sampled_regions))
        ck = getattr(c, "clocks", {}).get(ep)
        if ck is None:
            raise SkipFault
        say(f"  nemesis: clock leader-fast x1.25 on {ep}")
        if ck.frozen:
            ck.unfreeze()
        ck.set_rate(1.25)

    async def clock_unfreeze():
        # heal only LIVENESS faults: frozen clocks park election/beat
        # timers, so they thaw after the dwell — but accumulated drift
        # and jumps PERSIST across faults (real skew does not heal
        # itself), which is the regime the drift bound must survive
        while clock_frozen:
            ck = clock_frozen.pop()
            if ck.frozen:
                ck.unfreeze()

    if churn:
        churn_driver = MembershipChurn(c, sampled_regions[0], rng, say)

    actions = [
        NemesisAction("leader-kill", kill_leader, restart_killed,
                      dwell_s=0.7, weight=1.5,
                      check=with_conf_check(None)),
        NemesisAction("one-way-partition", one_way, heal_net, dwell_s=0.5,
                      check=with_conf_check(None)),
        NemesisAction("drops+delays", noise_on, noise_off, dwell_s=0.8,
                      check=with_conf_check(None)),
    ]
    if chaos and power_loss:
        actions.append(
            NemesisAction("power-loss", power_loss_kill,
                          power_loss_restart, dwell_s=0.6, weight=1.5,
                          check=with_conf_check(power_loss_ok)))
    if gray:
        # dwell long enough for the whole arc: EMAs cross thresholds,
        # hysteresis worsens to SICK (~eval_interval x worsen_after),
        # evacuation transfers fire, the client re-routes — all while
        # the fault still holds
        actions += [
            NemesisAction("gray-disk-stall", gray_disk_stall, gray_heal,
                          dwell_s=4.0, weight=1.5,
                          check=with_conf_check(None)),
            NemesisAction("gray-slow-store", gray_slow_store, gray_heal,
                          dwell_s=4.0, weight=1.0,
                          check=with_conf_check(None)),
            NemesisAction("gray-stalled-fsync", gray_stalled_fsync,
                          gray_heal, dwell_s=4.0, weight=1.0,
                          check=with_conf_check(None)),
            NemesisAction("gray-slow-endpoint", gray_slow_endpoint,
                          gray_heal, dwell_s=3.0, weight=1.0,
                          check=with_conf_check(None)),
        ]
    if disk_pressure:
        # dwell spans the whole arc at the 100ms health cadence: fill ->
        # FULL (writes shed) -> pressure-triggered snapshot reclaim ->
        # usage drops -> hysteresis folds back -> writes RESUME — all
        # while the fault still holds
        actions += [
            NemesisAction("disk-quota-shrink", disk_quota_shrink,
                          disk_quota_restore, dwell_s=6.0, weight=1.5,
                          check=with_conf_check(None)),
            NemesisAction("disk-enospc-burst", disk_enospc_burst,
                          disk_burst_heal, dwell_s=2.5, weight=1.0,
                          check=with_conf_check(None)),
        ]
    if clock_chaos:
        # high weight: clock faults should land MORE often than any
        # single network/kill fault so skew states overlap with them
        actions += [
            NemesisAction("clock-chaos", clock_chaos_step,
                          clock_unfreeze, dwell_s=1.2, weight=2.0,
                          check=with_conf_check(None)),
            NemesisAction("clock-leader-fast", clock_leader_fast,
                          clock_unfreeze, dwell_s=1.5, weight=1.5,
                          check=with_conf_check(None)),
        ]
    if churn_driver is not None:
        actions.append(
            NemesisAction("churn-crash", churn_crash, churn_crash_restart,
                          dwell_s=0.6, weight=1.5, check=churn_ok))
        churn_driver.start()
    if quiesce:
        # dwell past the store-lease expiry + randomized election spread
        # (~3x eto) so fail-over actually runs while the store is down
        eto_s = getattr(c, "election_timeout_ms", 400) / 1000.0
        actions.append(
            NemesisAction("store-kill-quiescent", quiescent_store_kill,
                          quiescent_store_restart,
                          dwell_s=max(2.5, 3.0 * eto_s), weight=1.5,
                          check=with_conf_check(None)))
    if topo is not None and geo:
        eto_s = getattr(c, "election_timeout_ms", 400) / 1000.0
        actions += [
            # dwell past fail-over so elections actually run ACROSS the
            # shaped WAN while a zone is dark
            NemesisAction("zone-partition", zone_partition, heal_topology,
                          dwell_s=max(1.2, 3.0 * eto_s), weight=1.5),
            NemesisAction("wan-degrade", wan_degrade, heal_topology,
                          dwell_s=1.0, weight=1.0),
            NemesisAction("link-flap", link_flap, heal_topology,
                          dwell_s=0.8, weight=1.0),
        ]
    if witness:
        # EVERY fault's post-heal probe also asserts witness safety
        for a in actions:
            a.check = with_witness_check(a.check)

    workers = [asyncio.ensure_future(worker(i)) for i in range(5)]
    try:
        await run_nemesis(actions, duration_s, rng,
                          on_tick=lambda n: say("  nemesis:", n))
        stop.set()
        if churn_driver is not None:
            await churn_driver.stop()
        await asyncio.gather(*workers)
        ops = h.ops()
        completed = sum(1 for o in ops if o.ret is not None)
        say(f"workload done: {len(ops)} ops ({completed} completed); "
            f"checking linearizability…")
        t0 = time.monotonic()
        rep = check_history(h)
        check_s = time.monotonic() - t0
        result = {
            "linearizable": rep.ok,
            "ops": len(ops),
            "completed": completed,
            "maybe_applied": len(ops) - completed,
            "faults": {a.name: a.applied for a in actions},
            "checker_s": round(check_s, 1),
        }
        if read_mix > 0:
            # targeted no-stale-read assertion (a read must observe
            # every write acked before it was issued) on top of the
            # full linearizability proof
            stale = check_stale_reads(ops, _seq_of)
            result["read_mix"] = read_mix
            result["read_from"] = read_from
            result["reads"] = sum(1 for o in ops if o.kind == "r")
            result["stale_reads"] = len(stale)
            if stale:
                result["linearizable"] = False
                result["stale_violations"] = stale[:5]
        # read-plane counters: store-wide confirm batching, per-batch
        # fence dedupe, lease vs SAFE vs forwarded serve counts, and
        # (when spread) the client's fan-out distribution
        read_plane: dict[str, int] = {}

        def _acc(d: dict) -> None:
            for k, v in d.items():
                read_plane[k] = read_plane.get(k, 0) + v

        for store in c.stores.values():
            if getattr(store, "read_batcher", None) is not None:
                _acc(store.read_batcher.counters())
            _acc({"kv_read_fences": store.kv_processor.read_fences,
                  "kv_fenced_reads": store.kv_processor.fenced_reads})
            for re_ in store._regions.values():
                node = re_.node
                if node is not None:
                    _acc(node.read_only_service.counters())
        if any(read_plane.values()):
            result["read_plane"] = read_plane
        # write-plane counters (ISSUE 15): store-wide append rounds +
        # ack-at-commit, live stores + everything retired by kill/restart
        write_plane: dict[str, int] = dict(
            (k, v) for k, v in c.retired_counters.items()
            if k.startswith("append_") or k == "fsm_eager_acked")
        for store in c.stores.values():
            ab = getattr(store, "append_batcher", None)
            if ab is not None:
                for k, v in ab.counters().items():
                    write_plane[k] = write_plane.get(k, 0) + v
            for re_ in store._regions.values():
                node = re_.node
                if node is not None:
                    write_plane["fsm_eager_acked"] = (
                        write_plane.get("fsm_eager_acked", 0)
                        + node.fsm_caller.eager_acked)
        if any(write_plane.values()):
            result["write_plane"] = write_plane
        if read_from != "leader":
            result["read_serves"] = dict(kv.read_serves)
        if chaos:
            injected: dict[str, int] = {}
            for cd in chaos.values():
                for k, v in cd.injected.items():
                    injected[k] = injected.get(k, 0) + v
            result["power_loss_crashes"] = sum(
                cd.crash_count for cd in chaos.values())
            result["storage_injections"] = injected
        if gray:
            # gray-failure plane: injection counts + the detection /
            # mitigation counters the acceptance criteria key on —
            # >0 evacuations proves the SICK score fired AND moved
            # leadership while the fault held
            slow_inj: dict[str, int] = {}
            for cd in chaos.values():
                for k, v in cd.slow_counts.items():
                    slow_inj[k] = slow_inj.get(k, 0) + v
            # live engines + everything retired by kill/restart: a
            # leader-kill landing on a store AFTER it evacuated must
            # not erase the evacuations from the run record
            rc = c.retired_counters
            evac = rc.get("evacuations", 0) \
                + sum(s.evacuations for s in c.stores.values())
            shed = rc.get("shed_items", 0) \
                + sum(s.kv_processor.shed_items
                      for s in c.stores.values())
            health_evals = rc.get("health_evaluations", 0) + sum(
                s.health.evaluations for s in c.stores.values()
                if s.health is not None)
            sick_rounds = rc.get("sick_rounds", 0) + sum(
                s.health.level_counts["sick"] for s in c.stores.values()
                if s.health is not None)
            result["gray"] = {
                "slow_injections": slow_inj,
                "health_evaluations": health_evals,
                "sick_rounds": sick_rounds,
                "evacuations": evac,
                "shed_items": shed,
            }
            # a long gray drive that never evacuated means detection or
            # mitigation is broken — fail the run, don't just log it
            result["gray_detection_ok"] = (evac > 0
                                           or duration_s < 120)
        if disk_pressure:
            # pressure-ladder counters: live stores + everything retired
            # by kill/restart (the gray retired-counter lesson), plus
            # the fault plane's own injection counts
            rc = c.retired_counters
            bsum: dict[str, int] = {}
            for s in c.stores.values():
                if s.disk_budget is not None:
                    for k, v in s.disk_budget.counters().items():
                        bsum[k] = bsum.get(k, 0) + v
            reclaims = rc.get("disk_reclaims", 0) \
                + sum(s.disk_reclaims for s in c.stores.values())
            sheds = rc.get("disk_shed_items", 0) \
                + sum(s.disk_shed_items for s in c.stores.values())
            resumes = rc.get("disk_pressure_resumes", 0) \
                + bsum.get("disk_pressure_resumes", 0)
            enospc_inj: dict[str, int] = {}
            for cd in chaos.values():
                for k, v in cd.enospc_counts.items():
                    enospc_inj[k] = enospc_inj.get(k, 0) + v
            result["disk"] = {
                "quota_bytes": _DISK_QUOTA_BYTES,
                "enospc_injections": enospc_inj,
                "enospc_observed": rc.get("disk_enospc_events", 0)
                + bsum.get("disk_enospc_events", 0),
                "near_full_rounds": rc.get("disk_near_full_rounds", 0)
                + bsum.get("disk_near_full_rounds", 0),
                "full_rounds": rc.get("disk_full_rounds", 0)
                + bsum.get("disk_full_rounds", 0),
                "reclaims": reclaims,
                "shed_writes": sheds,
                "resumes": resumes,
            }
            # acceptance gate: a long drive must show the WHOLE ladder
            # — >=1 pressure-triggered reclaim, >=1 FULL shed, and >=1
            # FULL->resume WITHOUT a restart — or the run fails
            result["disk_pressure_ok"] = (
                (reclaims > 0 and sheds > 0 and resumes > 0)
                or duration_s < 120)
        if clock_chaos:
            # clock plane: what the nemesis injected vs what the stores
            # detected (sentinel) and refused to serve on (fenced
            # leases + SAFE fallbacks) — live stores plus everything
            # retired by kill/restart (the gray retired-counter lesson)
            rc = c.retired_counters
            clock_inj: dict[str, int] = {}
            for ck in getattr(c, "clocks", {}).values():
                for k, v in ck.faults.items():
                    clock_inj[k] = clock_inj.get(k, 0) + v
            sent = {k: rc.get(k, 0)
                    for k in ("clock_skew_samples", "clock_anomalies",
                              "clock_lease_fenced")}
            for s in c.stores.values():
                for k, v in s.clock_sentinel.counters().items():
                    if k in sent:
                        sent[k] += v
            fallbacks = rc.get("lease_fallbacks", 0)
            for s in c.stores.values():
                for re_ in s._regions.values():
                    if re_.node is not None:
                        fallbacks += \
                            re_.node.read_only_service.lease_fallbacks
            result["clock"] = {
                "injections": clock_inj,
                **sent,
                "lease_fallbacks": fallbacks,
                "peer_skews": {ep: s.clock_sentinel.peers()
                               for ep, s in sorted(c.stores.items())},
            }
            # acceptance gate: with every clock broken on purpose past
            # the declared bound, at least one lease check must have
            # refused the fast path (sentinel fence) or fallen back to
            # a SAFE quorum round — a long drive where every lease
            # check still passed means the hardening never engaged
            result["clock_detection_ok"] = (
                sent["clock_lease_fenced"] + fallbacks > 0
                or duration_s < 120)
        if churn_driver is not None:
            result["membership"] = churn_driver.summary()
        # beat-plane + quiescence counters (HeartbeatHub.counters() via
        # each live store's NodeManager) — the soak stats line's view of
        # how much idle traffic hibernation actually removed
        hub_totals: dict[str, int] = {}
        for store in c.stores.values():
            for k, v in store.node_manager.heartbeat_hub.counters().items():
                hub_totals[k] = hub_totals.get(k, 0) + v
        if hub_totals:
            result["hub"] = hub_totals
        if quiesce:
            result["store_kills_while_quiescent"] = len(quiesce_kill_counts)
            result["quiescent_groups_at_kill"] = quiesce_kill_counts
        if topo is not None:
            result["geo_zones"] = geo
            result["topology"] = dict(topo.counters)
        if witness:
            await witness_safety_check()   # final sweep, aborts on breach
            result["witness_safe"] = True
            stripped = 0
            for node in witness_nodes():
                for i in range(node.log_manager.first_log_index(),
                               node.log_manager.last_log_index() + 1):
                    e = node.log_manager.get_entry(i)
                    assert e is None or e.data == b"" or e.type.value == 2, \
                        f"witness journaled a payload at index {i}"
                    stripped += 1
            result["witness_journal_entries_checked"] = stripped
        if not rep.ok:
            result["violation"] = str(rep)
        if dump_history and not rep.ok:
            import json as _json
            with open(dump_history, "w") as f:
                for o in ops:
                    f.write(_json.dumps({
                        "id": o.op_id, "client": o.client, "kind": o.kind,
                        "args": [a.hex() if isinstance(a, bytes) else a
                                 for a in o.args],
                        "invoke": o.invoke, "ret": o.ret,
                        "result": (o.result.hex()
                                   if isinstance(o.result, bytes)
                                   else o.result)}) + "\n")
            result["history_dump"] = dump_history
        if trace:
            from tpuraft.util.trace import TRACER

            result["trace"] = TRACER.stats()
            result["trace_file"] = trace
            result["trace_spans"] = TRACER.export_chrome(trace)
        # flight recorder: a failing run carries the protocol-event
        # lead-up in its OWN report — no re-run with prints needed.
        # note_anomaly snapshots the ring so later teardown events
        # can't churn the incident context away.
        if not result["linearizable"] \
                or not result.get("gray_detection_ok", True) \
                or not result.get("disk_pressure_ok", True) \
                or not result.get("clock_detection_ok", True):
            from tpuraft.util.trace import RECORDER

            RECORDER.note_anomaly(
                "soak_failure",
                ("oracle: " + result.get("violation", ""))[:200]
                if not result["linearizable"]
                else ("gray detection never fired"
                      if not result.get("gray_detection_ok", True)
                      else ("disk-pressure ladder never completed"
                            if not result.get("disk_pressure_ok", True)
                            else "clock hardening never engaged")))
            result["flight_recorder"] = RECORDER.dump(256)
            result["recorder_anomalies"] = [
                {"ts": a["ts"], "reason": a["reason"],
                 "detail": a["detail"]}
                for a in RECORDER.anomaly_report()]
        return result
    finally:
        # also on checker errors / cancellation: no leaked workers or
        # still-running stores
        stop.set()
        if churn_driver is not None:
            await churn_driver.stop()
        for w in workers:
            w.cancel()
        await asyncio.gather(*workers, return_exceptions=True)
        await kv.shutdown()
        for ep in list(c.stores):
            await c.stop_store(ep)
        ct = getattr(c, "_client_t", None)
        if ct is not None and hasattr(ct, "close"):
            await ct.close()
        # chaos uninstall happens in run_soak's outer finally (it must
        # cover startup failures before this block exists too)


async def run_hotspot_soak(duration_s: float, n_stores: int,
                           n_regions: int, seed: int, data_path: str,
                           verbose: bool) -> dict:
    """Zipfian-hotspot telemetry soak (fleet observability plane).

    Boots a REAL in-proc PD alongside the stores, drives a skewed
    workload (80% of ops into a 3-region hot set, the rest uniform),
    SHIFTS the hot set mid-run, and asserts the PD ClusterView's top-K
    identifies the new hot regions within 3 heartbeat rounds of the
    shift — the end-to-end accuracy contract for the heat plane
    (store intake -> EWMA fold -> noise-gated heartbeat rows -> PD
    stats -> cluster view)."""
    import os as _os

    from tpuraft.rheakv.pd_client import RemotePlacementDriverClient
    from tpuraft.rheakv.pd_server import (PlacementDriverOptions,
                                          PlacementDriverServer)

    rng = random.Random(seed)
    hb_ms = 500
    c = SoakCluster(n_stores, data_path, n_regions=n_regions,
                    pd_endpoint="127.0.0.1:7100",
                    heartbeat_interval_ms=hb_ms)

    def say(*a):
        if verbose:
            print(*a, flush=True)

    # PD first (single-node metadata group on the same fabric): stores
    # attach via heartbeats, the first batch full-syncs every region
    pd_ep = c.pd_endpoint
    server = RpcServer(pd_ep)
    c.net.bind(server)
    c.net.start_endpoint(pd_ep)
    pd_transport = InProcTransport(c.net, pd_ep)
    pd = PlacementDriverServer(
        PlacementDriverOptions(
            endpoints=[pd_ep], election_timeout_ms=300,
            data_path=_os.path.join(data_path, "pd")),
        pd_ep, server, pd_transport)
    await pd.start()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if pd.node is not None and pd.node.is_leader():
            break
        await asyncio.sleep(0.05)
    else:
        raise TimeoutError("PD never elected")

    for ep in c.endpoints:
        await c.start_store(ep)
    kv = RheaKVStore(FakePlacementDriverClient(
        [r.copy() for r in c.regions]), c.client_transport(),
        max_retries=1, jitter_seed=rng.randrange(1 << 30))
    await kv.start()
    pd_view = RemotePlacementDriverClient(
        InProcTransport(c.net, "hotspot-admin:0"), [pd_ep])

    hot_n = 3
    hot_a = sorted(rng.sample(range(n_regions), hot_n))
    hot_b = sorted(rng.sample(
        [r for r in range(n_regions) if r not in hot_a], hot_n))
    hot_now = list(hot_a)
    payload = b"h" * 64

    def hot_key() -> bytes:
        # region k+1 owns [k%06d, (k+1)%06d)
        if rng.random() < 0.8:
            k = rng.choice(hot_now)
        else:
            k = rng.randrange(n_regions)
        return b"k%06d/h%02d" % (k, rng.randrange(8))

    stop = asyncio.Event()
    ops = [0]
    errs = [0]

    async def driver() -> None:
        while not stop.is_set():
            key = hot_key()
            try:
                if rng.random() < 0.5:
                    await kv.put(key, payload)
                else:
                    await kv.get(key)
                ops[0] += 1
            except Exception:
                errs[0] += 1
            await asyncio.sleep(0.001)

    drivers = [asyncio.ensure_future(driver()) for _ in range(4)]
    half = max(4.0, duration_s / 2.0)
    await asyncio.sleep(half)

    # phase A sanity: the PD already ranks the current hot set on top
    view = await pd_view.cluster_describe(top_k=8)
    top_a = [r["region"] for r in (view or {}).get("hot", [])]
    phase_a_ok = all((k + 1) in top_a for k in hot_a)
    say(f"phase A top-K {top_a} (true {[k + 1 for k in hot_a]})")

    # the shift: re-aim the hot set, then count heartbeat rounds until
    # the view's top-K contains every NEW hot region
    hot_now[:] = hot_b
    true_b = [k + 1 for k in hot_b]
    detect_rounds = -1
    rounds_slept = 0
    top_b: list = []
    for rnd in range(1, 9):
        await asyncio.sleep(hb_ms / 1000.0)
        rounds_slept = rnd
        view = await pd_view.cluster_describe(top_k=8)
        top_b = [r["region"] for r in (view or {}).get("hot", [])]
        say(f"round {rnd}: top-K {top_b} (want {true_b})")
        if all(r in top_b for r in true_b):
            detect_rounds = rnd
            break
    # credit the rounds already slept, detected or not — a failing run
    # must not overshoot the requested duration
    await asyncio.sleep(max(0.0, duration_s - half - rounds_slept
                            * hb_ms / 1000.0))
    stop.set()
    for d in drivers:
        d.cancel()

    view = await pd_view.cluster_describe(top_k=8) or {}
    # the hot_region detector (the flight-recorder signal the split/
    # move policy will consume) must also have flagged the new hot set
    flag_ok = all(r in view.get("hot_flagged", []) for r in true_b)
    hotspot_ok = phase_a_ok and 0 < detect_rounds <= 3 and flag_ok
    result = {
        "mode": "hotspot",
        "duration_s": duration_s,
        "regions": n_regions,
        "stores": n_stores,
        "ops": ops[0],
        "errors": errs[0],
        "heartbeat_ms": hb_ms,
        "true_hot_a": [k + 1 for k in hot_a],
        "true_hot_b": true_b,
        "phase_a_topk_ok": phase_a_ok,
        "detect_rounds": detect_rounds,
        "hot_flag_ok": flag_ok,
        "pd_top_hot": top_b,
        "pd_hot_flagged": view.get("hot_flagged", []),
        "pd_heat_rows": pd.hb_heat_rows,
        "zone_rates": view.get("zone_rates", {}),
        "hotspot_ok": hotspot_ok,
        # the linearizability key so main()'s exit gate composes
        "linearizable": True,
    }
    await kv.shutdown()
    for ep in list(c.stores):
        await c.stop_store(ep)
    await pd.shutdown()
    return result


async def run_lifecycle_soak(duration_s: float, n_stores: int,
                             n_regions: int, seed: int, data_path: str,
                             verbose: bool) -> dict:
    """Region-lifecycle soak (ISSUE 20): a lifecycle-enabled PD runs
    the full actuation loop against a live fleet under a SHIFTING
    zipfian hotspot.

    Exit gates: >0 heat-driven splits, >0 cold merges, >0 cross-store
    moves; the PD's region set still tiles the keyspace (the
    coverage oracle); the single-writer-per-key workload observed no
    lost ack / stale read through all the churn; and the post-shift
    cold keyspace hibernated (engine quiescence on idle groups).

    Stores 1..3 host every region initially and store 4 hosts none —
    the imbalance the move actuator must fix (add-learner -> catch up
    -> joint promote+remove onto the empty store)."""
    import os as _os

    from tpuraft.rheakv.keyspace import coverage_errors
    from tpuraft.rheakv.pd_client import RemotePlacementDriverClient
    from tpuraft.rheakv.pd_server import (PlacementDriverOptions,
                                          PlacementDriverServer)

    rng = random.Random(seed)
    hb_ms = 300
    n_stores = max(4, n_stores)
    c = SoakCluster(n_stores, data_path, n_regions=n_regions,
                    engine=True, pd_endpoint="127.0.0.1:7200",
                    heartbeat_interval_ms=hb_ms,
                    quiesce_after_rounds=3)
    # heat splits mint groups mid-run: leave engine [G] headroom
    c.engine_group_cap = 1 << max(6, (n_regions * 2 + 8).bit_length())
    home = c.endpoints[:3]
    for r in c.regions:
        r.peers = list(home)   # store 4+: move destination only

    def say(*a):
        if verbose:
            print(*a, flush=True)

    pd_ep = c.pd_endpoint
    server = RpcServer(pd_ep)
    c.net.bind(server)
    c.net.start_endpoint(pd_ep)
    pd = PlacementDriverServer(
        PlacementDriverOptions(
            endpoints=[pd_ep], election_timeout_ms=300,
            data_path=_os.path.join(data_path, "pd"),
            lifecycle=True,
            lifecycle_heat_split_min_keys=16,
            lifecycle_merge_cooldown_s=1.0,
            lifecycle_min_regions=max(4, n_regions // 2),
            lifecycle_move_cooldown_s=1.0,
            lifecycle_move_imbalance=2),
        pd_ep, server, InProcTransport(c.net, pd_ep))
    await pd.start()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if pd.node is not None and pd.node.is_leader():
            break
        await asyncio.sleep(0.05)
    else:
        raise TimeoutError("PD never elected")

    for ep in c.endpoints:
        await c.start_store(ep)
    kv = RheaKVStore(
        RemotePlacementDriverClient(
            InProcTransport(c.net, "lifecycle-pdc:0"), [pd_ep]),
        c.client_transport(), timeout_ms=4000, max_retries=12,
        jitter_seed=rng.randrange(1 << 30))
    await kv.start()

    # wait until the PD learned the whole fleet from heartbeats
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if len(pd.fsm.regions) >= n_regions:
            break
        await asyncio.sleep(0.1)
    else:
        raise TimeoutError("PD never learned the initial region set")

    hot_n = min(3, max(1, n_regions // 4))
    hot_a = sorted(rng.sample(range(n_regions), hot_n))
    hot_b = sorted(rng.sample(
        [k for k in range(n_regions) if k not in hot_a], hot_n))
    hot_now = list(hot_a)

    # single-writer-per-key linearizability proxy: every key is owned
    # by ONE driver task; a read must never return a sequence older
    # than the last ACKED write (lost ack) nor a missing value after
    # one was acked (lost keyspace — the merge-bug signature)
    acked: dict = {}
    issued: dict = {}
    seqs: dict = {}
    violations: list = []
    ops = [0]
    errs = [0]
    stop = asyncio.Event()
    n_drivers = 3

    def _key(k: int, j: int) -> bytes:
        # region k+1 owns [k%06d, (k+1)%06d)
        return b"k%06d/%03d" % (k, j)

    async def driver(t: int) -> None:
        while not stop.is_set():
            if rng.random() < 0.85:
                k = rng.choice(hot_now)
                j = rng.randrange(64)
            else:
                k = rng.randrange(n_regions)
                j = rng.randrange(12)
            j = (j - j % n_drivers) + t     # task t owns its j-slice
            key = _key(k, j)
            try:
                if rng.random() < 0.55:
                    seq = seqs.get(key, 0) + 1
                    seqs[key] = seq
                    issued[key] = seq
                    if await kv.put(key, b"s%010d" % seq):
                        acked[key] = seq
                    ops[0] += 1
                else:
                    got = await kv.get(key)
                    floor = acked.get(key, -1)
                    if got is None:
                        if floor >= 0:
                            violations.append(
                                f"{key!r}: acked seq {floor} vanished")
                    else:
                        seen = int(got[1:])
                        if seen < floor:
                            violations.append(
                                f"{key!r}: read seq {seen} < acked "
                                f"{floor}")
                    ops[0] += 1
            except Exception:
                errs[0] += 1
            await asyncio.sleep(0.001)

    drivers = [asyncio.ensure_future(driver(t)) for t in range(n_drivers)]
    half = max(5.0, duration_s / 2.0)
    await asyncio.sleep(half)
    say(f"shift: hot {hot_a} -> {hot_b}; pd regions="
        f"{len(pd.fsm.regions)} splits={pd.heat_splits_ordered} "
        f"merges={pd.merges_completed} moves={pd.moves_ordered}")
    hot_now[:] = hot_b
    await asyncio.sleep(max(0.0, duration_s - half))
    stop.set()
    for d in drivers:
        d.cancel()

    # quiet tail: let in-flight merges finalize and idle groups (the
    # merged-away cold keyspace's survivors) hibernate
    await asyncio.sleep(max(3.0, hb_ms / 1000.0 * 6))
    moves_applied = sum(s.moves_applied for s in c.stores.values())
    merges_led = sum(s.merges_led for s in c.stores.values())
    occ = [s.tick_occupancy() for s in c.stores.values()]
    hibernated = sum(q for _, q in occ)
    coverage = coverage_errors(pd.fsm.regions.values())
    coverage_detail = {}
    if coverage:
        # PD-view corruption forensics: the PD's record (with epochs)
        # next to every store's live truth for the same ids, so a
        # stale-wide record is attributable to the exact epoch race
        coverage_detail["pd"] = {
            rid: [r.start_key.decode("latin1"), r.end_key.decode("latin1"),
                  r.epoch.version, r.epoch.conf_ver]
            for rid, r in sorted(pd.fsm.regions.items())}
        coverage_detail["stores"] = {
            ep: {e.region.id: [e.region.start_key.decode("latin1"),
                               e.region.end_key.decode("latin1"),
                               e.region.epoch.version,
                               e.region.epoch.conf_ver]
                 for e in s._regions.values()}
            for ep, s in c.stores.items()}
    view = await RemotePlacementDriverClient(
        InProcTransport(c.net, "lifecycle-adm:0"),
        [pd_ep]).cluster_describe(top_k=8) or {}
    lifecycle_ok = (
        pd.heat_splits_ordered > 0
        and pd.merges_completed > 0 and merges_led > 0
        and moves_applied > 0
        and not coverage
        and not violations)
    hibernate_ok = hibernated > 0
    result = {
        "mode": "lifecycle",
        "duration_s": duration_s,
        "regions_initial": n_regions,
        "regions_final": len(pd.fsm.regions),
        "stores": n_stores,
        "ops": ops[0],
        "errors": errs[0],
        "heartbeat_ms": hb_ms,
        "true_hot_a": [k + 1 for k in hot_a],
        "true_hot_b": [k + 1 for k in hot_b],
        "heat_splits_ordered": pd.heat_splits_ordered,
        "merges_ordered": pd.merges_ordered,
        "merges_completed": pd.merges_completed,
        "merges_led": merges_led,
        "moves_ordered": pd.moves_ordered,
        "moves_applied": moves_applied,
        "coverage_errors": coverage,
        "coverage_detail": coverage_detail,
        "lin_violations": violations[:8],
        "hibernated_replicas": hibernated,
        "hibernate_ok": hibernate_ok,
        "pd_lifecycle_view": view.get("lifecycle"),
        "lifecycle_ok": lifecycle_ok and hibernate_ok,
        "linearizable": not violations,
    }
    await kv.shutdown()
    for ep in list(c.stores):
        await c.stop_store(ep)
    await pd.shutdown()
    return result


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--duration", type=float, default=30)
    ap.add_argument("--stores", type=int, default=3)
    ap.add_argument("--keys", type=int, default=6,
                    help="distinct keys (fewer = more contention; "
                         "checker cost grows with ops/key)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--data", default="",
                    help="durable state dir (default: a temp dir)")
    ap.add_argument("--transport", choices=["inproc", "native"],
                    default="inproc",
                    help="'native': C++ epoll sockets + C++ KV engines, "
                         "faults injected per-store")
    ap.add_argument("--lease-reads", action="store_true",
                    help="LEASE_BASED readIndex (no per-read quorum "
                         "round; assumes bounded clock drift)")
    ap.add_argument("--dump-history", default="",
                    help="on violation, write the full op history "
                         "(JSON lines) here for offline analysis")
    ap.add_argument("--regions", type=int, default=1,
                    help=">1: split the keyspace into this many raft "
                         "groups per store (in-proc fabric only) — the "
                         "G>=1K chaos configuration")
    ap.add_argument("--engine", action="store_true",
                    help="MultiRaftEngine protocol plane + multilog "
                         "journal per store (required reading at "
                         "region density)")
    ap.add_argument("--election-timeout-ms", type=int, default=400)
    ap.add_argument("--power-loss", action="store_true",
                    help="add power-loss crashes to the nemesis menu: "
                         "a store is killed at a random instant and "
                         "restarted from its durable-only on-disk image "
                         "(torn writes / lost fsyncs / bit flips in the "
                         "unsynced tails; tpuraft/storage/fault.py)")
    ap.add_argument("--churn", action="store_true",
                    help="continuous membership churn while faults fly: "
                         "add/remove voters, add/promote/remove "
                         "learners, leadership transfers — plus a "
                         "stage-trap nemesis action that lands seeded "
                         "crashes inside each joint-consensus stage "
                         "(catching_up / joint / stable); conf "
                         "invariants asserted after every fault")
    ap.add_argument("--quiesce", action="store_true",
                    help="enable group quiescence (hibernate-raft, "
                         "quiesce_after_rounds=4; requires --engine) and "
                         "add a store-kill-while-quiescent nemesis "
                         "action: a store leading quiescent groups is "
                         "killed, and its dependents must elect via "
                         "store-lease expiry within the normal "
                         "fault-detection envelope")
    ap.add_argument("--geo", type=int, default=0, metavar="ZONES",
                    help="geo scenario: tag stores round-robin into this "
                         "many zones and shape every link through a "
                         "seeded NetworkTopology (asymmetric WAN latency "
                         "+ jitter + loss); adds zone-partition, "
                         "wan-degrade and link-flap to the nemesis menu")
    ap.add_argument("--witness", action="store_true",
                    help="(with --geo) the last store joins every region "
                         "as a WITNESS: votes + metadata-only journal, "
                         "never leads; witness safety (never leader, "
                         "never a ballot window, no payload journaled) "
                         "is asserted after every fault")
    ap.add_argument("--gray", action="store_true",
                    help="gray-failure (fail-slow) nemesis menu: "
                         "disk-stall, slow-store, stalled-fsync and "
                         "slow-endpoint faults — the victim stays "
                         "'alive' while limping; store health scoring "
                         "must detect it and evacuate leadership "
                         "(in-proc fabric, no --engine)")
    ap.add_argument("--disk-pressure", action="store_true",
                    help="capacity-fault nemesis menu: every store runs "
                         "under a standing ChaosDir byte quota (matched "
                         "by its DiskBudget ceiling), plus quota-shrink "
                         "and seeded-ENOSPC-burst faults; the pressure "
                         "ladder must reclaim at NEAR_FULL, shed writes "
                         "retryably at FULL (reads keep serving), and "
                         "resume after reclaim without a restart "
                         "(in-proc fabric, no --engine)")
    ap.add_argument("--clock-chaos", action="store_true",
                    help="time-chaos nemesis menu: every store runs on "
                         "its own injected ChaosClock (survives "
                         "restarts) with seeded drift / forward-jump / "
                         "freeze faults plus a targeted leader-fast "
                         "fault, composed with leader kills and "
                         "partitions; stores declare a 5%% drift bound "
                         "and the run fails unless the shrunk lease "
                         "window / clock sentinel forced at least one "
                         "clock-independent serve (in-proc fabric, no "
                         "--engine); combine with --lease-reads "
                         "--read-mix for the stale-read oracle")
    ap.add_argument("--kv-batching", action="store_true",
                    help="drive load through the batching client: ops "
                         "coalesce into store-grouped kv_command_batch "
                         "RPCs; linearizability is checked per op as "
                         "usual (batched items ack/apply atomically)")
    ap.add_argument("--write-burst", action="store_true",
                    help="write-heavy load shape (ISSUE 15): each worker "
                         "issues bursts of 4 concurrent puts (~10%% "
                         "reads) so the store-wide append rounds + "
                         "ack-at-commit pipeline run saturated under "
                         "the nemesis menu; write-plane counters land "
                         "in the report")
    ap.add_argument("--read-mix", type=float, default=0.0, metavar="FRAC",
                    help="read-dominant workload: reads with this "
                         "probability (e.g. 0.95), writes carry per-key "
                         "monotone sequence values (one writer per key) "
                         "so the checker additionally asserts NO STALE "
                         "READ — a read must observe every write acked "
                         "before it was issued — under the full nemesis "
                         "menu")
    ap.add_argument("--read-from",
                    choices=["leader", "follower", "learner", "any"],
                    default="leader",
                    help="route GETs to this replica class (client "
                         "read fan-out; follower/learner serve locally "
                         "after a forwarded-ReadIndex fence)")
    ap.add_argument("--trace", default="",
                    help="enable sampled product tracing (5%% of ops) "
                         "and export a perfetto-loadable Chrome trace "
                         "JSON to this path at the end")
    ap.add_argument("--hotspot", action="store_true",
                    help="zipfian-hotspot telemetry soak: real in-proc "
                         "PD, skewed load with a mid-run hot-set "
                         "shift; asserts the PD ClusterView top-K "
                         "identifies the new hot regions within 3 "
                         "heartbeat rounds (fleet observability)")
    ap.add_argument("--lifecycle", action="store_true",
                    help="region-lifecycle soak: lifecycle-enabled PD "
                         "(heat splits + cold merges + cross-store "
                         "moves) under a shifting zipfian hotspot; "
                         "gates on >0 of each actuation, keyspace "
                         "coverage, per-key linearizability and cold-"
                         "group hibernation")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args()
    data = args.data or tempfile.mkdtemp(prefix="tpuraft-soak-")
    if args.lifecycle:
        import json

        n_regions = args.regions if args.regions > 1 else 12
        result = asyncio.run(run_lifecycle_soak(
            args.duration, args.stores, n_regions, args.seed, data,
            args.verbose))
        print(json.dumps(result))
        raise SystemExit(0 if result["lifecycle_ok"] else 1)
    if args.hotspot:
        import json

        n_regions = args.regions if args.regions > 1 else 24
        result = asyncio.run(run_hotspot_soak(
            args.duration, args.stores, n_regions, args.seed, data,
            args.verbose))
        print(json.dumps(result))
        raise SystemExit(0 if result["hotspot_ok"] else 1)
    result = asyncio.run(run_soak(args.duration, args.stores, args.keys,
                                  args.seed, data, args.verbose,
                                  transport=args.transport,
                                  dump_history=args.dump_history,
                                  lease_reads=args.lease_reads,
                                  n_regions=args.regions,
                                  engine=args.engine,
                                  election_timeout_ms=args.election_timeout_ms,
                                  power_loss=args.power_loss,
                                  churn=args.churn,
                                  quiesce=args.quiesce,
                                  kv_batching=args.kv_batching,
                                  geo=args.geo,
                                  witness=args.witness,
                                  read_mix=args.read_mix,
                                  read_from=args.read_from,
                                  gray=args.gray,
                                  write_burst=args.write_burst,
                                  disk_pressure=args.disk_pressure,
                                  clock_chaos=args.clock_chaos,
                                  trace=args.trace))
    import json

    print(json.dumps(result))
    ok = result["linearizable"] \
        and result.get("gray_detection_ok", True) \
        and result.get("disk_pressure_ok", True) \
        and result.get("clock_detection_ok", True)
    raise SystemExit(0 if ok else 1)


if __name__ == "__main__":
    main()
