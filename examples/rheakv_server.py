"""Standalone RheaKV store server: one OS process per store.

Reference parity: the server side of ``example:rheakv/*`` (SURVEY.md
§3.3) — the reference boots `RheaKVStore` server mains from yaml
topologies; here the topology is CLI flags shared by every member.

    # a 3-store cluster, 4 pre-split regions, durable native engines:
    python -m examples.rheakv_server --serve 127.0.0.1:9001 \\
        --stores 127.0.0.1:9001,127.0.0.1:9002,127.0.0.1:9003 \\
        --regions 4 --data /tmp/rkv1 [--transport native] [--store native]

Every member derives the same region layout from (--stores, --regions),
so a client needs only the store list (see `client_for`); region
discovery and split survival ride the `kv_list_regions` refresh path.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import signal
import sys

from examples.rheakv_bench import make_regions
from tpuraft.rheakv.client import RheaKVStore
from tpuraft.rheakv.pd_client import FakePlacementDriverClient
from tpuraft.rheakv.store_engine import StoreEngine, StoreEngineOptions


def derive_regions(stores: list[str], n_regions: int):
    regions = make_regions(n_regions)
    for r in regions:
        r.peers = list(stores)
    return regions


async def serve(endpoint: str, stores: list[str], n_regions: int,
                data_path: str, transport_kind: str = "tcp",
                store_kind: str = "memory",
                pd_endpoints: list[str] | None = None,
                log_scheme: str = "file",
                metrics_port: int | None = None,
                eto_ms: int = 1000,
                apply_lane: bool = False,
                engine: bool = False,
                drain_timeout_s: float = 10.0,
                boot_delay_s: float = 0.0) -> None:
    if boot_delay_s:
        # fault-injection hook: a supervised restart that comes up slow
        # (cold page cache, crash-loop backoff) — lets tests prove the
        # readiness probe really gates client traffic
        await asyncio.sleep(boot_delay_s)
    if transport_kind == "native":
        from tpuraft.rpc.native_tcp import NativeTcpRpcServer as Server
        from tpuraft.rpc.native_tcp import NativeTcpTransport as Transport
    else:
        from tpuraft.rpc.tcp import TcpRpcServer as Server
        from tpuraft.rpc.tcp import TcpTransport as Transport

    server = Server(endpoint)
    await server.start()
    transport = Transport(endpoint=endpoint)
    opts = StoreEngineOptions(
        server_id=endpoint,
        initial_regions=derive_regions(stores, n_regions),
        data_path=data_path,
        election_timeout_ms=eto_ms,
        log_scheme=log_scheme,
        metrics_port=metrics_port,
        apply_lane=apply_lane,
    )
    if store_kind == "native":
        from tpuraft.rheakv.native_store import NativeRawKVStore
        base = f"{data_path}/kv_{endpoint.replace(':', '_')}"
        # the C++ engine mkdirs only the leaf — ensure the parents exist
        os.makedirs(os.path.dirname(base) or ".", exist_ok=True)
        opts.raw_store_factory = lambda: NativeRawKVStore(base)
    pd_client = None
    if pd_endpoints:
        from tpuraft.rheakv.pd_client import RemotePlacementDriverClient
        pd_client = RemotePlacementDriverClient(transport, pd_endpoints)
    raft_engine = None
    if engine:
        # ONE MultiRaftEngine drives every region node of this store
        # with a fused [G] tick (StoreEngine starts/stops it); capacity
        # sized to the next power of two above the region count so
        # splits can land without an immediate _grow
        from tpuraft.core.engine import MultiRaftEngine
        from tpuraft.options import TickOptions
        cap = 1 << max(4, (n_regions + 3).bit_length())
        raft_engine = MultiRaftEngine(TickOptions(
            max_groups=cap, max_peers=max(4, len(stores) + 1),
            tick_interval_ms=20))
    engine = StoreEngine(opts, server, transport,
                         multi_raft_engine=raft_engine, pd_client=pd_client)
    await engine.start()
    # SIGTERM = drain: bounce NEW work retryably (ERR_STORE_BUSY), wait
    # for everything already admitted to ack, then exit 0 — the process
    # supervisor's clean-stop contract (SIGKILL is the crash path)
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    try:
        loop.add_signal_handler(signal.SIGTERM, stop.set)
        loop.add_signal_handler(signal.SIGINT, stop.set)
    except NotImplementedError:   # non-unix event loop
        pass
    # machine-readable readiness line FIRST (supervisors parse it to
    # gate client traffic), the human line after
    print("READY " + json.dumps({
        "endpoint": endpoint, "pid": os.getpid(),
        "metrics_port": engine.metrics_http_port,
        "regions": n_regions}), flush=True)
    print(f"rheakv store {endpoint} up "
          f"({n_regions} regions, {len(stores)} stores)"
          + (f", /metrics on :{engine.metrics_http_port}"
             if engine.metrics_http_port else ""), flush=True)
    try:
        await stop.wait()
        clean = await engine.drain(drain_timeout_s)
        print("DRAINED " + json.dumps({"clean": bool(clean)}), flush=True)
    finally:
        await engine.shutdown()
        await server.stop()
        await transport.close()


def client_for(stores: list[str], n_regions: int,
               transport=None, **kw) -> RheaKVStore:
    """Client against a cluster started with the same (stores, regions)."""
    if transport is None:
        from tpuraft.rpc.tcp import TcpTransport
        transport = TcpTransport()
    pd = FakePlacementDriverClient(derive_regions(stores, n_regions))
    return RheaKVStore(pd, transport, **kw)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--serve", required=True, help="this store's ip:port")
    ap.add_argument("--stores", required=True,
                    help="comma-separated store endpoints (all members)")
    ap.add_argument("--regions", type=int, default=2)
    ap.add_argument("--data", required=True, help="durable state dir")
    ap.add_argument("--transport", choices=["tcp", "native"], default="tcp")
    ap.add_argument("--log-scheme", choices=["file", "multilog"],
                    default="file",
                    help="per-region segment dirs, or ONE shared C++ journal engine per store (group-commit fsync)")
    ap.add_argument("--store", choices=["memory", "native"],
                    default="memory")
    ap.add_argument("--pd", default="",
                    help="comma-separated PD endpoints: heartbeat region "
                         "meta + stats there and execute its instructions "
                         "(splits, leader transfers)")
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="serve Prometheus text at GET /metrics on this "
                         "port (0 = ephemeral, printed at boot); "
                         "omit = off — `admin.py metrics` still scrapes "
                         "over the admin transport")
    ap.add_argument("--eto-ms", type=int, default=1000,
                    help="election timeout (ms)")
    ap.add_argument("--apply-lane", action="store_true",
                    help="run FSM applies + fenced reads on a dedicated "
                         "worker lane thread (one hot store saturates "
                         ">1 core)")
    ap.add_argument("--engine", action="store_true",
                    help="drive all region nodes from ONE MultiRaftEngine "
                         "(fused [G] device/numpy tick) instead of "
                         "per-node timers; witness members, priority "
                         "re-election and device read fences all ride "
                         "the engine lanes")
    ap.add_argument("--drain-timeout", type=float, default=10.0,
                    help="seconds to wait for in-flight work on SIGTERM")
    ap.add_argument("--boot-delay", type=float, default=0.0,
                    help="sleep this long before serving (fault-injection "
                         "hook for readiness-gating tests)")
    args = ap.parse_args()
    stores = [s for s in args.stores.split(",") if s]
    if args.serve not in stores:
        print("error: --serve must be one of --stores", file=sys.stderr)
        sys.exit(2)
    try:
        asyncio.run(serve(args.serve, stores, args.regions, args.data,
                          args.transport, args.store,
                          [e for e in args.pd.split(",") if e] or None,
                          log_scheme=args.log_scheme,
                          metrics_port=args.metrics_port,
                          eto_ms=args.eto_ms,
                          apply_lane=args.apply_lane,
                          engine=args.engine,
                          drain_timeout_s=args.drain_timeout,
                          boot_delay_s=args.boot_delay))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
