"""Standalone RheaKV store server: one OS process per store.

Reference parity: the server side of ``example:rheakv/*`` (SURVEY.md
§3.3) — the reference boots `RheaKVStore` server mains from yaml
topologies; here the topology is CLI flags shared by every member.

    # a 3-store cluster, 4 pre-split regions, durable native engines:
    python -m examples.rheakv_server --serve 127.0.0.1:9001 \\
        --stores 127.0.0.1:9001,127.0.0.1:9002,127.0.0.1:9003 \\
        --regions 4 --data /tmp/rkv1 [--transport native] [--store native]

Every member derives the same region layout from (--stores, --regions),
so a client needs only the store list (see `client_for`); region
discovery and split survival ride the `kv_list_regions` refresh path.
"""

from __future__ import annotations

import argparse
import asyncio
import sys

from examples.rheakv_bench import make_regions
from tpuraft.rheakv.client import RheaKVStore
from tpuraft.rheakv.pd_client import FakePlacementDriverClient
from tpuraft.rheakv.store_engine import StoreEngine, StoreEngineOptions


def derive_regions(stores: list[str], n_regions: int):
    regions = make_regions(n_regions)
    for r in regions:
        r.peers = list(stores)
    return regions


async def serve(endpoint: str, stores: list[str], n_regions: int,
                data_path: str, transport_kind: str = "tcp",
                store_kind: str = "memory",
                pd_endpoints: list[str] | None = None,
                log_scheme: str = "file",
                metrics_port: int | None = None) -> None:
    if transport_kind == "native":
        from tpuraft.rpc.native_tcp import NativeTcpRpcServer as Server
        from tpuraft.rpc.native_tcp import NativeTcpTransport as Transport
    else:
        from tpuraft.rpc.tcp import TcpRpcServer as Server
        from tpuraft.rpc.tcp import TcpTransport as Transport

    server = Server(endpoint)
    await server.start()
    transport = Transport(endpoint=endpoint)
    opts = StoreEngineOptions(
        server_id=endpoint,
        initial_regions=derive_regions(stores, n_regions),
        data_path=data_path,
        election_timeout_ms=1000,
        log_scheme=log_scheme,
        metrics_port=metrics_port,
    )
    if store_kind == "native":
        import os

        from tpuraft.rheakv.native_store import NativeRawKVStore
        base = f"{data_path}/kv_{endpoint.replace(':', '_')}"
        # the C++ engine mkdirs only the leaf — ensure the parents exist
        os.makedirs(os.path.dirname(base) or ".", exist_ok=True)
        opts.raw_store_factory = lambda: NativeRawKVStore(base)
    pd_client = None
    if pd_endpoints:
        from tpuraft.rheakv.pd_client import RemotePlacementDriverClient
        pd_client = RemotePlacementDriverClient(transport, pd_endpoints)
    engine = StoreEngine(opts, server, transport, pd_client=pd_client)
    await engine.start()
    print(f"rheakv store {endpoint} up "
          f"({n_regions} regions, {len(stores)} stores)"
          + (f", /metrics on :{engine.metrics_http_port}"
             if engine.metrics_http_port else ""), flush=True)
    try:
        while True:
            await asyncio.sleep(3600)
    finally:
        await engine.shutdown()
        await server.stop()
        await transport.close()


def client_for(stores: list[str], n_regions: int,
               transport=None, **kw) -> RheaKVStore:
    """Client against a cluster started with the same (stores, regions)."""
    if transport is None:
        from tpuraft.rpc.tcp import TcpTransport
        transport = TcpTransport()
    pd = FakePlacementDriverClient(derive_regions(stores, n_regions))
    return RheaKVStore(pd, transport, **kw)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--serve", required=True, help="this store's ip:port")
    ap.add_argument("--stores", required=True,
                    help="comma-separated store endpoints (all members)")
    ap.add_argument("--regions", type=int, default=2)
    ap.add_argument("--data", required=True, help="durable state dir")
    ap.add_argument("--transport", choices=["tcp", "native"], default="tcp")
    ap.add_argument("--log-scheme", choices=["file", "multilog"],
                    default="file",
                    help="per-region segment dirs, or ONE shared C++ journal engine per store (group-commit fsync)")
    ap.add_argument("--store", choices=["memory", "native"],
                    default="memory")
    ap.add_argument("--pd", default="",
                    help="comma-separated PD endpoints: heartbeat region "
                         "meta + stats there and execute its instructions "
                         "(splits, leader transfers)")
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="serve Prometheus text at GET /metrics on this "
                         "port (0 = ephemeral, printed at boot); "
                         "omit = off — `admin.py metrics` still scrapes "
                         "over the admin transport")
    args = ap.parse_args()
    stores = [s for s in args.stores.split(",") if s]
    if args.serve not in stores:
        print("error: --serve must be one of --stores", file=sys.stderr)
        sys.exit(2)
    try:
        asyncio.run(serve(args.serve, stores, args.regions, args.data,
                          args.transport, args.store,
                          [e for e in args.pd.split(",") if e] or None,
                          log_scheme=args.log_scheme,
                          metrics_port=args.metrics_port))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
