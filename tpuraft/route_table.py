"""RouteTable: client-side groupId -> (configuration, leader) cache.

Reference parity: ``core:RouteTable`` (``#updateConfiguration``,
``#refreshLeader``, ``#refreshConfiguration``, ``#selectLeader``) —
SURVEY.md §3.1 "Client routing".  A process-local singleton is available
via :func:`RouteTable.instance` to mirror ``RouteTable#getInstance``, but
instances are independently constructible for tests.
"""

from __future__ import annotations

import asyncio
from typing import Optional

from tpuraft.conf import Configuration
from tpuraft.core.cli_service import CliService
from tpuraft.entity import PeerId
from tpuraft.errors import RaftError, Status
from tpuraft.rpc.transport import RpcError


class RouteTable:
    _instance: Optional["RouteTable"] = None

    def __init__(self) -> None:
        self._conf: dict[str, Configuration] = {}
        self._leaders: dict[str, PeerId] = {}

    @classmethod
    def instance(cls) -> "RouteTable":
        if cls._instance is None:
            cls._instance = RouteTable()
        return cls._instance

    # -- local cache ops -----------------------------------------------------

    def update_configuration(self, group_id: str,
                             conf: Configuration | str) -> bool:
        if isinstance(conf, str):
            conf = Configuration.parse(conf)
        if not conf.is_valid():
            return False
        self._conf[group_id] = conf.copy()
        return True

    def get_configuration(self, group_id: str) -> Optional[Configuration]:
        c = self._conf.get(group_id)
        return c.copy() if c else None

    def update_leader(self, group_id: str, leader: PeerId | str | None) -> bool:
        if leader is None or (isinstance(leader, str) and not leader):
            self._leaders.pop(group_id, None)
            return True
        if isinstance(leader, str):
            leader = PeerId.parse(leader)
        self._leaders[group_id] = leader
        return True

    def select_leader(self, group_id: str) -> Optional[PeerId]:
        return self._leaders.get(group_id)

    def remove_group(self, group_id: str) -> None:
        self._conf.pop(group_id, None)
        self._leaders.pop(group_id, None)

    # -- remote refresh ------------------------------------------------------

    async def refresh_leader(self, cli: CliService, group_id: str,
                             timeout_ms: float = 3000) -> Status:
        conf = self._conf.get(group_id)
        if conf is None:
            return Status.error(RaftError.ENOENT,
                                f"group {group_id} not in route table")
        try:
            leader = await asyncio.wait_for(
                cli.get_leader(group_id, conf), timeout_ms / 1000.0)
        except asyncio.TimeoutError:
            return Status.error(RaftError.ETIMEDOUT, "refresh_leader timeout")
        if leader is None:
            return Status.error(RaftError.EAGAIN,
                                f"no leader found for {group_id}")
        self._leaders[group_id] = leader
        return Status.OK()

    async def refresh_configuration(self, cli: CliService, group_id: str,
                                    timeout_ms: float = 3000) -> Status:
        conf = self._conf.get(group_id)
        if conf is None:
            return Status.error(RaftError.ENOENT,
                                f"group {group_id} not in route table")
        st = await self.refresh_leader(cli, group_id, timeout_ms)
        if not st.is_ok():
            return st
        try:
            fresh = await asyncio.wait_for(
                cli.get_configuration(group_id, conf), timeout_ms / 1000.0)
        except asyncio.TimeoutError:
            return Status.error(RaftError.ETIMEDOUT,
                                "refresh_configuration timeout")
        except RpcError as e:
            return e.status
        if fresh.is_valid():
            self._conf[group_id] = fresh
        return Status.OK()
