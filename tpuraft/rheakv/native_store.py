"""ctypes bindings for the C++ KV storage engine (native/kvstore.cc).

Reference parity: the JNI seam under ``rhea:storage/RocksRawKVStore`` —
Java orchestrates, RocksDB (C++) owns the bytes (SURVEY.md §3.2/§3.4).
Here the C++ engine owns the ordered tables, WAL durability, CRC
recovery and checkpointing; Python owns op semantics (sequences, lock
leases, CAS) — safe because every mutation arrives through the region
state machine's single apply thread.

Columns: 0=data 1=sequence 2=lock 3=meta (fencing counter).  Snapshot
blobs use the exact MemoryRawKVStore format so the two engines are
interchangeable across snapshot install.

Build: ``make -C native``; :func:`ensure_built` does it on demand.
"""

from __future__ import annotations

import ctypes
import os
import struct
import threading
import time
from typing import Optional

from tpuraft.rheakv.raw_store import LockOwner, RawKVStore, Sequence

_LIB_NAME = "libtpuraft_kvstore.so"
_COL_DATA, _COL_SEQ, _COL_LOCK, _COL_META = 0, 1, 2, 3
_FENCING_KEY = b"fencing"
_U32 = struct.Struct("<I")
_I64 = struct.Struct("<q")
# lock value: wall deadline (f64), fencing (i64), acquires (u32), locker_id
_LOCK_HDR = struct.Struct("<dqI")
_OP_PUT, _OP_DELETE, _OP_DELETE_RANGE = 1, 2, 3


def _native_dir() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), os.pardir, "native")


def lib_path() -> str:
    return os.environ.get(
        "TPURAFT_NATIVE_KV_LIB",
        os.path.normpath(os.path.join(_native_dir(), _LIB_NAME)))


def ensure_built(timeout: float = 120.0) -> str:
    from tpuraft.util.native_build import ensure_built as _eb
    return _eb(_native_dir(), lib_path(), timeout=timeout)


_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None


def _load() -> ctypes.CDLL:
    global _lib
    with _lib_lock:
        if _lib is None:
            lib = ctypes.CDLL(lib_path())
            u8p = ctypes.POINTER(ctypes.c_uint8)
            lib.tkv_open.restype = ctypes.c_void_p
            lib.tkv_open.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                     ctypes.c_int64, ctypes.c_char_p,
                                     ctypes.c_int]
            lib.tkv_close.argtypes = [ctypes.c_void_p]
            lib.tkv_free.argtypes = [u8p]
            lib.tkv_apply_batch.restype = ctypes.c_int
            lib.tkv_apply_batch.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                            ctypes.c_int64, ctypes.c_char_p,
                                            ctypes.c_int]
            lib.tkv_get.restype = ctypes.c_int64
            lib.tkv_get.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                    ctypes.c_char_p, ctypes.c_int64,
                                    ctypes.POINTER(u8p)]
            lib.tkv_scan.restype = ctypes.c_int64
            lib.tkv_scan.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                     ctypes.c_char_p, ctypes.c_int64,
                                     ctypes.c_char_p, ctypes.c_int64,
                                     ctypes.c_int64, ctypes.c_int,
                                     ctypes.c_int, ctypes.POINTER(u8p)]
            lib.tkv_count_range.restype = ctypes.c_int64
            lib.tkv_count_range.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                            ctypes.c_char_p, ctypes.c_int64,
                                            ctypes.c_char_p, ctypes.c_int64]
            lib.tkv_checkpoint.restype = ctypes.c_int
            lib.tkv_checkpoint.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                           ctypes.c_int]
            lib.tkv_wal_bytes.restype = ctypes.c_int64
            lib.tkv_wal_bytes.argtypes = [ctypes.c_void_p]
            lib.tkv_count.restype = ctypes.c_int64
            lib.tkv_count.argtypes = [ctypes.c_void_p, ctypes.c_int]
            lib.tkv_open2.restype = ctypes.c_void_p
            lib.tkv_open2.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                      ctypes.c_int64, ctypes.c_int64,
                                      ctypes.c_int64, ctypes.c_char_p,
                                      ctypes.c_int]
            for name in ("tkv_run_count", "tkv_mem_bytes",
                         "tkv_compactions",
                         "tkv_compact_input_bytes",
                         "tkv_compact_last_input_bytes",
                         "tkv_data_bytes"):
                fn = getattr(lib, name)
                fn.restype = ctypes.c_int64
                fn.argtypes = [ctypes.c_void_p]
            _lib = lib
        return _lib


def _encode_ops(ops: list[tuple[int, int, bytes, bytes]]) -> bytes:
    parts = []
    for op, col, key, val in ops:
        parts.append(bytes((op, col)))
        parts.append(_U32.pack(len(key)))
        parts.append(key)
        parts.append(_U32.pack(len(val)))
        parts.append(val)
    return b"".join(parts)


class NativeRawKVStore(RawKVStore):
    """RawKVStore over the C++ engine; selected by ``native://<dir>``."""

    def __init__(self, dir_path: str, sync: bool = True,
                 checkpoint_wal_bytes: int = 0,
                 memtable_budget_bytes: int = 0, max_runs: int = 0):
        """memtable_budget_bytes > 0 enables the LSM tier (the RocksDB
        >RAM role): the memtable spills to immutable sorted runs at the
        budget, background compaction merges runs past ``max_runs``, and
        recovery replays at most one memtable of WAL.  0 keeps the
        bounded-by-RAM memtable+checkpoint engine."""
        self._dir = dir_path
        self._lib = _load()
        err = ctypes.create_string_buffer(256)
        h = self._lib.tkv_open2(dir_path.encode(), 1 if sync else 0,
                                checkpoint_wal_bytes, memtable_budget_bytes,
                                max_runs, err, 256)
        if not h:
            raise IOError(f"native kv open failed: {err.value.decode()}")
        self._h = h

    @property
    def run_count(self) -> int:
        return self._lib.tkv_run_count(self._handle())

    @property
    def mem_bytes(self) -> int:
        return self._lib.tkv_mem_bytes(self._handle())

    @property
    def compactions(self) -> int:
        return self._lib.tkv_compactions(self._handle())

    @property
    def compact_input_bytes(self) -> int:
        """Cumulative compaction input bytes (write amplification)."""
        return self._lib.tkv_compact_input_bytes(self._handle())

    @property
    def compact_last_input_bytes(self) -> int:
        """Input bytes of the latest compaction cycle — with size-tiered
        pick-K this tracks the small spill tier, NOT total store size."""
        return self._lib.tkv_compact_last_input_bytes(self._handle())

    @property
    def data_bytes(self) -> int:
        """On-disk bytes across all run files."""
        return self._lib.tkv_data_bytes(self._handle())

    def close(self) -> None:
        if self._h is not None:
            self._lib.tkv_close(self._h)
            self._h = None

    # -- raw plumbing --------------------------------------------------------

    def _handle(self):
        # raise (don't segfault) on use-after-close, e.g. a straggling
        # read draining during store shutdown; the C side also null-guards
        if self._h is None:
            raise IOError("native kv store is closed")
        return self._h

    def _write(self, ops: list[tuple[int, int, bytes, bytes]]) -> None:
        blob = _encode_ops(ops)
        err = ctypes.create_string_buffer(256)
        if self._lib.tkv_apply_batch(self._handle(), blob, len(blob),
                                     err, 256) != 0:
            raise IOError(f"native kv write failed: {err.value.decode()}")

    def _get(self, col: int, key: bytes) -> Optional[bytes]:
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = self._lib.tkv_get(self._handle(), col, key, len(key),
                              ctypes.byref(out))
        if n < 0:
            return None
        try:
            return ctypes.string_at(out, n)
        finally:
            self._lib.tkv_free(out)

    def _scan(self, col: int, start: bytes, end: bytes, limit: int,
              with_values: bool, reverse: bool = False
              ) -> list[tuple[bytes, Optional[bytes]]]:
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = self._lib.tkv_scan(self._handle(), col, start, len(start), end, len(end),
                               limit, 1 if with_values else 0,
                               1 if reverse else 0, ctypes.byref(out))
        if n < 0:
            raise IOError("native kv scan failed")
        try:
            blob = ctypes.string_at(out, n)
        finally:
            self._lib.tkv_free(out)
        (count,) = _U32.unpack_from(blob, 0)
        off = 4
        rows: list[tuple[bytes, Optional[bytes]]] = []
        for _ in range(count):
            (kl,) = _U32.unpack_from(blob, off)
            off += 4
            k = blob[off:off + kl]
            off += kl
            v = None
            if with_values:
                (vl,) = _U32.unpack_from(blob, off)
                off += 4
                v = blob[off:off + vl]
                off += vl
            rows.append((k, v))
        return rows

    # -- reads ---------------------------------------------------------------

    def get(self, key: bytes) -> Optional[bytes]:
        return self._get(_COL_DATA, key)

    def scan(self, start: bytes, end: bytes, limit: int = -1,
             return_value: bool = True) -> list[tuple[bytes, Optional[bytes]]]:
        return self._scan(_COL_DATA, start, end, limit, return_value)

    def reverse_scan(self, start: bytes, end: bytes, limit: int = -1,
                     return_value: bool = True
                     ) -> list[tuple[bytes, Optional[bytes]]]:
        return self._scan(_COL_DATA, start, end, limit, return_value,
                          reverse=True)

    def approximate_keys_in_range(self, start: bytes, end: bytes) -> int:
        return self._lib.tkv_count_range(self._handle(), _COL_DATA, start,
                                         len(start), end, len(end))

    # -- writes --------------------------------------------------------------

    def put(self, key: bytes, value: bytes) -> None:
        self._write([(_OP_PUT, _COL_DATA, key, value)])

    def put_list(self, kvs: list[tuple[bytes, bytes]]) -> None:
        if kvs:
            self._write([(_OP_PUT, _COL_DATA, k, v) for k, v in kvs])

    def delete(self, key: bytes) -> None:
        self._write([(_OP_DELETE, _COL_DATA, key, b"")])

    def delete_list(self, keys: list[bytes]) -> None:
        if keys:
            self._write([(_OP_DELETE, _COL_DATA, k, b"") for k in keys])

    def apply_write_batch(self, ops: list[tuple[bytes, Optional[bytes]]]
                          ) -> None:
        # one ctypes call + one WAL record for the whole mixed run
        if ops:
            self._write([(_OP_PUT, _COL_DATA, k, v) if v is not None
                         else (_OP_DELETE, _COL_DATA, k, b"")
                         for k, v in ops])

    def delete_range(self, start: bytes, end: bytes) -> None:
        self._write([(_OP_DELETE_RANGE, _COL_DATA, start, end)])

    def reset_range(self, start: bytes, end: bytes) -> None:
        # one atomic batch: data, sequences, locks
        self._write([(_OP_DELETE_RANGE, col, start, end)
                     for col in (_COL_DATA, _COL_SEQ, _COL_LOCK)])

    # -- sequences -----------------------------------------------------------

    def get_sequence(self, key: bytes, step: int) -> Sequence:
        raw = self._get(_COL_SEQ, key)
        cur = _I64.unpack(raw)[0] if raw else 0
        if step <= 0:
            return Sequence(cur, cur)
        self._write([(_OP_PUT, _COL_SEQ, key, _I64.pack(cur + step))])
        return Sequence(cur, cur + step)

    def reset_sequence(self, key: bytes) -> None:
        self._write([(_OP_DELETE, _COL_SEQ, key, b"")])

    # -- locks ---------------------------------------------------------------
    # Lease deadlines persist as wall-clock stamps (the engine outlives the
    # process, unlike MemoryRawKVStore's monotonic in-memory deadlines).

    def _load_lock(self, key: bytes) -> Optional[LockOwner]:
        raw = self._get(_COL_LOCK, key)
        if raw is None:
            return None
        deadline, token, acquires = _LOCK_HDR.unpack_from(raw, 0)
        return LockOwner(raw[_LOCK_HDR.size:], deadline, token, acquires)

    def _store_lock(self, key: bytes, o: LockOwner) -> None:
        self._write([(_OP_PUT, _COL_LOCK, key,
                      _LOCK_HDR.pack(o.deadline, o.fencing_token, o.acquires)
                      + o.locker_id)])

    def _next_fencing(self) -> int:
        raw = self._get(_COL_META, _FENCING_KEY)
        token = (_I64.unpack(raw)[0] if raw else 0) + 1
        self._write([(_OP_PUT, _COL_META, _FENCING_KEY, _I64.pack(token))])
        return token

    def try_lock_with(self, key: bytes, locker_id: bytes, lease_ms: int,
                      keep_lease: bool) -> tuple[bool, int, bytes]:
        # graftcheck: allow(raw-clock) — KV lock-lease deadline: process-local TTL, not consensus timing
        now = time.time()
        owner = self._load_lock(key)
        if owner is not None and not owner.expired(now):
            if owner.locker_id == locker_id:
                if keep_lease:
                    owner.deadline = now + lease_ms / 1000.0
                else:
                    owner.acquires += 1
                self._store_lock(key, owner)
                return True, owner.fencing_token, locker_id
            return False, owner.fencing_token, owner.locker_id
        token = self._next_fencing()
        self._store_lock(key, LockOwner(locker_id, now + lease_ms / 1000.0,
                                        token))
        return True, token, locker_id

    def release_lock(self, key: bytes, locker_id: bytes) -> bool:
        owner = self._load_lock(key)
        if owner is None:
            return True
        # graftcheck: allow(raw-clock) — KV lock-lease deadline: process-local TTL, not consensus timing
        if owner.locker_id != locker_id and not owner.expired(time.time()):
            return False
        owner.acquires -= 1
        if owner.acquires <= 0 or owner.locker_id != locker_id:
            self._write([(_OP_DELETE, _COL_LOCK, key, b"")])
        else:
            self._store_lock(key, owner)
        return True

    # -- admin ---------------------------------------------------------------

    def checkpoint(self) -> None:
        """Force a checkpoint + WAL truncation (auto above the WAL
        threshold; exposed for shutdown / tests)."""
        err = ctypes.create_string_buffer(256)
        if self._lib.tkv_checkpoint(self._handle(), err, 256) != 0:
            raise IOError(f"native kv checkpoint failed: {err.value.decode()}")

    def wal_bytes(self) -> int:
        return self._lib.tkv_wal_bytes(self._handle())

    # -- snapshot (MemoryRawKVStore-compatible blob) -------------------------

    def serialize_range(self, start: bytes, end: bytes) -> bytes:
        kvs = self.scan(start, end)
        seqs = [(k, _I64.unpack(v)[0])
                for k, v in self._scan(_COL_SEQ, start, end, -1, True)]
        locks = []
        for k, raw in self._scan(_COL_LOCK, start, end, -1, True):
            deadline, token, acquires = _LOCK_HDR.unpack_from(raw, 0)
            locks.append((k, LockOwner(raw[_LOCK_HDR.size:], deadline, token,
                                       acquires)))
        out = bytearray(struct.pack("<III", len(kvs), len(seqs), len(locks)))
        for k, v in kvs:
            out += _U32.pack(len(k)) + k + _U32.pack(len(v)) + v
        for k, v in seqs:
            out += _U32.pack(len(k)) + k + _I64.pack(v)
        # graftcheck: allow(raw-clock) — lock-lease persisted as REMAINING duration; wall stamps never cross stores
        now = time.time()
        for k, o in locks:
            out += _U32.pack(len(k)) + k
            out += _U32.pack(len(o.locker_id)) + o.locker_id
            out += struct.pack("<dqI", max(0.0, o.deadline - now),
                               o.fencing_token, o.acquires)
        raw = self._get(_COL_META, _FENCING_KEY)
        out += _I64.pack(_I64.unpack(raw)[0] if raw else 0)
        return bytes(out)

    def load_serialized(self, blob: bytes) -> None:
        buf = memoryview(blob)
        nkv, nseq, nlock = struct.unpack_from("<III", buf, 0)
        off = 12
        ops: list[tuple[int, int, bytes, bytes]] = []
        for _ in range(nkv):
            (kl,) = _U32.unpack_from(buf, off)
            off += 4
            k = bytes(buf[off:off + kl])
            off += kl
            (vl,) = _U32.unpack_from(buf, off)
            off += 4
            ops.append((_OP_PUT, _COL_DATA, k, bytes(buf[off:off + vl])))
            off += vl
        for _ in range(nseq):
            (kl,) = _U32.unpack_from(buf, off)
            off += 4
            k = bytes(buf[off:off + kl])
            off += kl
            (v,) = _I64.unpack_from(buf, off)
            off += 8
            ops.append((_OP_PUT, _COL_SEQ, k, _I64.pack(v)))
        # graftcheck: allow(raw-clock) — lock-lease persisted as REMAINING duration; wall stamps never cross stores
        now = time.time()
        max_token = 0
        for _ in range(nlock):
            (kl,) = _U32.unpack_from(buf, off)
            off += 4
            k = bytes(buf[off:off + kl])
            off += kl
            (ll,) = _U32.unpack_from(buf, off)
            off += 4
            lid = bytes(buf[off:off + ll])
            off += ll
            remain, token, acquires = struct.unpack_from("<dqI", buf, off)
            off += 20
            ops.append((_OP_PUT, _COL_LOCK, k,
                        _LOCK_HDR.pack(now + remain, token, acquires) + lid))
            max_token = max(max_token, token)
        (fencing,) = _I64.unpack_from(buf, off)
        raw = self._get(_COL_META, _FENCING_KEY)
        cur = _I64.unpack(raw)[0] if raw else 0
        fencing = max(cur, fencing, max_token)
        if fencing > cur:
            ops.append((_OP_PUT, _COL_META, _FENCING_KEY, _I64.pack(fencing)))
        if ops:
            self._write(ops)


def create_raw_kv_store(uri: str) -> RawKVStore:
    """SPI-style factory by URI scheme (same seam as create_log_storage)."""
    from tpuraft.rheakv.raw_store import MemoryRawKVStore

    if uri == "memory://":
        return MemoryRawKVStore()
    if uri.startswith("native://"):
        ensure_built()
        return NativeRawKVStore(uri[len("native://"):])
    raise ValueError(f"unknown raw kv store uri: {uri}")
