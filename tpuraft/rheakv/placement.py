"""Region lifecycle placement engine: heat-driven split / cold merge /
cross-store move decisions for the PD leader.

Reference parity: the scheduling half of ``pd:ClusterStatsManager`` +
TiKV-PD-style operators, grown over this repo's heat plane (ISSUE 20).
The engine turns the PD leader's live picture — per-region
:class:`~tpuraft.rheakv.pd_server.RegionStats` (key counts + heat
EWMAs), the hot-region detector, store zone labels and gray-failure
health — into three actuators:

- **split** a HOT region even below the key-count threshold (the heat
  detector, not key counts, is the signal; a small floor keeps
  single-key hotspots from splitting into empty shells),
- **merge** an adjacent COLD pair (the colder region is the SOURCE and
  is absorbed into its neighbor; the decision is replicated as a
  pending merge so a PD failover re-issues the SAME pair),
- **move** a replica off a crowded store onto a roomy, healthy one
  (add-learner → catch up → promote + remove on joint consensus,
  executed store-side; SICK stores are never destinations).

Like ``ClusterStatsManager``, every pacing clock here is PD-leader-
local and ephemeral: after a failover the new leader re-derives its
picture from heartbeats, and ``note_term`` rebuilds the cooldowns so
the fresh leader cannot double-order what its predecessor just did.
The DECISIONS that must survive failover (pending merges, allocated
split ids) are replicated through the PD group by the caller.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from typing import Optional

from tpuraft.rheakv.metadata import Region


def _peer_endpoint(peer_str: str) -> str:
    return ":".join(peer_str.split("/", 1)[0].split(":")[:2])


def _is_voter(peer_str: str) -> bool:
    return not (peer_str.endswith("/learner")
                or peer_str.endswith("/witness"))


@dataclass
class LifecycleOptions:
    """Policy knobs (surfaced via PlacementDriverOptions.lifecycle_*)."""

    # heat-driven split: a hot-flagged region splits regardless of the
    # key-count threshold, provided it holds at least this many keys
    # (a one-key hotspot has nothing to split)
    heat_split_min_keys: int = 32
    # cold merge: the SOURCE must score at most this and hold at most
    # merge_max_keys keys (big cold regions would churn big absorb
    # blobs through the target group's log)
    merge_max_score: float = 0.05
    merge_max_keys: int = 4096
    # the surviving TARGET may be warmer than the source, but not hot:
    # its score must stay under this multiple of merge_max_score
    merge_target_factor: float = 8.0
    # pacing + caps
    merge_cooldown_s: float = 10.0
    max_inflight_merges: int = 2
    # never merge the fleet below this many regions
    min_regions: int = 4
    # cross-store move: the source store must host at least this many
    # more replicas than the destination
    move_imbalance: int = 2
    move_cooldown_s: float = 10.0
    max_inflight_moves: int = 2


class PlacementEngine:
    """Leader-local lifecycle policy over the PD's cluster picture.

    One instance per :class:`PlacementDriverServer`; every method runs
    on the PD node's RPC loop (heartbeat handlers), so the state needs
    no locks.  The engine DECIDES; replication and instruction delivery
    stay with the PD server.
    """

    # bounded memory of recent decisions for the admin plane
    # (examples/admin.py regions --pd) and the ClusterView
    RECENT_MAX = 64

    def __init__(self, opts: LifecycleOptions) -> None:
        self.opts = opts
        self._term = -1
        self._grace_until = 0.0
        # region -> deadline: a region recently ORDERED merged/moved is
        # left alone (attempt-paced, like the evacuation cooldowns)
        self._merge_cooldown: dict[int, float] = {}
        self._move_cooldown: dict[int, float] = {}
        # region -> (src_peer, dst_peer, deadline): moves ordered but
        # not yet observed in the region's reported peers — counted as
        # already-moved so one heartbeat burst can't order the whole
        # imbalance at once
        self._inflight_moves: dict[int, tuple[str, str, float]] = {}
        self.recent: deque = deque(maxlen=self.RECENT_MAX)

    def note_term(self, term: int, cooldown_s: float) -> None:
        """PD leadership changed: pacing state is leader-local, so the
        new leader starts every region on one full cooldown (the
        note_leadership idiom — an immediate re-order of something the
        predecessor just ordered becomes structurally impossible)."""
        if term == self._term:
            return
        self._term = term
        # graftcheck: allow(raw-clock) — PD-side post-failover grace (real time)
        self._grace_until = time.monotonic() + cooldown_s
        self._merge_cooldown.clear()
        self._move_cooldown.clear()
        self._inflight_moves.clear()

    def note_decision(self, kind: str, **fields) -> None:
        self.recent.append({"kind": kind, "term": self._term, **fields})

    def recent_decisions(self) -> list[dict]:
        return list(self.recent)

    # -- heat-driven split ---------------------------------------------------

    def should_heat_split(self, region_id: int, stats) -> bool:
        """True when the heat detector flags the region and it holds
        enough keys to be worth splitting.  The caller still routes
        through the replicated pending-split allocation, so a PD
        failover re-issues the SAME child id."""
        if region_id not in stats.hot_regions():
            return False
        return stats.last_keys(region_id) >= self.opts.heat_split_min_keys

    # -- cold merge ----------------------------------------------------------

    def pick_merge(self, regions: dict[int, Region],
                   region_leaders: dict[int, str], leader_ep: str,
                   stats, pending_merges: dict[int, int],
                   pending_splits: dict[int, int]
                   ) -> Optional[tuple[int, int]]:
        """Pick one (source, target) cold-adjacent pair whose SOURCE is
        led from ``leader_ep`` (instructions ride that store's
        heartbeat response, so only its led regions can act).  The
        colder region of the pair is the source; the survivor extends
        over it."""
        # graftcheck: allow(raw-clock) — PD-side merge pacing (real time)
        now = time.monotonic()
        if now < self._grace_until:
            return None
        if len(pending_merges) >= max(1, self.opts.max_inflight_merges):
            return None
        live = len(regions) - len(pending_merges)
        if live <= max(2, self.opts.min_regions):
            return None
        self._merge_cooldown = {r: d for r, d in
                                self._merge_cooldown.items() if d > now}
        # regions already involved in a merge (either side) or a split
        # are off the table — one multi-step protocol per region
        busy = (set(pending_merges) | set(pending_merges.values())
                | set(pending_splits) | set(pending_splits.values()))
        hot = stats.hot_regions()
        # adjacency index over the CURRENT tiling
        by_start = {r.start_key: r for r in regions.values()}

        def cold(rid: int, factor: float = 1.0) -> bool:
            ent = stats.region_stats(rid)
            return (ent.score <= self.opts.merge_max_score * factor
                    and rid not in hot)

        best: Optional[tuple[float, int, int]] = None
        for rid, region in regions.items():
            if rid in busy or rid in self._merge_cooldown:
                continue
            leader = region_leaders.get(rid, "")
            if not leader or _peer_endpoint(leader) != leader_ep:
                continue
            ent = stats.region_stats(rid)
            if not cold(rid) or ent.keys > self.opts.merge_max_keys:
                continue
            # the RIGHT neighbor (its start is our end) absorbs us;
            # merging left would need the neighbor's leader to act
            if region.end_key == b"":
                continue  # rightmost region has no right neighbor
            neigh = by_start.get(region.end_key)
            if neigh is None or neigh.id in busy \
                    or neigh.id in self._merge_cooldown:
                continue
            if not cold(neigh.id, self.opts.merge_target_factor):
                continue
            if not region_leaders.get(neigh.id):
                continue  # leaderless target can't absorb
            key = (ent.score, ent.keys, rid)
            if best is None or key < best:
                best = key
                pair = (rid, neigh.id)
        if best is None:
            return None
        src, tgt = pair
        self._merge_cooldown[src] = now + self.opts.merge_cooldown_s
        self._merge_cooldown[tgt] = now + self.opts.merge_cooldown_s
        return src, tgt

    def merge_reissue_due(self, source_id: int) -> bool:
        """Pace re-issue of an already-replicated pending merge (the
        source store defers mid-conf-change, bounces on a stale target
        leader, ...): at most one instruction per cooldown window."""
        # graftcheck: allow(raw-clock) — PD-side merge pacing (real time)
        now = time.monotonic()
        if self._merge_cooldown.get(source_id, 0.0) > now:
            return False
        self._merge_cooldown[source_id] = now + self.opts.merge_cooldown_s
        return True

    # -- cross-store move ----------------------------------------------------

    def pick_move(self, regions: dict[int, Region],
                  region_leaders: dict[int, str], leader_ep: str,
                  store_eps: list[str], zones: dict[str, str],
                  health: dict[str, str],
                  pending_merges: dict[int, int],
                  pending_splits: dict[int, int]
                  ) -> Optional[tuple[int, str, str]]:
        """Pick one (region_id, src_peer, dst_peer) replica move for a
        region led from ``leader_ep``: shed a replica from the most
        crowded store onto the roomiest healthy store that doesn't
        already host one — preferring a destination whose ZONE the
        region doesn't cover yet.  Never targets SICK stores."""
        # graftcheck: allow(raw-clock) — PD-side move pacing (real time)
        now = time.monotonic()
        if now < self._grace_until:
            return None
        self._move_cooldown = {r: d for r, d in
                               self._move_cooldown.items() if d > now}
        self._inflight_moves = {
            r: m for r, m in self._inflight_moves.items() if m[2] > now
            and (r in regions
                 and any(_peer_endpoint(p) == _peer_endpoint(m[0])
                         for p in regions[r].peers))}
        if len(self._inflight_moves) >= max(1, self.opts.max_inflight_moves):
            return None
        busy = (set(pending_merges) | set(pending_merges.values())
                | set(pending_splits) | set(pending_splits.values()))
        # replica count per store endpoint, with in-flight moves
        # overlaid (source already "lost" the replica, dest "gained" it)
        counts: dict[str, int] = {ep: 0 for ep in store_eps}
        for region in regions.values():
            for p in region.peers:
                ep = _peer_endpoint(p)
                if ep in counts:
                    counts[ep] += 1
        for _rid, (src_p, dst_p, _d) in self._inflight_moves.items():
            s, d = _peer_endpoint(src_p), _peer_endpoint(dst_p)
            if s in counts:
                counts[s] -= 1
            if d in counts:
                counts[d] += 1

        def sick(ep: str) -> bool:
            return health.get(ep, "") == "sick"

        best: Optional[tuple[tuple, int, str, str]] = None
        for rid, region in regions.items():
            if rid in busy or rid in self._move_cooldown \
                    or rid in self._inflight_moves:
                continue
            leader = region_leaders.get(rid, "")
            if not leader or _peer_endpoint(leader) != leader_ep:
                continue
            hosted = {_peer_endpoint(p) for p in region.peers}
            hosted_zones = {zones.get(ep, "") for ep in hosted}
            # movable replicas: plain voters only (witness journals and
            # learner roles don't survive a generic move), and prefer
            # NOT the leader itself (the store would have to hand
            # leadership off first and defer)
            movable = [p for p in region.peers if _is_voter(p)]
            if len(movable) < 2:
                continue
            for src_p in movable:
                src_ep = _peer_endpoint(src_p)
                for dst_ep in store_eps:
                    if dst_ep in hosted or sick(dst_ep):
                        continue
                    gap = counts.get(src_ep, 0) - counts.get(dst_ep, 0)
                    if gap < max(1, self.opts.move_imbalance):
                        continue
                    new_zone = int(zones.get(dst_ep, "")
                                   not in hosted_zones)
                    is_leader_src = int(src_ep == _peer_endpoint(leader))
                    # widest gap first, then zone diversity, then
                    # non-leader sources, then a stable hash spread
                    key = (-gap, -new_zone, is_leader_src,
                           hash((rid, src_ep, dst_ep)) & 0xffff)
                    if best is None or key < best[0]:
                        best = (key, rid, src_p, dst_ep)
        if best is None:
            return None
        _, rid, src_p, dst_ep = best
        self._move_cooldown[rid] = now + self.opts.move_cooldown_s
        self._inflight_moves[rid] = (
            src_p, dst_ep, now + 3 * self.opts.move_cooldown_s)
        return rid, src_p, dst_ep
