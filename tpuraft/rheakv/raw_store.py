"""RawKVStore: the storage interface under the raft layer + memory impl.

Reference parity: ``rhea:storage/RawKVStore`` /
``rhea:storage/MemoryRawKVStore`` / ``rhea:storage/RocksRawKVStore``
(SURVEY.md §3.2).  One store instance is SHARED by all regions of a
process — regions are key ranges over the same keyspace, exactly as the
reference shares one RocksDB across RegionEngines.  The native C++
engine (tpuraft.storage native seam) can replace MemoryRawKVStore via
the same interface.

Sequences and locks live in separate namespaces (the reference uses
RocksDB column families / separate TreeMaps) so data scans never see
them; region snapshots serialize all three namespaces range-wise.
"""

from __future__ import annotations

import bisect
import struct
import time
from dataclasses import dataclass
from typing import Iterable, Optional


@dataclass
class Sequence:
    start: int
    end: int


@dataclass
class LockOwner:
    locker_id: bytes
    deadline: float        # monotonic seconds
    fencing_token: int
    acquires: int = 1      # reentrant acquisition count

    def expired(self, now: Optional[float] = None) -> bool:
        # graftcheck: allow(raw-clock) — KV lock-lease default deadline: process-local TTL, not consensus timing
        return (now if now is not None else time.monotonic()) >= self.deadline


class RawKVStore:
    """Synchronous KV storage under one region's state machine.

    All ranges are ``[start, end)``; ``b""`` end means +inf.
    """

    # -- reads ---------------------------------------------------------------

    def get(self, key: bytes) -> Optional[bytes]:
        raise NotImplementedError

    def multi_get(self, keys: list[bytes]) -> dict[bytes, Optional[bytes]]:
        return {k: self.get(k) for k in keys}

    def contains_key(self, key: bytes) -> bool:
        return self.get(key) is not None

    def scan(self, start: bytes, end: bytes, limit: int = -1,
             return_value: bool = True) -> list[tuple[bytes, Optional[bytes]]]:
        raise NotImplementedError

    def reverse_scan(self, start: bytes, end: bytes, limit: int = -1,
                     return_value: bool = True
                     ) -> list[tuple[bytes, Optional[bytes]]]:
        out = self.scan(start, end, -1, return_value)
        out.reverse()
        return out[:limit] if limit >= 0 else out

    # -- writes --------------------------------------------------------------

    def put(self, key: bytes, value: bytes) -> None:
        raise NotImplementedError

    def put_list(self, kvs: list[tuple[bytes, bytes]]) -> None:
        for k, v in kvs:
            self.put(k, v)

    def put_if_absent(self, key: bytes, value: bytes) -> Optional[bytes]:
        prev = self.get(key)
        if prev is None:
            self.put(key, value)
        return prev

    def get_and_put(self, key: bytes, value: bytes) -> Optional[bytes]:
        prev = self.get(key)
        self.put(key, value)
        return prev

    def compare_and_put(self, key: bytes, expect: bytes, update: bytes) -> bool:
        actual = self.get(key)
        if actual is None or actual != expect:
            return False
        self.put(key, update)
        return True

    def merge(self, key: bytes, value: bytes) -> None:
        """Append-style merge (reference: RocksDB merge operator with
        stringappend separated by a comma)."""
        prev = self.get(key)
        self.put(key, value if prev is None else prev + b"," + value)

    def delete(self, key: bytes) -> None:
        raise NotImplementedError

    def delete_list(self, keys: list[bytes]) -> None:
        for k in keys:
            self.delete(k)

    def apply_write_batch(self, ops: list[tuple[bytes, Optional[bytes]]]
                          ) -> None:
        """Apply a mixed run of puts (``(key, value)``) and deletes
        (``(key, None)``) in order.  The FSM's apply coalescer flushes
        whole PUT/DELETE runs through this; engines with a batch write
        path (the native store's ``tkv_apply_batch``) override it with
        ONE atomic call instead of one per op."""
        for k, v in ops:
            if v is None:
                self.delete(k)
            else:
                self.put(k, v)

    def delete_range(self, start: bytes, end: bytes) -> None:
        for k, _ in self.scan(start, end, -1, return_value=False):
            self.delete(k)

    def reset_range(self, start: bytes, end: bytes) -> None:
        """Clear EVERY namespace (data, sequences, locks) in [start, end).
        Snapshot load must be an exact state reset — merging would leave
        post-snapshot sequence/lock keys behind and make log replay after
        restart non-deterministic across replicas."""
        raise NotImplementedError

    # -- sequences -----------------------------------------------------------

    def get_sequence(self, key: bytes, step: int) -> Sequence:
        raise NotImplementedError

    def reset_sequence(self, key: bytes) -> None:
        raise NotImplementedError

    # -- distributed lock primitives ----------------------------------------

    def try_lock_with(self, key: bytes, locker_id: bytes, lease_ms: int,
                      keep_lease: bool) -> tuple[bool, int, bytes]:
        """Returns (acquired, fencing_token, current_owner_id)."""
        raise NotImplementedError

    def release_lock(self, key: bytes, locker_id: bytes) -> bool:
        raise NotImplementedError

    # -- admin / split support ----------------------------------------------

    def approximate_keys_in_range(self, start: bytes, end: bytes) -> int:
        return len(self.scan(start, end, -1, return_value=False))

    def jump_over(self, start: bytes, end: bytes, distance: int
                  ) -> Optional[bytes]:
        """The key `distance` entries after start within [start, end) —
        split-point discovery (reference: RocksRawKVStore#jumpOver)."""
        keys = self.scan(start, end, distance + 1, return_value=False)
        if len(keys) <= distance:
            return None
        return keys[distance][0]

    # -- snapshot support ----------------------------------------------------

    def serialize_range(self, start: bytes, end: bytes) -> bytes:
        raise NotImplementedError

    def load_serialized(self, blob: bytes) -> None:
        raise NotImplementedError


def _in_range(key: bytes, start: bytes, end: bytes) -> bool:
    if start and key < start:
        return False
    if end and key >= end:
        return False
    return True


class MemoryRawKVStore(RawKVStore):
    """Dict-backed store with a lazily-rebuilt sorted key index.

    Writes are O(1); the sorted view is rebuilt on the first range read
    after a write burst (reference MemoryRawKVStore uses a skip-list
    TreeMap; the C++ engine provides the production-grade ordered store).
    """

    def __init__(self) -> None:
        self._data: dict[bytes, bytes] = {}
        self._sorted: list[bytes] = []
        self._dirty = False
        self._sequences: dict[bytes, int] = {}
        self._locks: dict[bytes, LockOwner] = {}
        self._fencing = 0

    # -- reads ---------------------------------------------------------------

    def get(self, key: bytes) -> Optional[bytes]:
        return self._data.get(key)

    def _keys(self) -> list[bytes]:
        if self._dirty:
            self._sorted = sorted(self._data)
            self._dirty = False
        return self._sorted

    def scan(self, start: bytes, end: bytes, limit: int = -1,
             return_value: bool = True) -> list[tuple[bytes, Optional[bytes]]]:
        keys = self._keys()
        lo = bisect.bisect_left(keys, start) if start else 0
        hi = bisect.bisect_left(keys, end) if end else len(keys)
        sel = keys[lo:hi]
        if limit >= 0:
            sel = sel[:limit]
        if return_value:
            return [(k, self._data[k]) for k in sel]
        return [(k, None) for k in sel]

    # -- writes --------------------------------------------------------------

    def put(self, key: bytes, value: bytes) -> None:
        if key not in self._data:
            self._dirty = True
        self._data[key] = value

    def approximate_keys_in_range(self, start: bytes, end: bytes) -> int:
        # O(log n) against the sorted index — this runs on the store
        # heartbeat hot loop for every leader region, so the base class's
        # materialize-the-whole-range default is not acceptable here
        keys = self._keys()
        lo = bisect.bisect_left(keys, start) if start else 0
        hi = bisect.bisect_left(keys, end) if end else len(keys)
        return hi - lo

    def delete(self, key: bytes) -> None:
        if self._data.pop(key, None) is not None:
            self._dirty = True

    def reset_range(self, start: bytes, end: bytes) -> None:
        self.delete_range(start, end)
        for d in (self._sequences, self._locks):
            for k in [k for k in d if _in_range(k, start, end)]:
                del d[k]

    # -- sequences -----------------------------------------------------------

    def get_sequence(self, key: bytes, step: int) -> Sequence:
        cur = self._sequences.get(key, 0)
        if step <= 0:
            return Sequence(cur, cur)
        self._sequences[key] = cur + step
        return Sequence(cur, cur + step)

    def reset_sequence(self, key: bytes) -> None:
        self._sequences.pop(key, None)

    # -- locks ---------------------------------------------------------------

    def try_lock_with(self, key: bytes, locker_id: bytes, lease_ms: int,
                      keep_lease: bool) -> tuple[bool, int, bytes]:
        # graftcheck: allow(raw-clock) — KV lock-lease deadline: process-local TTL, not consensus timing
        now = time.monotonic()
        owner = self._locks.get(key)
        if owner is not None and not owner.expired(now):
            if owner.locker_id == locker_id:
                if keep_lease:
                    # pure lease renewal (watchdog): no new hold to release
                    owner.deadline = now + lease_ms / 1000.0
                else:
                    owner.acquires += 1  # reentrant acquire
                return True, owner.fencing_token, locker_id
            return False, owner.fencing_token, owner.locker_id
        self._fencing += 1
        self._locks[key] = LockOwner(locker_id, now + lease_ms / 1000.0,
                                     self._fencing)
        return True, self._fencing, locker_id

    def release_lock(self, key: bytes, locker_id: bytes) -> bool:
        owner = self._locks.get(key)
        if owner is None:
            return True
        if owner.locker_id != locker_id and not owner.expired():
            return False
        owner.acquires -= 1
        if owner.acquires <= 0 or owner.locker_id != locker_id:
            del self._locks[key]
        return True

    # -- snapshot ------------------------------------------------------------

    def serialize_range(self, start: bytes, end: bytes) -> bytes:
        kvs = self.scan(start, end)
        seqs = [(k, v) for k, v in self._sequences.items()
                if _in_range(k, start, end)]
        locks = [(k, o) for k, o in self._locks.items()
                 if _in_range(k, start, end)]
        out = bytearray(struct.pack("<III", len(kvs), len(seqs), len(locks)))
        for k, v in kvs:
            out += struct.pack("<I", len(k)) + k
            out += struct.pack("<I", len(v)) + v
        for k, v in seqs:
            out += struct.pack("<I", len(k)) + k + struct.pack("<q", v)
        # graftcheck: allow(raw-clock) — lock-lease persisted as REMAINING duration; stamps never cross stores
        now = time.monotonic()
        for k, o in locks:
            out += struct.pack("<I", len(k)) + k
            out += struct.pack("<I", len(o.locker_id)) + o.locker_id
            # persist remaining lease, not an absolute monotonic stamp
            out += struct.pack("<dqI", max(0.0, o.deadline - now),
                               o.fencing_token, o.acquires)
        out += struct.pack("<q", self._fencing)
        return bytes(out)

    def load_serialized(self, blob: bytes) -> None:
        buf = memoryview(blob)
        nkv, nseq, nlock = struct.unpack_from("<III", buf, 0)
        off = 12
        for _ in range(nkv):
            (kl,) = struct.unpack_from("<I", buf, off)
            off += 4
            k = bytes(buf[off:off + kl])
            off += kl
            (vl,) = struct.unpack_from("<I", buf, off)
            off += 4
            self.put(k, bytes(buf[off:off + vl]))
            off += vl
        for _ in range(nseq):
            (kl,) = struct.unpack_from("<I", buf, off)
            off += 4
            k = bytes(buf[off:off + kl])
            off += kl
            (v,) = struct.unpack_from("<q", buf, off)
            off += 8
            self._sequences[k] = v
        # graftcheck: allow(raw-clock) — lock-lease persisted as REMAINING duration; stamps never cross stores
        now = time.monotonic()
        for _ in range(nlock):
            (kl,) = struct.unpack_from("<I", buf, off)
            off += 4
            k = bytes(buf[off:off + kl])
            off += kl
            (ll,) = struct.unpack_from("<I", buf, off)
            off += 4
            lid = bytes(buf[off:off + ll])
            off += ll
            remain, token, acquires = struct.unpack_from("<dqI", buf, off)
            off += 20
            self._locks[k] = LockOwner(lid, now + remain, token, acquires)
        (fencing,) = struct.unpack_from("<q", buf, off)
        self._fencing = max(self._fencing, fencing)


class MetricsRawKVStore(RawKVStore):
    """Latency/ops decorator (reference: ``rhea:storage/MetricsRawKVStore``).

    Forwarders are generated from the inner store's public callables at
    construction time (instance attributes shadow the abstract base-class
    methods), so new ``RawKVStore`` methods — and any specialized batch
    implementations a concrete store adds — forward automatically and get
    a ``kv_<op>`` timing histogram without hand-written boilerplate.
    """

    def __init__(self, inner: RawKVStore, metrics) -> None:
        self._inner = inner
        self._metrics = metrics
        for name in dir(inner):
            if name.startswith("_"):
                continue
            attr = getattr(inner, name)
            if callable(attr):
                setattr(self, name, self._timed(name, attr))

    def _timed(self, name: str, fn):
        def timed(*a, **kw):
            # graftcheck: allow(raw-clock) — op-latency metric timing, not consensus timing
            t0 = time.monotonic()
            try:
                return fn(*a, **kw)
            finally:
                self._metrics.update(
                    # graftcheck: allow(raw-clock) — op-latency metric timing, not consensus timing
                    f"kv_{name}", (time.monotonic() - t0) * 1000.0)

        return timed

    def __getattr__(self, name: str):
        # non-callable attributes and anything set on the inner store
        # after construction
        return getattr(self._inner, name)
