"""StoreEngine: one KV storage process hosting many region raft groups.

Reference parity: ``rhea:StoreEngine`` (SURVEY.md §3.2) — boots the
shared RPC server + NodeManager, the shared RawKVStore, one RegionEngine
per region, the KV command processor, split handling, and (optionally)
heartbeats to the placement driver.

TPU-native design: when given a :class:`MultiRaftEngine`, every region's
quorum/commit bookkeeping runs on the engine's fused ``[G, P]`` device
tick — thousands of regions advance their commit indexes in one XLA
dispatch per tick instead of per-group Python work (SURVEY.md §3.5
"multi-group data parallelism", the BASELINE.json north star).
"""

from __future__ import annotations

import asyncio
import logging
import os
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Callable, Optional

from tpuraft.conf import Configuration
from tpuraft.core.cli_service import CliProcessors
from tpuraft.core.node_manager import NodeManager
from tpuraft.entity import PeerId
from tpuraft.errors import RaftError, Status
from tpuraft.options import NodeOptions, ReadOnlyOption, SnapshotOptions
from tpuraft.rheakv.kv_service import KVCommandProcessor
from tpuraft.rheakv.metadata import Region, StoreMeta
from tpuraft.rheakv.raw_store import (
    MemoryRawKVStore,
    MetricsRawKVStore,
    RawKVStore,
)
from tpuraft.rpc.messages import BatchRequest, CompactBeat
from tpuraft.rpc.transport import RpcError, is_no_method
from tpuraft.util import clock as clockmod
from tpuraft.util.clock import ClockSentinel
from tpuraft.util.metrics import MetricRegistry, prometheus_text
from tpuraft.util.trace import RECORDER, TRACER
from tpuraft.rheakv.region_engine import RegionEngine

LOG = logging.getLogger(__name__)


def _dir_usage_bytes(root: str) -> int:
    """Recursive file-size sum (the disk reconcile's 'du'); runs on an
    executor thread — never call from the event loop."""
    total = 0
    for dirpath, _dirs, names in os.walk(root):
        for n in names:
            try:
                total += os.path.getsize(os.path.join(dirpath, n))
            except OSError:
                pass
    return total


@dataclass
class StoreEngineOptions:
    cluster_name: str = "rheakv"
    server_id: str = ""                  # this store's PeerId string
    initial_regions: list[Region] = field(default_factory=list)
    data_path: str = ""                  # "" = memory storage
    election_timeout_ms: int = 1000
    snapshot_interval_secs: int = 0      # 0 = on-demand only
    raw_store_factory: Callable[[], RawKVStore] = MemoryRawKVStore
    # least keys a region must hold before a split is sensible
    least_keys_on_split: int = 16
    # PD heartbeat cadence (only used when a pd_client is wired)
    heartbeat_interval_ms: int = 1000
    # linearizable read mode for region groups (SAFE: quorum heartbeat
    # round per read batch; LEASE_BASED: trust the leader lease — the
    # reference's ReadOnlyOption, surfaced here like RheaKVStoreOptions)
    read_only_option: ReadOnlyOption = ReadOnlyOption.SAFE
    # wrap the raw store in the op-latency decorator (reference:
    # MetricsRawKVStore, enabled by RheaKVStoreOptions metrics flags)
    enable_kv_metrics: bool = False
    # "file" = one segment dir per region (round-1 layout);
    # "multilog" = ALL regions of this store share ONE C++ journal
    # engine — group-keyed records, one fsync per flush round across
    # regions, O(bytes/segment) fds (the reference's single-RocksDB
    # role; storage/multilog.py).  Only used when data_path is set.
    log_scheme: str = "file"
    # cap per-region log segment size (file/native schemes; 0 = the
    # storage default, 64MB).  Prefix compaction frees disk in whole-
    # segment units, so tight storage budgets want small segments —
    # reclaim can then actually return bytes between snapshots.
    log_segment_max_bytes: int = 0
    # group quiescence (engine-driven regions only): an idle, fully
    # replicated region hibernates after this many consecutive fully-
    # acked beat rounds — see RaftOptions.quiesce_after_rounds.  0 = off.
    quiesce_after_rounds: int = 0
    # cap for the PD-heartbeat failure backoff (bounded exponential:
    # interval x 2^fails, clamped here) — a down PD costs one cheap
    # probe per cap interval, not a hot retry loop
    pd_backoff_max_ms: int = 30000
    # serving-plane apply coalescing: the region FSMs flush consecutive
    # PUT/DELETE(-list) entries as ONE store batch write (one ctypes
    # call + one WAL record per run) instead of one call per op — see
    # KVStoreStateMachine.coalesce_applies
    fsm_coalesce: bool = True
    # kv_command_batch write sub-batches ride ONE KVOp.MULTI log entry
    # per region (one quorum round amortized).  Set False during a
    # rolling upgrade from a pre-batch build: a MULTI entry replicated
    # to a replica whose FSM predates it fails to apply and silently
    # diverges state — per-op entries stay wire/FSM-compatible both ways
    multi_op_entries: bool = True
    # geo deployment: this store's zone (failure-domain) label.  Carried
    # on PD heartbeats so the PD spreads leaders across zones; "" =
    # unlabeled (single-zone legacy deployments)
    zone: str = ""
    # store-wide SAFE ReadIndex amortization: pending read confirmations
    # of ALL led groups coalesce into one beat-plane round per window
    # (ReadConfirmBatcher) instead of one quorum heartbeat round per
    # group.  False = per-group rounds (the pre-batch behavior).
    read_confirm_batching: bool = True
    # store-wide WRITE amortization (the read plane's mirror): every led
    # group's pending entry windows toward one destination endpoint ride
    # ONE windowed store_append round (core/append_batcher.AppendBatcher)
    # instead of the send plane's stop-and-wait endpoint lane.  Receivers
    # that predate the RPC get permanent per-group AppendEntries
    # fallback.  False = the pre-write-plane send-plane lane.
    append_batching: bool = True
    # pipelined FSM apply: blind writes (PUT/DELETE/... — result known a
    # priori) ack the client the moment their entry COMMITS; the FSM
    # applies behind in coalesced batches, and the read fence
    # (read_index + wait_applied) keeps reads observing applied state.
    # False = ack after apply (the pre-write-plane behavior).
    ack_at_commit: bool = True
    # -- apply worker lane (compartmentalization) ----------------------------
    # run FSM apply on a dedicated store-wide worker thread instead of
    # the event loop (tpuraft/core/lanes.py): the lane thread OWNS the
    # raw store — fenced reads, snapshot serialization and split-point
    # probing are submitted through its FIFO queue, so the loop only
    # pays an await per batch and a hot store saturates a second core.
    # False = apply on the loop (the single-core default; the native
    # store's C calls already release the GIL under the lane, the
    # memory store still offloads the loop's share).
    apply_lane: bool = False
    # -- gray-failure survival (fail-slow detection + mitigation) ------------
    # score this store {HEALTHY, DEGRADED, SICK} from hot-path signals
    # (append/fsync latency, peer ack RTTs, apply backlog — see
    # tpuraft/util/health.py) and mitigate: a SICK self-score evacuates
    # led groups' leadership at a bounded rate, and the KV serving plane
    # sheds with EBUSY+retry-after instead of queueing behind a dying
    # disk.  False = observe-only never (no tracker at all).
    health_scoring: bool = True
    # custom thresholds/hysteresis (None = HealthOptions defaults)
    health_options: Optional[object] = None
    # scoring cadence; hysteresis counts these rounds, so
    # interval x worsen_after bounds detection latency
    health_eval_interval_ms: int = 500
    # SICK => proactively transfer led groups to the healthiest
    # caught-up voter.  False = detect + shed only (operator drains).
    evacuate_on_sick: bool = True
    # at most this many transfers per evaluation round, so evacuation
    # itself can never storm the cluster with elections
    evacuation_rate: int = 2
    # a region just transferred (or attempted) is left alone for this
    # many evaluation rounds
    evacuation_cooldown_rounds: int = 4
    # serving-plane degradation: once SICK, kv_command_batch sheds with
    # per-item EBUSY + retry-after when this many items are already in
    # flight (0 = never shed).  A gray store fails fast instead of
    # timing out 256 workers at p99=inf.
    shed_backlog_items: int = 512
    shed_retry_after_ms: int = 250
    # -- disk-pressure survival (capacity accounting + reaction ladder) ------
    # account this store's on-disk usage into hysteretic {OK, NEAR_FULL,
    # FULL} pressure (tpuraft/util/health.py DiskBudget; hot-path fed:
    # log-append bytes, snapshot commit/prune deltas, ENOSPC
    # observations; periodically reconciled against real usage).  The
    # reaction ladder: NEAR_FULL floors health DEGRADED (PD stops
    # placing leaders here) and starts urgent snapshot+compaction
    # reclaim; FULL floors SICK (evacuation) and sheds WRITES at
    # kv_service admission with retryable ERR_STORE_BUSY while reads
    # keep serving.  Requires data_path; False = no tracker.  See
    # docs/operations.md "Disk-pressure runbook".
    disk_guard: bool = True
    # byte budget for this store's data directory.  0 = derive capacity
    # from os.statvfs at reconcile (whole filesystem — production);
    # tests/soaks set an explicit budget matching the chaos quota.
    disk_budget_bytes: int = 0
    # pressure thresholds as fractions of the budget.  full_frac < 1.0
    # is the RESERVED HEADROOM: admission stops at full_frac so
    # reclaim's own writes (snapshot temp dirs, compaction tmp files)
    # still fit under the hard budget — the can't-compact-when-full
    # deadlock guard.
    disk_near_full_frac: float = 0.80
    disk_full_frac: float = 0.92
    # reconcile real usage (directory walk / statvfs, on an executor
    # thread) every N health rounds
    disk_reconcile_rounds: int = 4
    # pressure reclaim: urgent snapshot+log-compaction across led
    # regions, at most this many per health round, with a per-region
    # cooldown so one region isn't re-snapshotted every round
    disk_reclaim_rate: int = 2
    disk_reclaim_cooldown_rounds: int = 8
    # -- live metrics exposition ---------------------------------------------
    # serve Prometheus text at GET /metrics on a stdlib HTTP listener:
    # None = off (the default — the describe_metrics admin RPC and
    # SIGUSR2 describer dumps still work), 0 = bind an ephemeral port
    # (tests; the bound port lands in StoreEngine.metrics_http_port),
    # N = bind that port.  The listener runs on its own daemon thread
    # and only READS counters — best-effort consistency by design.
    metrics_port: Optional[int] = None
    metrics_host: str = "127.0.0.1"
    # metrics_text() render cache: per-region aggregation is O(regions),
    # so a tight scrape loop against a 1024-region store would burn the
    # serving thread re-rendering identical text — scrapes within the
    # TTL serve the cached render (stale-ok; the render's age is itself
    # exposed as tpuraft_metrics_age_seconds, bounded by this TTL).
    # 0 = render every call (tests / debugging).
    metrics_cache_ttl_ms: int = 250
    # -- per-region heat telemetry (fleet observability) ---------------------
    # track decayed EWMAs of writes/s, reads/s and bytes in/out per
    # region (util/heat.RegionHeatTracker), fed O(1) from the KV
    # serving paths and FSM apply, reported to the PD on the delta-
    # batched heartbeat (noise-gated) — the signal ROADMAP item 2's
    # split/merge/move policy consumes.  False = no tracker at all
    # (the bench-gate A/B knob).
    heat_tracking: bool = True
    # EWMA half-life: how fast a region's rates chase the live load /
    # decay when it goes idle.  ~10 heartbeat intervals by default.
    heat_half_life_s: float = 10.0
    # steady-heat keepalive: a led region whose standing rate hasn't
    # been reported for this long is re-reported even though the noise
    # gate sees no movement — the PD expires rates not refreshed
    # within ClusterStatsManager.heat_stale_s (30s), so this must stay
    # WELL below that or a steadily-hot region vanishes from the view
    heat_refresh_s: float = 10.0
    # -- time discipline (ISSUE 18) ------------------------------------------
    # injectable store clock (util/clock.py): EVERY timing-sensitive
    # consumer of this store — election timers, engine tick deadlines,
    # store-lease bookkeeping, health hysteresis — reads this clock, so
    # a ChaosClock here skews the store exactly like a machine with a
    # bad oscillator.  None = the process-wide SystemClock (zero
    # indirection cost: module default, bench-gated <=2%).
    clock: Optional[object] = None
    # assumed maximum relative clock drift rho between any two stores
    # (e.g. 0.05 = 5%).  Shrinks the leader's usable lease window and
    # the receiver-side store-lease grant by (1 - rho), and arms the
    # peer-skew sentinel's fencing: a store whose clock the beat-plane
    # skew estimator flags as deviating beyond rho stops serving
    # lease reads (SAFE fallback) until it recovers.  0.0 = legacy
    # exact-clock behavior (no pads, sentinel observes but never
    # fences).
    clock_drift_bound: float = 0.0


class _GroupFence:
    """One group's pending read fence inside a ReadConfirmBatcher round:
    the (node, term) pinned at round build plus the ack tally.  Resolves
    its futures True the moment a voter quorum (both configs while
    joint) has acked IN TERM — stragglers then only delay other groups,
    never this one's readers."""

    __slots__ = ("node", "term", "futs", "new_peers", "old_peers", "acked",
                 "device")

    def __init__(self, node, futs: list) -> None:
        self.node = node
        self.term = node.current_term
        self.futs = futs
        self.new_peers = set(node.conf_entry.conf.peers)
        self.old_peers = set(node.conf_entry.old_conf.peers)
        self.acked = {node.server_id}
        # True when the quorum tally runs on the engine's device fence
        # lane (EngineControl.arm_read_fence) instead of this host set
        self.device = False

    def _quorum(self) -> bool:
        ok_new = (len(self.acked & self.new_peers)
                  >= len(self.new_peers) // 2 + 1)
        if not self.old_peers:
            return ok_new
        # joint consensus: a read fence must prove leadership against
        # BOTH quorums — a new-config-only majority may not intersect
        # the electorate that could depose us mid-change
        return ok_new and (len(self.acked & self.old_peers)
                           >= len(self.old_peers) // 2 + 1)

    def note_ack(self, peer) -> None:
        node = self.node
        if not node.is_leader() or node.current_term != self.term:
            return  # deposed/re-elected mid-round: this fence is void
        self.acked.add(peer)
        if self._quorum():
            self.resolve(True)

    def note_quorum(self) -> None:
        """Device fence lane callback: the engine tick's fused q_ack
        reduction covered this round's start.  Same (is_leader, term)
        gate as the per-ack path — the device counts raw ack arrival
        times, the host still vouches for the leadership pin."""
        node = self.node
        if not node.is_leader() or node.current_term != self.term:
            return
        self.resolve(True)

    def resolve(self, ok: bool) -> None:
        for fut in self.futs:
            if not fut.done():
                fut.set_result(ok)

    @property
    def done(self) -> bool:
        return all(fut.done() for fut in self.futs)


# graftcheck: loop-confined — one batcher per StoreEngine, driven from
# the store's event loop; pending lists, fences and counters are
# lockless by that confinement
class ReadConfirmBatcher:
    """Store-wide SAFE ReadIndex confirmation amortizer.

    ``ReadOnlyService`` already batches the concurrent readers of ONE
    group into one confirmation round; at region density that still
    costs one quorum heartbeat round PER GROUP with pending reads.  This
    batcher coalesces the pending SAFE confirmations of ALL led groups
    on a store into one beat-plane round: each round packs every pending
    group's read fence as a ``CompactBeat`` row and sends ONE
    ``multi_beat_fast`` RPC per destination endpoint (exactly how the
    HeartbeatHub amortizes idle beats), then tallies per-group in-term
    acks.  A ``BeatAck(ok=True)`` proves the follower saw this node as
    the leader of this term when it answered — the same leadership proof
    an empty-AppendEntries ack carries — so the fence is SAFE, not
    clock-dependent.  Deviating rows (term moved, follower restarted,
    committed behind) get a classic full-semantics beat as the follow-up
    and its in-term ack still counts.

    Safety argument (docs/architecture.md "Read-fence batching"):
    read_index is pinned BEFORE ``confirm()`` enqueues, every beat of a
    round is built AFTER the round collected its batch, and a fence only
    counts acks while ``(is_leader, term)`` still match the values
    pinned at round build — so each reader's confirmation round-trip
    strictly follows its invoke, which is the ReadIndex linearizability
    requirement.  Rounds are windowed (``max_inflight_rounds``): one
    dead endpoint's RPC timeout delays only its own round's stragglers,
    not the store's whole read plane.
    """

    max_inflight_rounds = 4

    def __init__(self) -> None:
        self._pending: list = []   # (node, future)
        self._task: Optional[asyncio.Task] = None
        self._rounds_inflight: set = set()
        self._fast_ok: dict[str, bool] = {}  # dst serves multi_beat_fast
        # nudges the drain out of its completed-round wait when a NEW
        # fence arrives with window slots free: without it, one STALLED
        # (not dead) endpoint's round parked the drain on
        # FIRST_COMPLETED and every later fence — healthy endpoints
        # included — convoyed behind the stall until its RPC timed out
        # (found by the gray-failure stalled-endpoint tests)
        self._arrival = asyncio.Event()
        # gray-failure signal sink (HealthTracker): every fence round's
        # RPC doubles as a per-endpoint RTT probe
        self.health = None
        # store clock (ISSUE 18): StoreEngine re-points this at its
        # injected clock so RTT probes stay on the store's time plane
        self.clock = clockmod.SYSTEM
        # counters (describe() + bench/soak stats lines)
        self.confirms = 0       # fences requested
        self.rounds = 0         # store-wide rounds run
        self.beat_rpcs = 0      # multi_beat_fast RPCs sent
        self.beats = 0          # CompactBeat fence rows carried
        self.classic_beats = 0  # classic per-peer follow-ups/fallbacks
        self.failed = 0         # fences that ended unconfirmed
        self.device_fences = 0  # fences tallied on the engine device lane
        # gauges bound to the live counters (the HeartbeatHub idiom)
        self.metrics = MetricRegistry()
        for name in ("confirms", "rounds", "beat_rpcs", "beats",
                     "classic_beats", "failed", "device_fences"):
            self.metrics.gauge(f"read_batcher.{name}",
                               lambda n=name: getattr(self, n))
        self.metrics.gauge(
            "read_batcher.reads_per_round",
            lambda: self.confirms / self.rounds if self.rounds else 0.0)

    def counters(self) -> dict:
        return {
            "read_confirms": self.confirms,
            "read_rounds": self.rounds,
            "read_beat_rpcs": self.beat_rpcs,
            "read_beats": self.beats,
            "read_classic_beats": self.classic_beats,
            "read_failed": self.failed,
            "read_device_fences": self.device_fences,
        }

    def describe(self) -> str:
        amort = self.confirms / self.rounds if self.rounds else 0.0
        return (f"ReadConfirmBatcher<confirms={self.confirms} "
                f"rounds={self.rounds} reads_per_round={amort:.2f} "
                f"beat_rpcs={self.beat_rpcs} beats={self.beats} "
                f"classic={self.classic_beats} failed={self.failed} "
                f"device_fences={self.device_fences}>")

    async def confirm(self, node) -> bool:
        """Enqueue one group's SAFE leadership fence; resolves True once
        a voter quorum acked a beat of a round that started after this
        call."""
        self.confirms += 1
        fut = asyncio.get_running_loop().create_future()
        self._pending.append((node, fut))
        self._arrival.set()
        if self._task is None or self._task.done():
            self._task = asyncio.ensure_future(self._drain())
        return await fut

    async def shutdown(self) -> None:
        for _node, fut in self._pending:
            if not fut.done():
                fut.set_result(False)
        self._pending.clear()
        for t in list(self._rounds_inflight):
            t.cancel()
        if self._task is not None and not self._task.done():
            self._task.cancel()
        self._task = None

    async def _drain(self) -> None:
        # microtask hop: every fence enqueued by tasks runnable in this
        # loop iteration joins the first round (the _Batcher idiom);
        # then windowed rounds — a round stuck on a dead endpoint's
        # timeout must not convoy later readers behind it
        await asyncio.sleep(0)
        while self._pending or self._rounds_inflight:
            while self._pending \
                    and len(self._rounds_inflight) < self.max_inflight_rounds:
                batch, self._pending = self._pending, []
                t = asyncio.ensure_future(self._round(batch))
                self._rounds_inflight.add(t)
                t.add_done_callback(self._reap_round)
            if self._rounds_inflight:
                # wake on a round completing OR a new fence arriving:
                # with window slots free the new fence must start ITS
                # OWN round now, not convoy behind a stalled endpoint's
                self._arrival.clear()
                arrival = asyncio.ensure_future(self._arrival.wait())
                try:
                    await asyncio.wait(
                        set(self._rounds_inflight) | {arrival},
                        return_when=asyncio.FIRST_COMPLETED)
                finally:
                    arrival.cancel()

    def _reap_round(self, t: asyncio.Task) -> None:
        self._rounds_inflight.discard(t)
        if not t.cancelled() and t.exception() is not None:
            LOG.warning("read-confirm round failed: %r", t.exception())

    async def _round(self, batch: list) -> None:
        """One store-wide round: build every pending group's fence beats
        SYNCHRONOUSLY (no await between the is_leader check and the
        build — the HeartbeatHub invariant), dispatch one RPC per
        destination, tally."""
        self.rounds += 1
        groups: dict[int, _GroupFence] = {}
        order: list[_GroupFence] = []
        for node, fut in batch:
            st = groups.get(id(node))
            if st is None:
                st = groups[id(node)] = _GroupFence(node, [fut])
                order.append(st)
            else:
                st.futs.append(fut)
        by_dst: dict[str, list] = {}
        classic: list = []
        try:
            for st in order:
                node = st.node
                if not node.is_leader():
                    st.resolve(False)
                    continue
                # engine-backed group: the quorum tally rides the device
                # tick's fused q_ack reduction (the fence_ok lane) — the
                # beats below still go out (they ARE the acks the lane
                # counts), but the per-ack host set arithmetic is skipped
                ctrl = getattr(node, "_ctrl", None)
                if ctrl is not None and getattr(ctrl, "drives_read_fences",
                                                False):
                    ctrl.arm_read_fence(st)
                    st.device = True
                    self.device_fences += 1
                voters = st.new_peers | st.old_peers
                committed = node.ballot_box.last_committed_index
                for r in node.replicators.all():
                    if r.peer not in voters:
                        continue   # a learner's ack proves nothing
                    if (r.peer_multi_hb and r._matched
                            and self._fast_ok.get(r.peer.endpoint, True)):
                        beat = CompactBeat(
                            group_id=node.group_id,
                            server_id=str(node.server_id),
                            peer_id=str(r.peer),
                            term=st.term,
                            committed_index=min(committed, r.match_index))
                        by_dst.setdefault(r.peer.endpoint, []
                                          ).append((st, r, beat))
                    else:
                        classic.append((st, r))
                if not st.device:
                    st.note_ack(node.server_id)  # self-only quorum case
            await asyncio.gather(
                *(self._beat_dst(dst, rows) for dst, rows in by_dst.items()),
                *(self._classic(st, r) for st, r in classic))
        finally:
            # device fences: the RPCs completed, so every ack this round
            # can produce is already in the engine's last_ack rows — one
            # forced tick per distinct engine reduces them and fires
            # fence_ok NOW (the adaptive loop's own tick may be a task
            # behind), so resolution is deterministic before the sweep
            dev_pending = [st for st in order
                           if st.device and not st.done]
            if dev_pending:
                engines = {id(st.node._ctrl.engine): st.node._ctrl.engine
                           for st in dev_pending}
                for eng in engines.values():
                    try:
                        eng.tick_once()
                    except Exception:  # noqa: BLE001 — fall to the sweep
                        LOG.exception("fence-resolve tick failed")
            failed_groups = 0
            for st in order:
                if st.device:
                    # the fence dies with the round either way; a void
                    # entry left armed would pin fence_start and spin
                    # dirty marks on every later ack
                    ctrl = getattr(st.node, "_ctrl", None)
                    if ctrl is not None:
                        ctrl.engine.discard_read_fence(ctrl.slot, st)
                if not st.done:
                    self.failed += 1
                    failed_groups += 1
                st.resolve(False)
            if failed_groups:
                # fence-round outcome (flight recorder): one event per
                # round with failures, not per group — a total
                # partition at region density must not churn the ring
                # with thousands of identical rows per round
                RECORDER.record("fence_round_failed", "",
                                groups=failed_groups,
                                beats=len(by_dst) + len(classic))

    async def _beat_dst(self, dst: str, rows: list) -> None:
        node = rows[0][0].node
        self.beat_rpcs += 1
        self.beats += len(rows)
        t0 = self.clock.monotonic()
        try:
            resp = await node.transport.call(
                dst, "multi_beat_fast",
                BatchRequest(items=[b for _s, _r, b in rows]),
                timeout_ms=node.options.election_timeout_ms // 2 or 1)
        except RpcError as e:
            if is_no_method(e):
                # pre-beat-plane receiver: classic beats from now on
                self._fast_ok[dst] = False
                await asyncio.gather(
                    *(self._classic(st, r) for st, r, _b in rows))
            return  # silence: the fences just miss these acks
        if self.health is not None:
            self.health.note_peer_rtt(dst, self.clock.monotonic() - t0)
        if len(resp.items) != len(rows):
            # short/overlong reply reads as silence for the whole chunk
            # (zip would pair acks with the wrong fences)
            LOG.warning("read-fence multi_beat_fast %s: %d acks for %d "
                        "beats", dst, len(resp.items), len(rows))
            return
        now = self.clock.monotonic()
        fallback: list = []
        for (st, r, _b), ack in zip(rows, resp.items):
            if getattr(ack, "ok", False):
                # inline ack bookkeeping, exactly like the hub's fast
                # path: the lease plane sees the (peer, when) write too
                # (for device fences on_peer_ack IS the tally — it lands
                # in the engine's last_ack row the fence_ok lane reduces)
                r.last_rpc_ack = now
                st.node.on_peer_ack(r.peer, now)
                if not st.device:
                    st.note_ack(r.peer)
            else:
                fallback.append((st, r))
        if fallback:
            # full-semantics follow-up: ok=False may just mean the
            # follower's committed lags (restart) — a classic beat still
            # returns the in-term ack the fence needs, and handles a
            # higher term via the normal step-down path
            await asyncio.gather(*(self._classic(st, r)
                                   for st, r in fallback))

    async def _classic(self, st: _GroupFence, r) -> None:
        self.classic_beats += 1
        try:
            ok = await r.send_heartbeat()
        except Exception:  # noqa: BLE001 — one peer's beat only
            return
        if ok and not st.device:
            # device fences: send_heartbeat already recorded the ack
            # arrival into the engine row the fence_ok lane reduces
            st.note_ack(r.peer)


class StoreEngine:
    def __init__(self, opts: StoreEngineOptions, rpc_server, transport,
                 multi_raft_engine=None, pd_client=None) -> None:
        self.opts = opts
        self.cluster_name = opts.cluster_name
        self.server_id = PeerId.parse(opts.server_id)
        self.rpc_server = rpc_server
        self.transport = transport
        # time discipline (ISSUE 18): ONE clock per store; every timing
        # consumer below reads it.  The sentinel rides the beat-plane
        # ack RTT probes to estimate per-peer skew; with drift_bound > 0
        # a suspect local clock fences lease reads (SAFE fallback).
        self.clock = clockmod.resolve(opts.clock)
        self.clock_sentinel = ClockSentinel(
            drift_bound=opts.clock_drift_bound,
            clock=self.clock, label=str(opts.server_id))
        self.node_manager = NodeManager(rpc_server)
        CliProcessors(self.node_manager)
        hub = self.node_manager.heartbeat_hub
        hub.clock = self.clock
        hub.clock_drift_bound = opts.clock_drift_bound
        hub.clock_sentinel = self.clock_sentinel
        # per-region heat telemetry: ONE tracker per store, fed from
        # the KV serving paths (kv_processor binds it at construction)
        # + FSM apply, folded and reported on the PD heartbeat cadence
        self.heat = None
        if opts.heat_tracking:
            from tpuraft.util.heat import RegionHeatTracker

            self.heat = RegionHeatTracker(
                half_life_s=opts.heat_half_life_s)
        self.kv_processor = KVCommandProcessor(self)
        # store-wide SAFE read-confirmation amortizer (attached to every
        # region node's ReadOnlyService by RegionEngine.start)
        self.read_batcher: Optional[ReadConfirmBatcher] = \
            ReadConfirmBatcher() if opts.read_confirm_batching else None
        if self.read_batcher is not None:
            self.read_batcher.clock = self.clock
            from tpuraft.util import describer

            describer.register(self.read_batcher)
        # store-wide write plane (the read batcher's mirror): every
        # region node's replicators submit their windows here
        # (RegionEngine.start attaches it to each node)
        self.append_batcher = None
        if opts.append_batching:
            from tpuraft.core.append_batcher import AppendBatcher
            from tpuraft.util import describer

            self.append_batcher = AppendBatcher()
            self.append_batcher.clock = self.clock
            describer.register(self.append_batcher)
        # gray-failure plane: one HealthTracker per store, fed by the
        # hot path (LogManager flush timing, beat-plane ack RTTs, FSM
        # apply backlog) and acted on by the health loop below
        self.health = None
        self._health_task: Optional[asyncio.Task] = None
        self._evac_round = 0                   # evaluation round counter
        self._evac_cooldown: dict[int, int] = {}  # region -> round gate
        self.evacuations = 0          # transfers triggered by SICK score
        self.evacuation_rounds = 0    # eval rounds that attempted any
        if opts.health_scoring:
            from tpuraft.util import describer
            from tpuraft.util.health import HealthTracker

            self.health = HealthTracker(opts.health_options,
                                        clock=self.clock.monotonic,
                                        label=str(self.server_id))
            describer.register(self.health)
            if self.read_batcher is not None:
                self.read_batcher.health = self.health
            if self.append_batcher is not None:
                # write-plane rounds double as per-endpoint RTT probes
                self.append_batcher.health = self.health
        # disk-pressure plane: one DiskBudget per store, fed by the hot
        # path (LogManager append bytes, snapshot commit/prune deltas,
        # ENOSPC observations) and reconciled + acted on by the health
        # loop's _disk_round below
        self.disk_budget = None
        self.disk_reclaims = 0        # pressure snapshots that completed
        self.disk_reclaim_rounds = 0  # rounds that attempted reclaim
        self.disk_shed_items = 0      # writes bounced at FULL admission
        self._reclaim_cooldown: dict[int, int] = {}  # region -> round gate
        if opts.disk_guard and opts.data_path:
            from tpuraft.util import describer
            from tpuraft.util.health import DiskBudget, DiskBudgetOptions

            self.disk_budget = DiskBudget(
                DiskBudgetOptions(
                    budget_bytes=opts.disk_budget_bytes,
                    near_full_frac=opts.disk_near_full_frac,
                    full_frac=opts.disk_full_frac),
                label=str(self.server_id))
            describer.register(self.disk_budget)
        self.metrics = MetricRegistry(enabled=opts.enable_kv_metrics)
        if self.health is not None:
            self.health.register_gauges(self.metrics)
        if self.disk_budget is not None:
            self.disk_budget.register_gauges(self.metrics)
        self.clock_sentinel.register_gauges(self.metrics)
        from tpuraft.util import describer as _describer
        _describer.register(self.clock_sentinel)
        raw: RawKVStore = opts.raw_store_factory()
        if opts.enable_kv_metrics:
            raw = MetricsRawKVStore(raw, self.metrics)
        self.raw_store: RawKVStore = raw
        # apply worker lane: ONE dedicated thread per store owning the
        # raw store's mutation order (see StoreEngineOptions.apply_lane)
        self.apply_lane = None
        if opts.apply_lane:
            from tpuraft.core.lanes import WorkerLane

            self.apply_lane = WorkerLane(
                name=f"apply-{self.server_id.endpoint}")
        # SIGTERM drain (process topology): True bounces NEW kv work
        # with a retryable busy while admitted items finish — see drain()
        self.draining = False
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self.multi_raft_engine = multi_raft_engine
        self.pd_client = pd_client
        self._regions: dict[int, RegionEngine] = {}
        self._leader_regions: set[int] = set()
        self._started = False
        self._pending_splits: set[int] = set()
        # region lifecycle plane (merge/move) counters — the soak exit
        # gate and admin `regions` view read these
        self.merges_led = 0        # source-side merges this store drove
        self.regions_retired = 0   # source replicas retired (merged away)
        self.regions_absorbed = 0  # absorb applies folded into a target
        self.moves_applied = 0     # PD-ordered replica moves executed
        # regions this store retired (merged away) -> absorbing target.
        # The PD only finalizes a pending merge on an explicit report,
        # so a re-issued KIND_MERGE that arrives after local retirement
        # is answered from this map with a fresh report (the original
        # may have been lost with a crashed leader).  Repopulated by
        # MERGE_COMMIT replay after a restart.
        self._retired_into: dict[int, int] = {}
        self._heartbeat_task: Optional[asyncio.Task] = None
        self._meta_journal = None  # store-lifetime ref (multilog scheme)
        # delta-batched PD reporting state: region -> (fingerprint,
        # last-reported approximate_keys); dirty = force-report next
        # round (fresh leadership, failed instruction); need_full =
        # next batch carries EVERY led region (first contact, or the
        # PD answered need_full after its own failover)
        self._pd_reported: dict[int, tuple] = {}
        self._pd_dirty: set[int] = set()
        self._pd_need_full = True
        # does the PD client's store_heartbeat_batch accept health= /
        # heat=?  Probed from the signature (not by catching TypeError,
        # which would also swallow bugs inside a real implementation):
        # a pre-health/pre-heat subclass override is reported to
        # without the kwargs it predates — the alternative is the
        # retry loop eating its TypeError forever and silently
        # starving the PD of heartbeats.
        self._pd_health_kwarg = True
        self._pd_heat_kwarg = True
        if pd_client is not None:
            import inspect

            try:
                params = inspect.signature(
                    pd_client.store_heartbeat_batch).parameters
                has_var_kw = any(p.kind == p.VAR_KEYWORD
                                 for p in params.values())
                self._pd_health_kwarg = "health" in params or has_var_kw
                self._pd_heat_kwarg = "heat" in params or has_var_kw
            except (TypeError, ValueError):
                pass  # unintrospectable callable: assume current API
        self.pd_batches_sent = 0     # observability (bench counters)
        self.pd_deltas_sent = 0
        self.pd_full_syncs = 0
        self.pd_hb_failures = 0
        self.pd_heat_rows_sent = 0
        if self.heat is not None:
            from tpuraft.util import describer

            describer.register(self.heat)
        # region -> (last-reported heat score, reported-at monotonic) —
        # the noise gate's memory (mirrors _pd_reported for the keys/
        # epoch delta plane) plus the steady-heat keepalive's clock
        self._pd_heat_reported: dict[int, tuple[float, float]] = {}
        # live metrics exposition: the describe_metrics admin RPC makes
        # a running fleet scrapeable over the wire (no signals), and the
        # optional HTTP listener serves the same text to Prometheus
        self.rpc_server.register("cli_describe_metrics",
                                 self._handle_describe_metrics)
        self._metrics_httpd = None
        self.metrics_http_port: Optional[int] = None
        # metrics_text render cache (satellite: a tight scrape loop at
        # region density must not burn the serving thread re-rendering):
        # (body, rendered_at_monotonic); the HTTP daemon thread and the
        # loop-side RPC handler both serve through it
        self._metrics_cache_lock = threading.Lock()
        self._metrics_cache: tuple[Optional[str], float] = \
            (None, 0.0)  # guarded-by: _metrics_cache_lock
        self.metrics_renders = 0       # actual renders (cache misses)
        self.metrics_cache_hits = 0    # scrapes served from the cache

    # -- lifecycle -----------------------------------------------------------

    async def start(self) -> None:
        self._loop = asyncio.get_running_loop()
        if self.health is not None:
            # beat-plane RPCs double as per-endpoint RTT probes
            self.node_manager.heartbeat_hub.health = self.health
            # event-loop lag probe: scheduling delay of a call_later
            # chain — loop saturation becomes a scored gray-failure
            # signal instead of a bench-only inference
            self.health.loop_lag.start()
        if self.multi_raft_engine is not None:
            await self.multi_raft_engine.start()
        # batched-concurrent region boot: one region at a time serializes
        # every node.init's await points — at region density (rhea:
        # StoreEngine's thousands-of-regions role) that alone dominates
        # store restart time.  Bounded batches keep the task herd small.
        BOOT_BATCH = 128
        regions = list(self.opts.initial_regions)
        for i in range(0, len(regions), BOOT_BATCH):
            # settle the WHOLE batch before failing: a bare gather would
            # abort on the first error while sibling boots keep running
            # detached against a half-torn store
            results = await asyncio.gather(
                *(self._start_region(r) for r in regions[i:i + BOOT_BATCH]),
                return_exceptions=True)
            for res in results:
                if isinstance(res, BaseException):
                    raise res
        self._started = True
        if self.pd_client is not None:
            self._heartbeat_task = asyncio.ensure_future(
                self._heartbeat_loop())
        if self.health is not None:
            self._wire_multilog_probe()
        if self.health is not None or self.disk_budget is not None:
            self._health_task = asyncio.ensure_future(self._health_loop())
        if self.opts.metrics_port is not None:
            self._start_metrics_http()
        LOG.info("store engine %s up with %d regions", self.server_id,
                 len(self._regions))

    def _wire_multilog_probe(self) -> None:
        """multilog scheme: the shared group commit times every fsync
        in its executor thread — feed those samples to the disk probe
        (the LogManager's flush timing covers the file scheme)."""
        if self.opts.log_scheme != "multilog" or not self.opts.data_path:
            return
        from tpuraft.storage.multilog import peek_engine

        store_base = (f"{self.opts.data_path}/"
                      f"{self.server_id.ip}_{self.server_id.port}")
        eng = peek_engine(f"{store_base}/mlog")
        if eng is not None:
            eng.group_commit.health_probe = self.health.disk

    async def shutdown(self) -> None:
        self._started = False
        if self._metrics_httpd is not None:
            httpd = self._metrics_httpd
            self._metrics_httpd = None
            # serve_forever exits on shutdown(); it blocks up to the
            # poll interval, so hop off the event loop for it
            await asyncio.get_running_loop().run_in_executor(
                None, httpd.shutdown_blocking)
        if self.heat is not None:
            from tpuraft.util import describer

            describer.unregister(self.heat)
        if self._heartbeat_task is not None:
            self._heartbeat_task.cancel()
            self._heartbeat_task = None
        if self._health_task is not None:
            self._health_task.cancel()
            self._health_task = None
        if self.health is not None:
            from tpuraft.util import describer

            self.health.loop_lag.stop()
            describer.unregister(self.health)
        if self.disk_budget is not None:
            from tpuraft.util import describer

            describer.unregister(self.disk_budget)
        if self.read_batcher is not None:
            from tpuraft.util import describer

            describer.unregister(self.read_batcher)
            await self.read_batcher.shutdown()
        if self.append_batcher is not None:
            from tpuraft.util import describer

            describer.unregister(self.append_batcher)
            await self.append_batcher.shutdown()
        for engine in list(self._regions.values()):
            await engine.shutdown()
        self._regions.clear()
        if self.multi_raft_engine is not None:
            await self.multi_raft_engine.shutdown()
        if self.apply_lane is not None:
            # after the regions: no FSMCaller is left to submit applies
            await self.apply_lane.aclose()
        close = getattr(self.raw_store, "close", None)
        if close is not None:
            close()  # native engine: flush + release the WAL fd
        if self._meta_journal is not None:
            from tpuraft.storage.meta_multilog import _release_journal

            _release_journal(self._meta_journal)
            self._meta_journal = None

    def loop_call_threadsafe(self, fn, *args) -> None:
        """Hop a loop-confined engine call off a worker lane thread
        (lane-applied RANGE_SPLIT is the one caller today)."""
        loop = self._loop
        if loop is None:
            fn(*args)
            return
        loop.call_soon_threadsafe(fn, *args)

    async def drain(self, timeout_s: float = 10.0) -> bool:
        """SIGTERM drain: stop admitting NEW kv work (handlers bounce it
        with a retryable busy the client re-offers elsewhere), then wait
        until every already-admitted item has acked — bounded by
        ``timeout_s``.  Returns True when the pipe emptied in time.
        The caller shuts the engine down afterwards; leadership moves
        when the silenced groups' peers time out, exactly like a crash
        but with zero lost acks."""
        self.draining = True
        # graftcheck: allow(raw-clock) — SIGTERM drain budget is REAL
        # wall seconds: a frozen/slow store clock must not stretch the
        # operator's shutdown window
        deadline = time.monotonic() + timeout_s
        while self.kv_processor.inflight_items > 0:
            # graftcheck: allow(raw-clock) — same real-time drain budget
            if time.monotonic() >= deadline:
                LOG.warning("drain timed out with %d items in flight",
                            self.kv_processor.inflight_items)
                return False
            await asyncio.sleep(0.01)
        return True

    # -- gray-failure survival: health loop + leadership evacuation ----------

    async def _health_loop(self) -> None:
        """Steady-cadence scoring (hysteresis counts these rounds) +
        SICK-triggered mitigation.  Detection latency is bounded by
        interval x worsen_after; evacuation is rate-bounded per round
        so mitigation can never itself storm the cluster."""
        from tpuraft.util.health import SICK

        interval = self.opts.health_eval_interval_ms / 1000.0
        while self._started:
            try:
                await asyncio.sleep(interval)
                self._evac_round += 1
                if self.disk_budget is not None:
                    await self._disk_round(self._evac_round)
                if self.health is None:
                    continue
                level = self.health.evaluate()
                if level == SICK and self.opts.evacuate_on_sick:
                    await self._evacuate_leaders()
            except asyncio.CancelledError:
                return
            except Exception:  # noqa: BLE001 — scoring must never die
                LOG.exception("health loop round failed")

    # -- disk-pressure survival: accounting + reaction ladder ----------------

    def _store_base(self) -> str:
        return (f"{self.opts.data_path}/"
                f"{self.server_id.ip}_{self.server_id.port}")

    async def _disk_round(self, round_no: int) -> None:
        """One disk-pressure round: periodic usage reconciliation
        (directory walk, off-loop), pressure fold, health floor
        (NEAR_FULL => DEGRADED stops PD leader placement; FULL => SICK
        engages the evacuation machinery), and rate-bounded urgent
        reclaim while under pressure."""
        from tpuraft.util.health import (DEGRADED, HEALTHY, SICK,
                                         PRESSURE_FULL, PRESSURE_NEAR_FULL,
                                         PRESSURE_OK)

        b = self.disk_budget
        if round_no % max(1, self.opts.disk_reconcile_rounds) == 1:
            loop = asyncio.get_running_loop()
            base = self._store_base()
            if self.opts.disk_budget_bytes > 0:
                used = await loop.run_in_executor(
                    None, _dir_usage_bytes, base)
                b.reconcile(used)
            else:
                # no explicit budget: whole-filesystem statvfs view
                try:
                    sv = await loop.run_in_executor(None, os.statvfs, base)
                    b.reconcile((sv.f_blocks - sv.f_bavail) * sv.f_frsize,
                                sv.f_blocks * sv.f_frsize)
                except OSError:
                    pass
        level = b.evaluate()
        if self.health is not None:
            if level == PRESSURE_FULL:
                self.health.set_floor(SICK, "disk_full")
            elif level == PRESSURE_NEAR_FULL:
                self.health.set_floor(DEGRADED, "disk_near_full")
            else:
                self.health.set_floor(HEALTHY)
        if level != PRESSURE_OK:
            await self._reclaim_round(level)

    async def _reclaim_round(self, pressure: str) -> None:
        """Urgent reclaim under pressure: snapshot + log-compact up to
        ``disk_reclaim_rate`` led regions this round (cooldown-gated
        per region).  Triggered already at NEAR_FULL — i.e. inside the
        reserved headroom below full_frac — so the snapshot/compaction
        writes themselves still fit under the hard budget."""
        self.disk_reclaim_rounds += 1
        done = 0
        for rid in self.leader_region_ids():
            if done >= max(1, self.opts.disk_reclaim_rate):
                break
            if self._reclaim_cooldown.get(rid, 0) > self._evac_round:
                continue
            engine = self._regions.get(rid)
            if engine is None or engine.node is None:
                continue
            # cooldown on ATTEMPT: a save that bounces (EBUSY, or
            # ENOSPC inside the headroom) must not be hammered every
            # round
            self._reclaim_cooldown[rid] = (
                self._evac_round
                + max(1, self.opts.disk_reclaim_cooldown_rounds))
            try:
                st = await engine.node.snapshot()
            except Exception:  # noqa: BLE001 — reclaim must never die
                LOG.exception("pressure reclaim snapshot failed (region %d)",
                              rid)
                continue
            if st.is_ok():
                done += 1
                self.disk_reclaims += 1
                RECORDER.record("disk_reclaim", engine.group_id,
                                node=str(self.server_id), pressure=pressure)
                LOG.warning("disk-pressure reclaim: region %d snapshotted + "
                            "log-compacted (store %s is %s)", rid,
                            self.server_id, pressure)

    def should_shed_writes(self) -> tuple[bool, int]:
        """FULL-disk admission gate (kv_service): WRITE ops bounce with
        the retryable busy while reads keep serving — a full store
        stays a useful read replica while reclaim frees space.
        Returns (shed?, retry_after_ms)."""
        from tpuraft.util.health import PRESSURE_FULL

        if self.disk_budget is None \
                or self.disk_budget.pressure() != PRESSURE_FULL:
            return False, 0
        return True, self.opts.shed_retry_after_ms

    async def _evacuate_leaders(self) -> int:
        """Proactive leadership evacuation: move up to
        ``evacuation_rate`` led groups to the healthiest caught-up
        voter this round.  Hysteretic by construction — only a SICK
        (not DEGRADED) score reaches here, and the tracker's
        recover_after rounds keep a recovering store from flapping
        between evacuating and re-acquiring."""
        done = 0
        self.evacuation_rounds += 1
        for rid in self.leader_region_ids():
            if done >= max(1, self.opts.evacuation_rate):
                break
            if self._evac_cooldown.get(rid, 0) > self._evac_round:
                continue
            engine = self._regions.get(rid)
            if engine is None or not engine.is_leader():
                continue
            target = self._pick_evacuation_target(engine)
            if target is None:
                continue
            # cooldown on ATTEMPT, not success: a transfer that bounces
            # (EBUSY mid-conf-change) must not be hammered every round
            self._evac_cooldown[rid] = (
                self._evac_round + max(1, self.opts.evacuation_cooldown_rounds))
            st = await engine.transfer_leadership_to(target)
            if st.is_ok():
                done += 1
                self.evacuations += 1
                RECORDER.record("evacuation", engine.group_id,
                                node=str(self.server_id),
                                target=str(target),
                                cause=self.health.cause)
                LOG.warning("gray-failure evacuation: region %d leadership "
                            "-> %s (store %s is SICK: %s)", rid, target,
                            self.server_id, self.health.cause)
        return done

    def _pick_evacuation_target(self, engine) -> Optional[PeerId]:
        """Healthiest caught-up voter: witness-aware (never a target),
        priority-aware (higher priority preferred), per-peer health
        scores first (a SICK peer is never a target — evacuating onto
        another gray store helps nobody), caught-up-ness required (the
        transfer protocol would stall on a lagging target)."""
        from tpuraft.util.health import DEGRADED, HEALTHY, SICK

        node = engine.node
        if node is None or node.state.value != "leader" \
                or node._conf_ctx is not None:
            return None
        conf = node.conf_entry.conf
        if not node.conf_entry.old_conf.is_empty():
            return None  # mid-joint: let the change finish first
        witnesses = set(conf.witnesses)
        committed = node.ballot_box.last_committed_index
        rank = {HEALTHY: 0, DEGRADED: 1, SICK: 2}
        best = None
        for p in conf.peers:
            if p == node.server_id or p in witnesses:
                continue
            r = node.replicators.get(p)
            if r is None or not r._matched or r.match_index < committed:
                continue
            score = self.health.peer_score(p.endpoint)
            if score == SICK:
                continue
            key = (rank[score], -p.priority, -r.match_index)
            if best is None or key < best[0]:
                best = (key, p)
        return best[1] if best else None

    def should_shed(self) -> tuple[bool, int]:
        """Serving-plane degradation gate (kv_service.handle_batch):
        once this store is SICK and the propose/apply pipe already
        holds ``shed_backlog_items``, new batch items bounce with
        EBUSY + retry-after instead of queueing behind the dying disk.
        Returns (shed?, retry_after_ms)."""
        from tpuraft.util.health import SICK

        if (self.health is None or self.opts.shed_backlog_items <= 0
                or self.health.score() != SICK):
            return False, 0
        if self.kv_processor.inflight_items < self.opts.shed_backlog_items:
            return False, 0
        return True, self.opts.shed_retry_after_ms

    # -- live metrics exposition ---------------------------------------------

    def metrics_counters(self) -> tuple[dict, dict]:
        """(counters, gauges) of everything this store knows: serving
        plane, PD reporting, hub/lease plane, read plane, health, trace
        plane.  Plain int/float reads only — safe from the exposition
        thread (best-effort consistency; no locks taken beyond the
        recorder's own)."""
        kp = self.kv_processor
        counters: dict = {
            "kv_batch_rpcs": kp.batch_rpcs,
            "kv_batch_items": kp.batch_items,
            "kv_batch_regions": kp.batch_regions,
            "kv_single_rpcs": kp.single_rpcs,
            "kv_shed_items": kp.shed_items,
            "kv_read_fences": kp.read_fences,
            "kv_fenced_reads": kp.fenced_reads,
            "pd_batches_sent": self.pd_batches_sent,
            "pd_deltas_sent": self.pd_deltas_sent,
            "pd_full_syncs": self.pd_full_syncs,
            "pd_hb_failures": self.pd_hb_failures,
            "pd_heat_rows_sent": self.pd_heat_rows_sent,
            "evacuations": self.evacuations,
            "evacuation_rounds": self.evacuation_rounds,
            "disk_reclaims": self.disk_reclaims,
            "disk_reclaim_rounds": self.disk_reclaim_rounds,
            "merges_led": self.merges_led,
            "regions_retired": self.regions_retired,
            "regions_absorbed": self.regions_absorbed,
            "moves_applied": self.moves_applied,
            "kv_disk_shed_items": self.disk_shed_items,
            "metrics_renders": self.metrics_renders,
            "metrics_cache_hits": self.metrics_cache_hits,
        }
        if self.heat is not None:
            counters.update(self.heat.counters())
        # per-region O(regions) aggregation (the pass metrics_text's
        # TTL cache bounds): apply/propose plane totals across every
        # hosted region — entries-per-batch amortization, live
        apply_batches = applied_entries = eager_acked = 0
        propose_drains = proposed_ops = lane_batches = 0
        for eng in list(self._regions.values()):
            node = eng.node
            if node is not None and node.fsm_caller is not None:
                apply_batches += node.fsm_caller.apply_batches
                applied_entries += node.fsm_caller.applied_entries
                eager_acked += node.fsm_caller.eager_acked
                lane_batches += node.fsm_caller.lane_batches
            if eng.raft_store is not None:
                propose_drains += eng.raft_store.propose_drains
                proposed_ops += eng.raft_store.proposed_ops
        counters.update({
            "fsm_apply_batches": apply_batches,
            "fsm_applied_entries": applied_entries,
            "fsm_eager_acked": eager_acked,
            "fsm_lane_batches": lane_batches,
            "propose_drains": propose_drains,
            "proposed_ops": proposed_ops,
        })
        if self.apply_lane is not None:
            counters["lane_jobs"] = self.apply_lane.jobs
        if self.read_batcher is not None:
            counters.update(self.read_batcher.counters())
        if self.append_batcher is not None:
            counters.update(self.append_batcher.counters())
        counters.update(self.node_manager.heartbeat_hub.counters())
        counters.update(TRACER.counters())
        counters.update(RECORDER.counters())
        # non-monotonic trace/recorder series render as gauges — a
        # Prometheus rate() over a value that can DROP (ring occupancy,
        # the enabled toggle, a two-way EMA) reads as counter resets
        trace_gauges = {**TRACER.gauges(), **RECORDER.gauges()}
        # read-plane + node counters aggregated across region groups
        agg: dict = {}
        for engine in list(self._regions.values()):
            node = engine.node
            if node is None:
                continue
            for k, v in node.read_only_service.counters().items():
                agg[k] = agg.get(k, 0) + v
            for k, v in node.metrics.counters_snapshot().items():
                agg[f"node_{k}"] = agg.get(f"node_{k}", 0) + v
        counters.update(agg)
        gauges: dict = {
            "regions": len(self._regions),
            "leader_regions": len(self._leader_regions),
            "kv_inflight_items": kp.inflight_items,
            "draining": int(self.draining),
            **trace_gauges,
        }
        if self.apply_lane is not None:
            gauges["lane_depth"] = self.apply_lane.depth()
        if self.health is not None:
            gauges.update(self.health.counters())
        if self.disk_budget is not None:
            gauges.update(self.disk_budget.counters())
        # clock plane rides the unconditional exposition path (like
        # health/disk above) — admin.py clocks must see the sentinel
        # even on stores that never enabled the opt-in KV registry
        gauges.update(self.clock_sentinel.gauges())
        counters.update({
            "clock_skew_samples": self.clock_sentinel.samples,
            "clock_anomalies": self.clock_sentinel.anomalies,
        })
        if self.heat is not None:
            gauges.update(self.heat.gauges())
        if self.multi_raft_engine is not None:
            # tick-plane occupancy lanes ([G] vectorized reductions —
            # no per-group Python) + tick counters
            eng = self.multi_raft_engine
            counters["engine_ticks"] = eng.ticks
            counters["engine_commit_advances"] = eng.commit_advances
            counters["engine_eager_commits"] = eng.eager_commits
            gauges.update({f"engine_{k}": v
                           for k, v in eng.lane_stats().items()})
        return counters, gauges

    def _render_metrics_text(self) -> str:
        """Uncached Prometheus render of :meth:`metrics_counters` plus
        the store registry's histograms (when KV metrics are on) and
        the engine tick-plane histograms (when engine-backed)."""
        counters, gauges = self.metrics_counters()
        hists: dict = {}
        if self.metrics.enabled:
            snap = self.metrics.snapshot()
            counters.update({f"reg_{k}": v
                             for k, v in snap["counters"].items()})
            gauges.update({f"reg_{k}": v
                           for k, v in snap["gauges"].items()})
            hists = snap["histograms"]
        if self.multi_raft_engine is not None:
            hists.update(self.multi_raft_engine.tick_histograms())
        return prometheus_text(counters, gauges, hists,
                               labels={"store": str(self.server_id)})

    def metrics_text(self) -> str:
        """Cached Prometheus text exposition.

        The per-region aggregation in :meth:`metrics_counters` is
        O(regions); at 1024 regions a tight scrape loop re-rendering
        per GET burns the serving thread.  Renders within
        ``metrics_cache_ttl_ms`` serve the cached body (stale-ok), and
        every response carries ``tpuraft_metrics_age_seconds`` — the
        staleness is visible and bounded by the TTL."""
        ttl = max(0.0, self.opts.metrics_cache_ttl_ms / 1000.0)
        with self._metrics_cache_lock:
            # graftcheck: allow(raw-clock) — scrape-cache TTL is against
            # the scraper's real cadence, not the store's time plane
            now = time.monotonic()
            body, t = self._metrics_cache
            if body is None or now - t >= ttl:
                body = self._render_metrics_text()
                t = now
                self._metrics_cache = (body, t)
                self.metrics_renders += 1
            else:
                self.metrics_cache_hits += 1
            age = now - t
        return body + prometheus_text(
            gauges={"metrics_age_seconds": round(age, 4)},
            labels={"store": str(self.server_id)})

    async def _handle_describe_metrics(self, req):
        """``cli_describe_metrics`` admin RPC: the wire-borne scrape
        (examples/admin.py metrics) — same text the HTTP listener
        serves, without needing a second listener or signals."""
        from tpuraft.rpc.cli_messages import DescribeMetricsResponse

        return DescribeMetricsResponse(text=self.metrics_text())

    def _start_metrics_http(self) -> None:
        """Optional stdlib HTTP listener: GET /metrics on its own
        daemon thread (util/metrics_http — shared with the PD's
        listener).  Port 0 binds ephemerally (tests read
        ``metrics_http_port``)."""
        from tpuraft.util.metrics_http import MetricsHttpServer

        self._metrics_httpd = MetricsHttpServer(
            self.opts.metrics_host, self.opts.metrics_port,
            self.metrics_text, name=f"metrics-http-{self.server_id}")
        self.metrics_http_port = self._metrics_httpd.port

    # -- PD heartbeats -------------------------------------------------------

    async def _heartbeat_loop(self) -> None:
        """Reference: ``rhea:StoreEngine``'s Store/Region heartbeat
        senders — now DELTA-BATCHED: one ``pd_store_heartbeat_batch``
        RPC per interval carrying only changed-region rows (idle PD
        traffic is O(stores), not O(regions)), executing returned
        Instructions.

        Hardening: every store used to beat on the same 1000 ms phase
        and drop failed rounds at LOG.debug — now each store starts at
        a seeded random phase with per-round jitter (the PD never sees
        the whole fleet in one burst), and consecutive failures back
        off exponentially (bounded by ``pd_backoff_max_ms``) with a
        WARNING once the PD looks actually down."""
        import random

        interval = self.opts.heartbeat_interval_ms / 1000.0
        rng = random.Random(zlib.crc32(str(self.server_id).encode())
                            ^ 0x5bd1e995)
        # per-store phase offset: spread the fleet over the interval
        await asyncio.sleep(rng.random() * interval)
        fails = 0
        while self._started:
            try:
                await self._heartbeat_once()
                fails = 0
            except asyncio.CancelledError:
                return
            except Exception:  # noqa: BLE001 — PD may be down; keep trying
                fails += 1
                self.pd_hb_failures += 1
                log = LOG.warning if fails in (3, 10) or fails % 60 == 0 \
                    else LOG.debug
                log("pd heartbeat failed (%d consecutive)", fails,
                    exc_info=fails == 3)
            backoff = interval * (2 ** min(fails, 6)) if fails else interval
            backoff = min(backoff, self.opts.pd_backoff_max_ms / 1000.0)
            # ±10% per-round jitter: phase-locked fleets drift apart
            await asyncio.sleep(backoff * (0.9 + 0.2 * rng.random()))

    async def _approx_keys(self, start: bytes, end: bytes) -> int:
        """Range key-count probe — through the apply lane when one owns
        the store (a loop-side index rebuild would race lane applies)."""
        if self.apply_lane is not None:
            return await self.apply_lane.submit(
                self.raw_store.approximate_keys_in_range, start, end)
        return self.raw_store.approximate_keys_in_range(start, end)

    def _pd_fingerprint(self, region: Region) -> tuple:
        return (region.epoch.conf_ver, region.epoch.version,
                region.start_key, region.end_key, tuple(region.peers))

    async def _heartbeat_once(self) -> None:
        from tpuraft.rheakv.pd_messages import Instruction

        full = self._pd_need_full
        deltas: list[tuple[Region, str, int]] = []
        fps: dict[int, tuple] = {}
        me = str(self.server_id)
        for rid in self.leader_region_ids():
            engine = self._regions.get(rid)
            if engine is None or not engine.is_leader():
                continue
            region = engine.region
            keys = await self._approx_keys(region.start_key, region.end_key)
            fp = self._pd_fingerprint(region)
            last = self._pd_reported.get(rid)
            # a keys move under ~12.5% (and < 64 abs) is noise, not a
            # delta — the PD's split threshold only needs coarse counts
            changed = (full or last is None or last[0] != fp
                       or rid in self._pd_dirty
                       or abs(keys - last[1]) * 8 >= max(last[1], 64))
            if changed:
                deltas.append((region.copy(), me, keys))
                fps[rid] = (fp, keys)
        # batch reporting: region rows ride as deltas, so build the
        # bare store identity directly — store_meta() would deep-copy
        # every region just for us to throw the list away each interval
        meta = StoreMeta(id=zlib.crc32(str(self.server_id).encode()),
                         endpoint=self.server_id.endpoint, regions=[],
                         zone=self.opts.zone)
        # health rides the heartbeat as a trailing wire field: the PD
        # stops placing leaders onto SICK stores and drains them (a
        # pre-health PD client override is probed at construction and
        # reported to without the kwarg — see _pd_health_kwarg)
        health = self.health.score() if self.health is not None else ""
        heat_rows = self._heat_report(full)
        kwargs: dict = {}
        if self._pd_health_kwarg:
            kwargs["health"] = health
        if self._pd_heat_kwarg:
            kwargs["heat"] = [row for row, _score in heat_rows]
            kwargs["occupancy"] = self.tick_occupancy()
        instructions, need_full = \
            await self.pd_client.store_heartbeat_batch(
                meta, deltas, full=full, **kwargs)
        # only now (RPC succeeded) do the fingerprints count as reported
        self.pd_batches_sent += 1
        self.pd_deltas_sent += len(deltas)
        if self._pd_heat_kwarg:
            self.pd_heat_rows_sent += len(heat_rows)
            # graftcheck: allow(raw-clock) — keepalive bookkeeping vs
            # the PD's REAL heat_stale_s expiry, not store time
            now = time.monotonic()
            self._pd_heat_reported.update(
                {row[0]: (score, now) for row, score in heat_rows})
        if full:
            self.pd_full_syncs += 1
        self._pd_reported.update(fps)
        self._pd_dirty.difference_update(fps)
        self._pd_need_full = bool(need_full)
        for ins in instructions:
            engine = self._regions.get(ins.region_id)
            if engine is None or not engine.is_leader():
                if ins.kind == Instruction.KIND_MERGE and \
                        self._retired_into.get(ins.region_id) == \
                        ins.new_region_id:
                    # re-issued merge for a region this store already
                    # retired: the completion reports were all lost
                    # (PD down/partitioned across the merge) — answer
                    # with a fresh one so the PD finalizes the pending
                    # pair instead of re-issuing forever
                    try:
                        await self.pd_client.report_merge(
                            ins.region_id, ins.new_region_id)
                    except Exception:  # noqa: BLE001 — next round
                        LOG.debug("retired-merge report %d -> %d "
                                  "failed; will answer the next "
                                  "re-issue", ins.region_id,
                                  ins.new_region_id, exc_info=True)
                continue
            if ins.kind == Instruction.KIND_SPLIT:
                st = await self.apply_split(ins.region_id,
                                            ins.new_region_id)
                if not st.is_ok():
                    LOG.info("pd-ordered split of region %d failed: %s",
                             ins.region_id, st)
                    # the PD only re-issues on a fresh report: force one
                    self._pd_dirty.add(ins.region_id)
            elif ins.kind == Instruction.KIND_TRANSFER_LEADER \
                    and ins.target_peer:
                await engine.transfer_leadership_to(
                    PeerId.parse(ins.target_peer))
            elif ins.kind == Instruction.KIND_MERGE:
                st = await self.apply_merge(ins.region_id,
                                            ins.new_region_id,
                                            ins.target_peer)
                if not st.is_ok():
                    # deferred (mid-conf-change) or bounced (target
                    # leader moved): a fresh report makes the PD
                    # re-issue from its replicated pending_merges map
                    LOG.info("pd-ordered merge of region %d into %d "
                             "deferred: %s", ins.region_id,
                             ins.new_region_id, st)
                    self._pd_dirty.add(ins.region_id)
            elif ins.kind == Instruction.KIND_MOVE and ins.target_peer:
                st = await self.apply_move(ins.region_id, ins.target_peer,
                                           ins.src_peer)
                if not st.is_ok():
                    LOG.info("pd-ordered move of region %d -> %s failed: "
                             "%s", ins.region_id, ins.target_peer, st)
                    self._pd_dirty.add(ins.region_id)

    def _heat_report(self, full: bool) -> list[tuple[tuple, float]]:
        """Fold the heat window and pick the led regions whose heat
        moved past the noise gate (util/heat.heat_changed), whose
        standing rate is due its keepalive refresh (``heat_refresh_s``
        — the PD expires silent rates after heat_stale_s, so steady
        heat must re-report, just slowly), or every led region with
        any heat when ``full`` (PD resync).  Returns
        [((region_id, w, r, bi, bo), score), ...]; the scores land in
        ``_pd_heat_reported`` only after the RPC succeeds."""
        if self.heat is None:
            return []
        from tpuraft.util.heat import heat_changed

        self.heat.fold()
        # graftcheck: allow(raw-clock) — keepalive refresh races the
        # PD's REAL heat_stale_s expiry window
        now = time.monotonic()
        rows: list[tuple[tuple, float]] = []
        for rid in self.leader_region_ids():
            h = self.heat.heat(rid)
            score = h.score
            last, last_t = self._pd_heat_reported.get(rid, (0.0, 0.0))
            refresh = (score >= 0.5 and last_t > 0.0
                       and now - last_t >= self.opts.heat_refresh_s)
            if full and (score or last) or refresh \
                    or heat_changed(score, last):
                rows.append(((rid, h.writes_s, h.reads_s,
                              h.bytes_in_s, h.bytes_out_s), score))
        return rows

    def tick_occupancy(self) -> tuple[int, int]:
        """(replicas_hosted, replicas_quiescent) for the PD heartbeat's
        hibernation fraction — one vectorized reduce over the engine's
        [G] rows for engine-backed stores; (regions, 0) in timer mode
        (host timers have no quiescence)."""
        e = self.multi_raft_engine
        if e is None:
            return len(self._regions), 0
        return (int(e.has_ctrl.sum()),
                int((e.quiescent & e.has_ctrl).sum()))

    async def _start_region(self, region: Region) -> RegionEngine:
        engine = RegionEngine(region, self)
        await engine.start()
        self._regions[region.id] = engine
        return engine

    # -- region access -------------------------------------------------------

    def get_region_engine(self, region_id: int) -> Optional[RegionEngine]:
        return self._regions.get(region_id)

    def list_regions(self) -> list[Region]:
        return [e.region for e in self._regions.values()]

    def store_meta(self) -> StoreMeta:
        # stable across restarts/processes (builtin hash() is seeded)
        sid = zlib.crc32(str(self.server_id).encode())
        return StoreMeta(id=sid,
                         endpoint=self.server_id.endpoint,
                         regions=[r.copy() for r in self.list_regions()],
                         zone=self.opts.zone)

    # -- node options for a region's raft group ------------------------------

    def make_node_options(self, region: Region, fsm) -> NodeOptions:
        conf = Configuration.parse(",".join(region.peers))
        opts = NodeOptions(
            election_timeout_ms=self.opts.election_timeout_ms,
            initial_conf=conf,
            fsm=fsm,
        )
        # '/witness'-flagged own peer: this store hosts the region as a
        # WITNESS — metadata-only journal, null FSM, never campaigns
        opts.witness = conf.is_witness(self.server_id)
        if conf.witnesses and self.multi_raft_engine is not None:
            # the device plane is witness-aware since ISSUE 19 (the tick
            # carries a witness_mask and clamps the commit reduce to the
            # best DATA-replica match, mirroring ballot_box.commit_point)
            # — but only on a tick module that actually has those lanes.
            # A stale ops plane would count witness rows as plain data
            # matches on device, silently dropping the third safety
            # layer, so refuse LOUDLY rather than run witness regions
            # with weaker guarantees than documented.
            from tpuraft.ops.tick import witness_lanes_available
            if not witness_lanes_available():
                raise ValueError(
                    f"region {region.id}: witness members "
                    f"{[str(p) for p in conf.witnesses]} on an "
                    f"engine-backed store, but this device tick plane "
                    f"predates the witness commit clamp (no "
                    f"witness_mask/fence_ok lanes) — upgrade tpuraft.ops "
                    f"or host witness regions on timer-mode stores (no "
                    f"MultiRaftEngine)")
        opts.raft_options.read_only_option = self.opts.read_only_option
        opts.raft_options.quiesce_after_rounds = \
            self.opts.quiesce_after_rounds
        # time discipline: every region node of this store runs on the
        # ONE store clock and consults the ONE skew sentinel before
        # trusting its leader lease (ISSUE 18)
        opts.clock = self.opts.clock
        opts.clock_sentinel = self.clock_sentinel
        opts.raft_options.clock_drift_bound = self.opts.clock_drift_bound
        # gray-failure plane: every region node of this store feeds (and
        # consults) the ONE store-level tracker — disk probe from its
        # LogManager, apply depth from its FSMCaller, election gate from
        # its _allow_launch_election
        opts.health = self.health
        # disk-pressure plane: every region node feeds the ONE
        # store-level capacity tracker (LogManager append bytes,
        # snapshot executor commit/prune deltas, ENOSPC observations)
        opts.disk_budget = self.disk_budget
        # apply worker lane: every region's FSMCaller submits committed
        # DATA runs to the ONE store-wide lane (total store order
        # preserved by the lane's FIFO; witness regions have a null FSM
        # with no apply_sync and stay on the loop)
        opts.apply_lane = self.apply_lane
        if self.opts.data_path:
            store_base = (f"{self.opts.data_path}/"
                          f"{self.server_id.ip}_{self.server_id.port}")
            base = f"{store_base}/r{region.id}"
            if self.opts.log_scheme == "multilog":
                # one shared journal engine for every region of this
                # store: cross-region group-commit fsync — and the SAME
                # treatment for {term, votedFor}: per-region file://
                # meta would pay one fsync per region per election,
                # which is the serial-fsync herd the shared meta
                # journal exists to absorb (storage/meta_multilog.py)
                opts.log_uri = f"multilog://{store_base}/mlog#r{region.id}"
                opts.raft_meta_uri = \
                    f"multimeta://{store_base}/meta#r{region.id}"
                if self._meta_journal is None:
                    # store-lifetime ref: per-region opens (migration
                    # below, node init) become refcount bumps instead
                    # of journal reopen+fsync cycles on the loop
                    from tpuraft.storage.meta_multilog import get_journal

                    self._meta_journal = get_journal(f"{store_base}/meta")
                self._migrate_legacy_meta(store_base, base, region.id)
            else:
                opts.log_uri = f"{self.opts.log_scheme}://{base}/log"
                if self.opts.log_segment_max_bytes > 0:
                    opts.log_uri += \
                        f"?seg={self.opts.log_segment_max_bytes}"
                opts.raft_meta_uri = f"file://{base}/meta"
            opts.snapshot_uri = f"file://{base}/snapshot"
        else:
            opts.log_uri = "memory://"
            opts.raft_meta_uri = "memory://"
        opts.snapshot = SnapshotOptions(
            interval_secs=self.opts.snapshot_interval_secs)
        return opts

    @staticmethod
    def _migrate_legacy_meta(store_base: str, base: str, rid: int) -> None:
        """One-time upgrade: multilog-scheme stores used to keep
        per-region ``file://`` meta; seed the shared meta journal from
        it so a restarted store can never fall back to term 0 and vote
        twice in a term it already voted in.  The legacy file is
        renamed after seeding (the term guard makes a replayed
        migration a no-op regardless)."""
        legacy = os.path.join(base, "meta", "raft_meta")
        if not os.path.exists(legacy):
            return
        from tpuraft.storage.meta_multilog import MultiRaftMetaStorage
        from tpuraft.storage.meta_storage import RaftMetaStorage

        old = RaftMetaStorage(os.path.join(base, "meta"))
        old.init()
        new = MultiRaftMetaStorage(f"{store_base}/meta", f"r{rid}")
        new.init()
        try:
            if old.term > new.term:
                new.set_term_and_voted_for(old.term, old.voted_for)
        finally:
            new.shutdown()
        os.replace(legacy, legacy + ".migrated")

    def ballot_box_factory(self):
        if self.multi_raft_engine is None:
            return None
        return self.multi_raft_engine.ballot_box_factory()

    # -- leadership bookkeeping (PD heartbeat fodder) ------------------------

    def on_region_leader_start(self, region_id: int, term: int) -> None:
        self._leader_regions.add(region_id)

    def on_region_leader_stop(self, region_id: int) -> None:
        self._leader_regions.discard(region_id)

    def leader_region_ids(self) -> list[int]:
        return sorted(self._leader_regions)

    # -- split ---------------------------------------------------------------

    async def apply_split(self, region_id: int, new_region_id: int,
                          split_key: Optional[bytes] = None) -> Status:
        """Leader-side entry: replicate a RANGE_SPLIT through the region's
        raft group (reference: ``rhea:StoreEngine#applySplit``)."""
        engine = self._regions.get(region_id)
        if engine is None:
            return Status.error(RaftError.ENOENT, f"region {region_id} absent")
        if new_region_id in self._regions:
            return Status.error(RaftError.EEXISTS,
                                f"region {new_region_id} exists")
        region = engine.region
        if split_key is None:
            n = await self._approx_keys(region.start_key, region.end_key)
            if n < self.opts.least_keys_on_split:
                return Status.error(
                    RaftError.EBUSY,
                    f"region {region_id} too small to split ({n} keys)")
            if self.apply_lane is not None:
                split_key = await self.apply_lane.submit(
                    self.raw_store.jump_over,
                    region.start_key, region.end_key, n // 2)
            else:
                split_key = self.raw_store.jump_over(
                    region.start_key, region.end_key, n // 2)
        if split_key is None or not region.contains_key(split_key):
            return Status.error(RaftError.EINVAL,
                                f"bad split key {split_key!r}")
        try:
            await engine.raft_store.range_split(new_region_id, split_key)
        except Exception as e:  # noqa: BLE001
            return Status.error(RaftError.EINTERNAL, f"split failed: {e}")
        return Status.OK()

    def do_split(self, region_id: int, new_region_id: int,
                 split_key: bytes) -> None:
        """FSM-side application, invoked deterministically on EVERY replica
        when the RANGE_SPLIT entry commits.  Metadata mutates synchronously;
        the new region's raft node boots asynchronously."""
        engine = self._regions.get(region_id)
        if engine is None or new_region_id in self._regions \
                or new_region_id in self._pending_splits:
            return
        parent = engine.region
        if not parent.contains_key(split_key):
            return
        new_region = Region(
            id=new_region_id,
            start_key=split_key,
            end_key=parent.end_key,
            peers=list(parent.peers),
        )
        new_region.epoch.version = parent.epoch.version + 1
        parent.end_key = split_key
        parent.epoch.version += 1
        if self.heat is not None:
            # the parent's standing rates describe the PRE-split
            # keyspace — half that load now lands on the child.  Reset
            # and let both halves re-accumulate their true rates (the
            # PD-side mirror: mark_split_issued resets keys)
            self.heat.drop(region_id)
        self._pending_splits.add(new_region_id)

        async def boot():
            try:
                await self._start_region(new_region)
                if self.pd_client is not None:
                    await self.pd_client.report_split(parent, new_region)
            except Exception:  # noqa: BLE001
                LOG.exception("booting split region %d failed", new_region_id)
            finally:
                self._pending_splits.discard(new_region_id)

        asyncio.ensure_future(boot())

    # -- merge / move (the region lifecycle plane) ---------------------------

    async def apply_merge(self, region_id: int, target_region_id: int,
                          target_peer: str) -> Status:
        """Leader-side entry for a PD-ordered cold merge: replicate the
        seal barrier through the SOURCE group, hand the sealed keyspace
        to the TARGET group's leader (kv_merge_absorb), then retire the
        source group with a MERGE_COMMIT entry.

        Every step is retry-safe: the PD's replicated pending-merge map
        re-issues the instruction until the merge completes, and a
        resumed attempt skips the already-applied seal (``sealed_into``
        names the target) while absorb/extend apply idempotently."""
        engine = self._regions.get(region_id)
        if engine is None:
            return Status.error(RaftError.ENOENT, f"region {region_id} absent")
        node = engine.node
        if node is None or not engine.is_leader():
            return Status.error(RaftError.EPERM,
                                f"not leader of region {region_id}")
        already = getattr(engine.fsm, "sealed_into", -1)
        if already >= 0 and already != target_region_id:
            return Status.error(
                RaftError.EINVAL,
                f"region {region_id} already sealed into {already}")
        if already < 0 and (node._conf_ctx is not None
                            or not node.conf_entry.old_conf.is_empty()):
            # DEFER, don't wedge: a seal proposed while a joint conf
            # change is in flight would interleave two multi-step
            # protocols on one log — the PD re-issues after the change
            # completes (satellite 3's merge-vs-conf-change test)
            return Status.error(
                RaftError.EBUSY,
                f"region {region_id} mid-conf-change (merge deferred)")
        region = engine.region
        # leader-local barrier half: no NEW write is admitted once the
        # seal's log position is decided; the FSM's replicated
        # sealed_into takes over when the entry applies.  If the seal
        # never applies (propose failed, leadership lost mid-attempt)
        # the flag is cleared in the finally below — otherwise a
        # regained leadership would bounce every write ERR_STORE_BUSY
        # on a region that was never actually sealed.
        engine.sealing = True
        try:
            if already < 0:
                await engine.raft_store.merge_seal(target_region_id)
            # capture the range AFTER the seal applies: a split racing
            # the merge may have shrunk this region up to the seal's
            # log position (later splits bounce off the sealed guard) —
            # serializing the pre-split range would hand the target
            # keys a sibling region now owns
            src_start, src_end = region.start_key, region.end_key
            # the blob ALWAYS carries the data: target replicas on
            # stores that never hosted the source need it (replicas
            # sharing this raw store re-apply it as an idempotent
            # overwrite)
            if self.apply_lane is not None:
                blob = await self.apply_lane.submit(
                    self.raw_store.serialize_range, src_start, src_end)
            else:
                blob = self.raw_store.serialize_range(src_start, src_end)
            st = await self._absorb_into_target(
                target_region_id, target_peer, region_id,
                src_start, src_end, blob)
            if not st.is_ok():
                return st
            await engine.raft_store.merge_commit(target_region_id)
        except Exception as e:  # noqa: BLE001
            return Status.error(RaftError.EINTERNAL, f"merge failed: {e}")
        finally:
            if getattr(engine.fsm, "sealed_into", -1) < 0:
                engine.sealing = False
        self.merges_led += 1
        RECORDER.record("region_merge", engine.group_id,
                        node=str(self.server_id), into=target_region_id)
        LOG.info("region %d merged into %d (store %s)", region_id,
                 target_region_id, self.server_id)
        if self.pd_client is not None:
            try:
                await self.pd_client.report_merge(region_id,
                                                  target_region_id)
            except Exception:  # noqa: BLE001 — every replica's
                # MERGE_COMMIT apply (do_retire) also reports, and a
                # re-issued KIND_MERGE for the retired region is
                # answered with a fresh report — the PD hears about
                # the completion through one of those
                LOG.warning("report_merge(%d -> %d) failed; replica "
                            "retirement reports will finalize",
                            region_id, target_region_id, exc_info=True)
        return Status.OK()

    async def _absorb_into_target(self, target_region_id: int,
                                  target_peer: str, src_id: int,
                                  src_start: bytes, src_end: bytes,
                                  blob: bytes) -> Status:
        """Hand the sealed source range to the target group's leader —
        directly when this store leads the target, over the store-to-
        store ``kv_merge_absorb`` RPC otherwise."""
        from tpuraft.rheakv.kv_service import MergeAbsorbRequest

        target_engine = self._regions.get(target_region_id)
        if target_engine is not None and target_engine.is_leader():
            try:
                await target_engine.raft_store.merge_absorb(
                    src_id, src_start, src_end, blob)
                return Status.OK()
            except Exception as e:  # noqa: BLE001
                return Status.error(RaftError.EINTERNAL,
                                    f"local absorb: {e}")
        if not target_peer:
            return Status.error(RaftError.EINVAL,
                                "no target peer for absorb")
        try:
            resp = await self.transport.call(
                PeerId.parse(target_peer).endpoint, "kv_merge_absorb",
                MergeAbsorbRequest(
                    target_region_id=target_region_id,
                    source_region_id=src_id,
                    source_start=src_start, source_end=src_end,
                    data_blob=blob),
                timeout_ms=max(5000, self.opts.election_timeout_ms * 3))
        except Exception as e:  # noqa: BLE001
            return Status.error(RaftError.EINTERNAL, f"absorb rpc: {e}")
        if resp.code != 0:
            # EPERM = stale target leader hint; the PD's next issue
            # carries the fresh leader from its cluster view
            return Status.error(RaftError.EBUSY,
                                f"target absorb bounced: {resp.code} "
                                f"{resp.msg}")
        return Status.OK()

    async def apply_move(self, region_id: int, target_peer: str,
                         src_peer: str) -> Status:
        """PD-ordered replica move: add the destination as a LEARNER
        (it catches up without voting), then one joint-consensus change
        promotes it and drops the source replica.  A move whose source
        is this leader itself hands leadership off first and defers —
        the joint change needs a leader that stays in the conf."""
        engine = self._regions.get(region_id)
        if engine is None:
            return Status.error(RaftError.ENOENT, f"region {region_id} absent")
        node = engine.node
        if node is None or not engine.is_leader():
            return Status.error(RaftError.EPERM,
                                f"not leader of region {region_id}")
        if not src_peer:
            return Status.error(RaftError.EINVAL, "move needs a source peer")
        dst = PeerId.parse(target_peer)
        src = PeerId.parse(src_peer)
        conf = node.conf_entry.conf
        if not conf.contains(src):
            # retried move whose removal already committed
            return Status.OK() if conf.contains(dst) else Status.error(
                RaftError.EINVAL, f"{src_peer} not in region {region_id}")
        if src == node.server_id:
            for p in conf.peers:
                if p != src and not conf.is_witness(p):
                    await engine.transfer_leadership_to(p)
                    break
            return Status.error(
                RaftError.EBUSY,
                f"region {region_id} leader is the move source; "
                f"transferring leadership first")
        if not conf.contains(dst) and dst not in conf.learners:
            st = await node.add_learners([dst])
            if not st.is_ok():
                return st
            conf = node.conf_entry.conf
        new_conf = conf.copy()
        if dst not in new_conf.peers:
            new_conf.peers.append(dst)
        new_conf.peers = [p for p in new_conf.peers if p != src]
        new_conf.learners = [l for l in new_conf.learners if l != dst]
        st = await node.change_peers(new_conf)
        if st.is_ok():
            self.moves_applied += 1
            self._pd_dirty.add(region_id)
            RECORDER.record("region_move", engine.group_id,
                            node=str(self.server_id), src=src_peer,
                            dst=target_peer)
            LOG.info("region %d replica moved %s -> %s", region_id,
                     src_peer, target_peer)
        return st

    def do_absorb(self, region_id: int, src_id: int, src_start: bytes,
                  src_end: bytes) -> None:
        """Loop-side metadata half of a MERGE_ABSORB apply (invoked on
        EVERY replica of the target group): extend the region over the
        absorbed range, fold lifecycle bookkeeping.  The absorbed data
        itself already landed via ``load_serialized`` in the store-
        owning context."""
        from tpuraft.rheakv.state_machine import extend_region_over

        engine = self._regions.get(region_id)
        if engine is None:
            LOG.warning("absorb for unknown region %d (src %d) dropped",
                        region_id, src_id)
            return
        try:
            extend_region_over(engine.region, src_start, src_end)
        except RuntimeError:
            LOG.exception("region %d cannot absorb [%r, %r)", region_id,
                          src_start, src_end)
            return
        self.regions_absorbed += 1
        if self.heat is not None:
            # the source's standing rates now land on this region —
            # let them re-accumulate under the merged id
            self.heat.drop(src_id)
        self._pd_dirty.add(region_id)

    def do_retire(self, region_id: int, target_id: int) -> None:
        """Loop-side MERGE_COMMIT apply (every source replica): drop the
        merged-away region from the serving table and shut its raft
        group down asynchronously.  The absorbed keyspace is NEVER
        wiped — on a shared per-store raw store the target region (or
        its replica on another store) serves those rows now."""
        self._retired_into[region_id] = target_id
        engine = self._regions.pop(region_id, None)
        if engine is None:
            return  # idempotent: replayed commit entry after a restart
        self._leader_regions.discard(region_id)
        self._pd_reported.pop(region_id, None)
        self._pd_dirty.discard(region_id)
        self._pd_heat_reported.pop(region_id, None)
        self._evac_cooldown.pop(region_id, None)
        self._reclaim_cooldown.pop(region_id, None)
        if self.heat is not None:
            self.heat.drop(region_id)
        self.regions_retired += 1
        RECORDER.record("region_retired", engine.group_id,
                        node=str(self.server_id), into=target_id)
        LOG.info("region %d retired into %d (store %s)", region_id,
                 target_id, self.server_id)
        if self.pd_client is not None:
            # replica-side completion report: the source LEADER's
            # apply_merge report is lost if it crashes between the
            # MERGE_COMMIT committing and the RPC landing — and a fully
            # retired group stops heartbeating, so without this the
            # PD's pending pair would re-issue into the void forever.
            # Every replica reports at its own commit apply (the PD's
            # _CMD_MERGE is idempotent and counts once), with a few
            # paced retries to ride out a PD failover.
            async def _report():
                for delay in (0.0, 0.5, 2.0, 8.0):
                    try:
                        await asyncio.sleep(delay)
                        await self.pd_client.report_merge(region_id,
                                                          target_id)
                        return
                    except Exception:  # noqa: BLE001
                        continue
                LOG.warning(
                    "retirement report %d -> %d never landed; the PD "
                    "will hear it when a re-issued merge instruction "
                    "reaches this store", region_id, target_id)

            asyncio.ensure_future(_report())

        async def _stop():
            # propagation grace: the replica that applied MERGE_COMMIT
            # first is usually the LEADER — shutting its node down at
            # its own apply would strand followers before the advanced
            # commit index reaches them (each successor leader then
            # retires itself the same way until the last replica is
            # alone without a quorum, wedged un-retired forever).  Keep
            # the node voting/appending for a few election timeouts so
            # every replica hears the commit; the region is already out
            # of the serving table either way.
            try:
                await asyncio.sleep(
                    self.opts.election_timeout_ms * 3 / 1000.0)
                await engine.shutdown()
            except Exception:  # noqa: BLE001
                LOG.exception("retiring region %d shutdown failed",
                              region_id)

        asyncio.ensure_future(_stop())

    def on_region_conf_changed(self, region_id: int) -> None:
        """FSM hook: a committed conf entry changed the replica roster
        (move promotion/removal) — force a fresh PD report so the route
        plane and the placement policy see the new peers/conf_ver."""
        self._pd_dirty.add(region_id)
