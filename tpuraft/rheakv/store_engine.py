"""StoreEngine: one KV storage process hosting many region raft groups.

Reference parity: ``rhea:StoreEngine`` (SURVEY.md §3.2) — boots the
shared RPC server + NodeManager, the shared RawKVStore, one RegionEngine
per region, the KV command processor, split handling, and (optionally)
heartbeats to the placement driver.

TPU-native design: when given a :class:`MultiRaftEngine`, every region's
quorum/commit bookkeeping runs on the engine's fused ``[G, P]`` device
tick — thousands of regions advance their commit indexes in one XLA
dispatch per tick instead of per-group Python work (SURVEY.md §3.5
"multi-group data parallelism", the BASELINE.json north star).
"""

from __future__ import annotations

import asyncio
import logging
import os
import zlib
from dataclasses import dataclass, field
from typing import Callable, Optional

from tpuraft.conf import Configuration
from tpuraft.core.cli_service import CliProcessors
from tpuraft.core.node_manager import NodeManager
from tpuraft.entity import PeerId
from tpuraft.errors import RaftError, Status
from tpuraft.options import NodeOptions, ReadOnlyOption, SnapshotOptions
from tpuraft.rheakv.kv_service import KVCommandProcessor
from tpuraft.rheakv.metadata import Region, StoreMeta
from tpuraft.rheakv.raw_store import (
    MemoryRawKVStore,
    MetricsRawKVStore,
    RawKVStore,
)
from tpuraft.util.metrics import MetricRegistry
from tpuraft.rheakv.region_engine import RegionEngine

LOG = logging.getLogger(__name__)


@dataclass
class StoreEngineOptions:
    cluster_name: str = "rheakv"
    server_id: str = ""                  # this store's PeerId string
    initial_regions: list[Region] = field(default_factory=list)
    data_path: str = ""                  # "" = memory storage
    election_timeout_ms: int = 1000
    snapshot_interval_secs: int = 0      # 0 = on-demand only
    raw_store_factory: Callable[[], RawKVStore] = MemoryRawKVStore
    # least keys a region must hold before a split is sensible
    least_keys_on_split: int = 16
    # PD heartbeat cadence (only used when a pd_client is wired)
    heartbeat_interval_ms: int = 1000
    # linearizable read mode for region groups (SAFE: quorum heartbeat
    # round per read batch; LEASE_BASED: trust the leader lease — the
    # reference's ReadOnlyOption, surfaced here like RheaKVStoreOptions)
    read_only_option: ReadOnlyOption = ReadOnlyOption.SAFE
    # wrap the raw store in the op-latency decorator (reference:
    # MetricsRawKVStore, enabled by RheaKVStoreOptions metrics flags)
    enable_kv_metrics: bool = False
    # "file" = one segment dir per region (round-1 layout);
    # "multilog" = ALL regions of this store share ONE C++ journal
    # engine — group-keyed records, one fsync per flush round across
    # regions, O(bytes/segment) fds (the reference's single-RocksDB
    # role; storage/multilog.py).  Only used when data_path is set.
    log_scheme: str = "file"
    # group quiescence (engine-driven regions only): an idle, fully
    # replicated region hibernates after this many consecutive fully-
    # acked beat rounds — see RaftOptions.quiesce_after_rounds.  0 = off.
    quiesce_after_rounds: int = 0
    # cap for the PD-heartbeat failure backoff (bounded exponential:
    # interval x 2^fails, clamped here) — a down PD costs one cheap
    # probe per cap interval, not a hot retry loop
    pd_backoff_max_ms: int = 30000
    # serving-plane apply coalescing: the region FSMs flush consecutive
    # PUT/DELETE(-list) entries as ONE store batch write (one ctypes
    # call + one WAL record per run) instead of one call per op — see
    # KVStoreStateMachine.coalesce_applies
    fsm_coalesce: bool = True
    # kv_command_batch write sub-batches ride ONE KVOp.MULTI log entry
    # per region (one quorum round amortized).  Set False during a
    # rolling upgrade from a pre-batch build: a MULTI entry replicated
    # to a replica whose FSM predates it fails to apply and silently
    # diverges state — per-op entries stay wire/FSM-compatible both ways
    multi_op_entries: bool = True
    # geo deployment: this store's zone (failure-domain) label.  Carried
    # on PD heartbeats so the PD spreads leaders across zones; "" =
    # unlabeled (single-zone legacy deployments)
    zone: str = ""


class StoreEngine:
    def __init__(self, opts: StoreEngineOptions, rpc_server, transport,
                 multi_raft_engine=None, pd_client=None) -> None:
        self.opts = opts
        self.cluster_name = opts.cluster_name
        self.server_id = PeerId.parse(opts.server_id)
        self.rpc_server = rpc_server
        self.transport = transport
        self.node_manager = NodeManager(rpc_server)
        CliProcessors(self.node_manager)
        self.kv_processor = KVCommandProcessor(self)
        self.metrics = MetricRegistry(enabled=opts.enable_kv_metrics)
        raw: RawKVStore = opts.raw_store_factory()
        if opts.enable_kv_metrics:
            raw = MetricsRawKVStore(raw, self.metrics)
        self.raw_store: RawKVStore = raw
        self.multi_raft_engine = multi_raft_engine
        self.pd_client = pd_client
        self._regions: dict[int, RegionEngine] = {}
        self._leader_regions: set[int] = set()
        self._started = False
        self._pending_splits: set[int] = set()
        self._heartbeat_task: Optional[asyncio.Task] = None
        self._meta_journal = None  # store-lifetime ref (multilog scheme)
        # delta-batched PD reporting state: region -> (fingerprint,
        # last-reported approximate_keys); dirty = force-report next
        # round (fresh leadership, failed instruction); need_full =
        # next batch carries EVERY led region (first contact, or the
        # PD answered need_full after its own failover)
        self._pd_reported: dict[int, tuple] = {}
        self._pd_dirty: set[int] = set()
        self._pd_need_full = True
        self.pd_batches_sent = 0     # observability (bench counters)
        self.pd_deltas_sent = 0
        self.pd_full_syncs = 0
        self.pd_hb_failures = 0

    # -- lifecycle -----------------------------------------------------------

    async def start(self) -> None:
        if self.multi_raft_engine is not None:
            await self.multi_raft_engine.start()
        # batched-concurrent region boot: one region at a time serializes
        # every node.init's await points — at region density (rhea:
        # StoreEngine's thousands-of-regions role) that alone dominates
        # store restart time.  Bounded batches keep the task herd small.
        BOOT_BATCH = 128
        regions = list(self.opts.initial_regions)
        for i in range(0, len(regions), BOOT_BATCH):
            # settle the WHOLE batch before failing: a bare gather would
            # abort on the first error while sibling boots keep running
            # detached against a half-torn store
            results = await asyncio.gather(
                *(self._start_region(r) for r in regions[i:i + BOOT_BATCH]),
                return_exceptions=True)
            for res in results:
                if isinstance(res, BaseException):
                    raise res
        self._started = True
        if self.pd_client is not None:
            self._heartbeat_task = asyncio.ensure_future(
                self._heartbeat_loop())
        LOG.info("store engine %s up with %d regions", self.server_id,
                 len(self._regions))

    async def shutdown(self) -> None:
        self._started = False
        if self._heartbeat_task is not None:
            self._heartbeat_task.cancel()
            self._heartbeat_task = None
        for engine in list(self._regions.values()):
            await engine.shutdown()
        self._regions.clear()
        if self.multi_raft_engine is not None:
            await self.multi_raft_engine.shutdown()
        close = getattr(self.raw_store, "close", None)
        if close is not None:
            close()  # native engine: flush + release the WAL fd
        if self._meta_journal is not None:
            from tpuraft.storage.meta_multilog import _release_journal

            _release_journal(self._meta_journal)
            self._meta_journal = None

    # -- PD heartbeats -------------------------------------------------------

    async def _heartbeat_loop(self) -> None:
        """Reference: ``rhea:StoreEngine``'s Store/Region heartbeat
        senders — now DELTA-BATCHED: one ``pd_store_heartbeat_batch``
        RPC per interval carrying only changed-region rows (idle PD
        traffic is O(stores), not O(regions)), executing returned
        Instructions.

        Hardening: every store used to beat on the same 1000 ms phase
        and drop failed rounds at LOG.debug — now each store starts at
        a seeded random phase with per-round jitter (the PD never sees
        the whole fleet in one burst), and consecutive failures back
        off exponentially (bounded by ``pd_backoff_max_ms``) with a
        WARNING once the PD looks actually down."""
        import random

        interval = self.opts.heartbeat_interval_ms / 1000.0
        rng = random.Random(zlib.crc32(str(self.server_id).encode())
                            ^ 0x5bd1e995)
        # per-store phase offset: spread the fleet over the interval
        await asyncio.sleep(rng.random() * interval)
        fails = 0
        while self._started:
            try:
                await self._heartbeat_once()
                fails = 0
            except asyncio.CancelledError:
                return
            except Exception:  # noqa: BLE001 — PD may be down; keep trying
                fails += 1
                self.pd_hb_failures += 1
                log = LOG.warning if fails in (3, 10) or fails % 60 == 0 \
                    else LOG.debug
                log("pd heartbeat failed (%d consecutive)", fails,
                    exc_info=fails == 3)
            backoff = interval * (2 ** min(fails, 6)) if fails else interval
            backoff = min(backoff, self.opts.pd_backoff_max_ms / 1000.0)
            # ±10% per-round jitter: phase-locked fleets drift apart
            await asyncio.sleep(backoff * (0.9 + 0.2 * rng.random()))

    def _pd_fingerprint(self, region: Region) -> tuple:
        return (region.epoch.conf_ver, region.epoch.version,
                region.start_key, region.end_key, tuple(region.peers))

    async def _heartbeat_once(self) -> None:
        from tpuraft.rheakv.pd_messages import Instruction

        full = self._pd_need_full
        deltas: list[tuple[Region, str, int]] = []
        fps: dict[int, tuple] = {}
        me = str(self.server_id)
        for rid in self.leader_region_ids():
            engine = self._regions.get(rid)
            if engine is None or not engine.is_leader():
                continue
            region = engine.region
            keys = self.raw_store.approximate_keys_in_range(
                region.start_key, region.end_key)
            fp = self._pd_fingerprint(region)
            last = self._pd_reported.get(rid)
            # a keys move under ~12.5% (and < 64 abs) is noise, not a
            # delta — the PD's split threshold only needs coarse counts
            changed = (full or last is None or last[0] != fp
                       or rid in self._pd_dirty
                       or abs(keys - last[1]) * 8 >= max(last[1], 64))
            if changed:
                deltas.append((region.copy(), me, keys))
                fps[rid] = (fp, keys)
        # batch reporting: region rows ride as deltas, so build the
        # bare store identity directly — store_meta() would deep-copy
        # every region just for us to throw the list away each interval
        meta = StoreMeta(id=zlib.crc32(str(self.server_id).encode()),
                         endpoint=self.server_id.endpoint, regions=[],
                         zone=self.opts.zone)
        instructions, need_full = await self.pd_client.store_heartbeat_batch(
            meta, deltas, full=full)
        # only now (RPC succeeded) do the fingerprints count as reported
        self.pd_batches_sent += 1
        self.pd_deltas_sent += len(deltas)
        if full:
            self.pd_full_syncs += 1
        self._pd_reported.update(fps)
        self._pd_dirty.difference_update(fps)
        self._pd_need_full = bool(need_full)
        for ins in instructions:
            engine = self._regions.get(ins.region_id)
            if engine is None or not engine.is_leader():
                continue
            if ins.kind == Instruction.KIND_SPLIT:
                st = await self.apply_split(ins.region_id,
                                            ins.new_region_id)
                if not st.is_ok():
                    LOG.info("pd-ordered split of region %d failed: %s",
                             ins.region_id, st)
                    # the PD only re-issues on a fresh report: force one
                    self._pd_dirty.add(ins.region_id)
            elif ins.kind == Instruction.KIND_TRANSFER_LEADER \
                    and ins.target_peer:
                await engine.transfer_leadership_to(
                    PeerId.parse(ins.target_peer))

    async def _start_region(self, region: Region) -> RegionEngine:
        engine = RegionEngine(region, self)
        await engine.start()
        self._regions[region.id] = engine
        return engine

    # -- region access -------------------------------------------------------

    def get_region_engine(self, region_id: int) -> Optional[RegionEngine]:
        return self._regions.get(region_id)

    def list_regions(self) -> list[Region]:
        return [e.region for e in self._regions.values()]

    def store_meta(self) -> StoreMeta:
        # stable across restarts/processes (builtin hash() is seeded)
        sid = zlib.crc32(str(self.server_id).encode())
        return StoreMeta(id=sid,
                         endpoint=self.server_id.endpoint,
                         regions=[r.copy() for r in self.list_regions()],
                         zone=self.opts.zone)

    # -- node options for a region's raft group ------------------------------

    def make_node_options(self, region: Region, fsm) -> NodeOptions:
        conf = Configuration.parse(",".join(region.peers))
        opts = NodeOptions(
            election_timeout_ms=self.opts.election_timeout_ms,
            initial_conf=conf,
            fsm=fsm,
        )
        # '/witness'-flagged own peer: this store hosts the region as a
        # WITNESS — metadata-only journal, null FSM, never campaigns
        opts.witness = conf.is_witness(self.server_id)
        if conf.witnesses and self.multi_raft_engine is not None:
            # the device ballot plane (ops/ballot, TpuBallotBox) has no
            # witness-aware commit clamp: witness rows would count as
            # plain data matches on device, silently dropping the third
            # safety layer (ballot_box.commit_point's data clamp).
            # Refuse LOUDLY instead of running witness regions with
            # weaker guarantees than documented.
            raise ValueError(
                f"region {region.id}: witness members "
                f"{[str(p) for p in conf.witnesses]} on an engine-backed "
                f"store — the [G, P] device ballot plane is not "
                f"witness-aware yet (ROADMAP item 4); host witness "
                f"regions on timer-mode stores (no MultiRaftEngine)")
        opts.raft_options.read_only_option = self.opts.read_only_option
        opts.raft_options.quiesce_after_rounds = \
            self.opts.quiesce_after_rounds
        if self.opts.data_path:
            store_base = (f"{self.opts.data_path}/"
                          f"{self.server_id.ip}_{self.server_id.port}")
            base = f"{store_base}/r{region.id}"
            if self.opts.log_scheme == "multilog":
                # one shared journal engine for every region of this
                # store: cross-region group-commit fsync — and the SAME
                # treatment for {term, votedFor}: per-region file://
                # meta would pay one fsync per region per election,
                # which is the serial-fsync herd the shared meta
                # journal exists to absorb (storage/meta_multilog.py)
                opts.log_uri = f"multilog://{store_base}/mlog#r{region.id}"
                opts.raft_meta_uri = \
                    f"multimeta://{store_base}/meta#r{region.id}"
                if self._meta_journal is None:
                    # store-lifetime ref: per-region opens (migration
                    # below, node init) become refcount bumps instead
                    # of journal reopen+fsync cycles on the loop
                    from tpuraft.storage.meta_multilog import get_journal

                    self._meta_journal = get_journal(f"{store_base}/meta")
                self._migrate_legacy_meta(store_base, base, region.id)
            else:
                opts.log_uri = f"{self.opts.log_scheme}://{base}/log"
                opts.raft_meta_uri = f"file://{base}/meta"
            opts.snapshot_uri = f"file://{base}/snapshot"
        else:
            opts.log_uri = "memory://"
            opts.raft_meta_uri = "memory://"
        opts.snapshot = SnapshotOptions(
            interval_secs=self.opts.snapshot_interval_secs)
        return opts

    @staticmethod
    def _migrate_legacy_meta(store_base: str, base: str, rid: int) -> None:
        """One-time upgrade: multilog-scheme stores used to keep
        per-region ``file://`` meta; seed the shared meta journal from
        it so a restarted store can never fall back to term 0 and vote
        twice in a term it already voted in.  The legacy file is
        renamed after seeding (the term guard makes a replayed
        migration a no-op regardless)."""
        legacy = os.path.join(base, "meta", "raft_meta")
        if not os.path.exists(legacy):
            return
        from tpuraft.storage.meta_multilog import MultiRaftMetaStorage
        from tpuraft.storage.meta_storage import RaftMetaStorage

        old = RaftMetaStorage(os.path.join(base, "meta"))
        old.init()
        new = MultiRaftMetaStorage(f"{store_base}/meta", f"r{rid}")
        new.init()
        try:
            if old.term > new.term:
                new.set_term_and_voted_for(old.term, old.voted_for)
        finally:
            new.shutdown()
        os.replace(legacy, legacy + ".migrated")

    def ballot_box_factory(self):
        if self.multi_raft_engine is None:
            return None
        return self.multi_raft_engine.ballot_box_factory()

    # -- leadership bookkeeping (PD heartbeat fodder) ------------------------

    def on_region_leader_start(self, region_id: int, term: int) -> None:
        self._leader_regions.add(region_id)

    def on_region_leader_stop(self, region_id: int) -> None:
        self._leader_regions.discard(region_id)

    def leader_region_ids(self) -> list[int]:
        return sorted(self._leader_regions)

    # -- split ---------------------------------------------------------------

    async def apply_split(self, region_id: int, new_region_id: int,
                          split_key: Optional[bytes] = None) -> Status:
        """Leader-side entry: replicate a RANGE_SPLIT through the region's
        raft group (reference: ``rhea:StoreEngine#applySplit``)."""
        engine = self._regions.get(region_id)
        if engine is None:
            return Status.error(RaftError.ENOENT, f"region {region_id} absent")
        if new_region_id in self._regions:
            return Status.error(RaftError.EEXISTS,
                                f"region {new_region_id} exists")
        region = engine.region
        if split_key is None:
            n = self.raw_store.approximate_keys_in_range(
                region.start_key, region.end_key)
            if n < self.opts.least_keys_on_split:
                return Status.error(
                    RaftError.EBUSY,
                    f"region {region_id} too small to split ({n} keys)")
            split_key = self.raw_store.jump_over(
                region.start_key, region.end_key, n // 2)
        if split_key is None or not region.contains_key(split_key):
            return Status.error(RaftError.EINVAL,
                                f"bad split key {split_key!r}")
        try:
            await engine.raft_store.range_split(new_region_id, split_key)
        except Exception as e:  # noqa: BLE001
            return Status.error(RaftError.EINTERNAL, f"split failed: {e}")
        return Status.OK()

    def do_split(self, region_id: int, new_region_id: int,
                 split_key: bytes) -> None:
        """FSM-side application, invoked deterministically on EVERY replica
        when the RANGE_SPLIT entry commits.  Metadata mutates synchronously;
        the new region's raft node boots asynchronously."""
        engine = self._regions.get(region_id)
        if engine is None or new_region_id in self._regions \
                or new_region_id in self._pending_splits:
            return
        parent = engine.region
        if not parent.contains_key(split_key):
            return
        new_region = Region(
            id=new_region_id,
            start_key=split_key,
            end_key=parent.end_key,
            peers=list(parent.peers),
        )
        new_region.epoch.version = parent.epoch.version + 1
        parent.end_key = split_key
        parent.epoch.version += 1
        self._pending_splits.add(new_region_id)

        async def boot():
            try:
                await self._start_region(new_region)
                if self.pd_client is not None:
                    await self.pd_client.report_split(parent, new_region)
            except Exception:  # noqa: BLE001
                LOG.exception("booting split region %d failed", new_region_id)
            finally:
                self._pending_splits.discard(new_region_id)

        asyncio.ensure_future(boot())
