"""RaftRawKVStore: the async KV API that routes writes through raft.

Reference parity: ``rhea:storage/RaftRawKVStore`` (SURVEY.md §4.5) —
every mutation becomes a serialized KVOperation applied via
``Node#apply``; reads take the readIndex barrier then read the local
store (linearizable without a log write — reference routes reads through
``Node#readIndex`` the same way).
"""

from __future__ import annotations

import asyncio
import struct
import time
from typing import Optional

from tpuraft.core.node import Node
from tpuraft.entity import Task
from tpuraft.errors import RaftError, Status
from tpuraft.rheakv.kv_operation import KVOp, KVOperation
from tpuraft.rheakv.raw_store import RawKVStore, Sequence
from tpuraft.rheakv.state_machine import KVClosure
from tpuraft.util.trace import TRACER, store_proc


class KVStoreError(Exception):
    def __init__(self, status: Status):
        super().__init__(str(status))
        self.status = status


# blind writes: ops whose FSM result is known a priori (always True) —
# the set eligible for ack-at-commit (the pipelined-apply fast path);
# anything whose result depends on store state (CAS, sequences, locks,
# reads-via-log) must wait for its apply
_BLIND_OPS = frozenset((KVOp.PUT, KVOp.DELETE, KVOp.PUT_LIST,
                        KVOp.DELETE_LIST, KVOp.DELETE_RANGE, KVOp.MERGE))

_NOT_EAGER = object()


class RaftRawKVStore:
    def __init__(self, node: Node, store: RawKVStore,
                 apply_batch: int = 32, multi_entries: bool = True,
                 ack_at_commit: bool = True, lane=None):
        self.node = node
        self.store = store
        # apply worker lane (StoreEngineOptions.apply_lane): when set,
        # the lane thread owns the raw store — local reads below are
        # SUBMITTED through it (queue FIFO is the happens-before edge
        # past the read fence) instead of touching the store from the
        # loop while another region's apply mutates it
        self.lane = lane
        # pipelined apply: blind writes ack their proposer at COMMIT
        # (the entry's linearization point — the result is known a
        # priori) and the FSM applies behind in coalesced batches;
        # reads still observe applied state through the read fence
        # (read_index + wait_applied).  False = ack after apply (the
        # pre-write-plane behavior).
        self._ack_at_commit = ack_at_commit
        # multi_entries=False is the mixed-version escape hatch: a
        # KVOp.MULTI log entry replicated to a pre-batch replica would
        # fail its apply (unknown op) and silently diverge state — in a
        # rolling upgrade, keep per-op entries until every store's FSM
        # understands MULTI (StoreEngineOptions.multi_op_entries)
        self._multi_entries = multi_entries
        # server-side apply micro-batching (reference: the apply
        # Disruptor drains up to applyBatch=32 tasks per event):
        # concurrent RPC handlers coalesce into ONE Node.apply_batch —
        # one node-lock acquisition and one flush wait per drain round
        # instead of per op
        self._apply_batch = max(1, apply_batch)
        self._pending: list[tuple[bytes, asyncio.Future, int]] = []
        self._drainer: Optional[asyncio.Task] = None
        # propose-plane observability (fleet metrics): drain rounds and
        # the entries they coalesced — proposed_ops/propose_drains is
        # the live write-amortization factor (ROADMAP item 1's number)
        self.propose_drains = 0
        self.proposed_ops = 0
        # trace-plane process identity for the propose-stage span
        self._proc = store_proc(node.server_id)

    # -- write path (through the log) ---------------------------------------

    async def apply(self, op: KVOperation, eager_result=_NOT_EAGER):
        """Replicate one KVOperation through the region's raft group and
        return its FSM result (public API — the KV command processors
        drive proposals through here).  Raises :class:`KVStoreError` on
        a failed proposal or a failed apply.

        ``eager_result``: pipelined-apply fast path — when set (or
        derived below for blind ops), the proposal acks at COMMIT with
        this pre-known result instead of waiting for the FSM apply."""
        if eager_result is _NOT_EAGER and self._ack_at_commit \
                and op.op in _BLIND_OPS:
            eager_result = True  # blind writes always apply to True
        elif not self._ack_at_commit:
            eager_result = _NOT_EAGER
        fut = asyncio.get_running_loop().create_future()
        # encode HERE, not in the drainer: a malformed op (bad key
        # type) must fail its own caller, not kill the drain task and
        # hang every op coalesced into the same batch
        blob = op.encode()
        tid = op.trace_id
        # propose-stage span: drain-queue wait + node.apply_batch (lock
        # + stage + fsync wait) + quorum round + FSM apply, ending when
        # the closure resolves — the server-side submit→ack envelope
        t0 = time.perf_counter() if tid else 0.0
        self._pending.append((blob, fut, tid, eager_result))
        if self._drainer is None or self._drainer.done():
            self._drainer = asyncio.ensure_future(self._drain())
        status, result = await fut
        if tid:
            TRACER.span(tid, "srv_propose", t0, time.perf_counter(),
                        proc=self._proc, ok=status.is_ok())
        if not status.is_ok():
            raise KVStoreError(status)
        return result

    # compat alias (pre-batch callers reached into the private name)
    _apply = apply

    async def apply_multi(self, ops: list[KVOperation]
                          ) -> list[tuple[Status, object]]:
        """Replicate MANY ops as ONE log entry (one quorum round, one
        fsync amortized over the whole sub-batch) and return per-op
        ``(status, result)`` — the server side of ``kv_command_batch``'s
        cross-region fan-out.  A sub-op failure fails only its slot; a
        failed PROPOSAL (not leader, shutting down) raises for the whole
        batch, exactly like :meth:`apply`."""
        if not ops:
            return []
        if len(ops) == 1:
            # no wrapping overhead for the degenerate batch
            try:
                return [(Status.OK(), await self.apply(ops[0]))]
            except KVStoreError as e:
                if e.status.code == int(RaftError.ESTATEMACHINE):
                    return [(e.status, None)]  # op-level, not proposal-level
                raise
        if not self._multi_entries:
            # per-op log entries (pre-batch-replica compatible): the
            # sub-batch still coalesces into one drain round / one
            # node-lock acquisition, just without log-entry amortization
            outs = await asyncio.gather(*(self.apply(op) for op in ops),
                                        return_exceptions=True)
            results: list[tuple[Status, object]] = []
            for out in outs:
                if isinstance(out, KVStoreError):
                    results.append((out.status, None))
                elif isinstance(out, BaseException):
                    raise out
                else:
                    results.append((Status.OK(), out))
            return results
        mop = KVOperation.multi(ops)
        # the MULTI entry carries ONE trace context: the first traced
        # sub-op's (the whole sub-batch shares one log entry / quorum
        # round, so its flush/quorum/apply stages are genuinely shared)
        mop.trace_id = next((o.trace_id for o in ops if o.trace_id), 0)
        eager = _NOT_EAGER
        if self._ack_at_commit and all(o.op in _BLIND_OPS for o in ops):
            # an all-blind MULTI's per-op outcomes are known a priori
            # too — ack the whole sub-batch at commit, apply behind
            eager = [(0, "", True)] * len(ops)
        outs = await self.apply(mop, eager_result=eager)
        return [(Status.OK() if code == 0 else Status(code, msg), result)
                for code, msg, result in outs]

    def submit_multi(self, ops: list[KVOperation]
                     ) -> Optional[asyncio.Future]:
        """Task-free region sub-batch submission: encode ONE MULTI log
        entry, queue it for the propose drainer, and return a plain
        future resolving to per-op ``(Status, result)`` (or raising
        :class:`KVStoreError` on a failed PROPOSAL, like
        :meth:`apply_multi`).  The batch handler collects MANY regions'
        futures into ONE gather instead of spawning a task per region —
        the server half of the per-op task fan the loop profile blamed.

        Returns ``None`` when multi-op entries are disabled (the
        mixed-version escape hatch) — the caller falls back to the
        task-per-region path."""
        if not self._multi_entries:
            return None
        loop = asyncio.get_running_loop()
        out = loop.create_future()
        if not ops:
            out.set_result([])
            return out
        mop = KVOperation.multi(ops)
        mop.trace_id = next((o.trace_id for o in ops if o.trace_id), 0)
        eager = _NOT_EAGER
        if self._ack_at_commit and all(o.op in _BLIND_OPS for o in ops):
            eager = [(0, "", True)] * len(ops)
        try:
            blob = mop.encode()
        except Exception as e:  # noqa: BLE001 — fail this batch only
            out.set_exception(KVStoreError(
                Status.error(RaftError.EINVAL, f"encode: {e!r}")))
            return out
        tid = mop.trace_id
        t0 = time.perf_counter() if tid else 0.0
        inner = loop.create_future()
        self._pending.append((blob, inner, tid, eager))
        if self._drainer is None or self._drainer.done():
            self._drainer = asyncio.ensure_future(self._drain())
        proc = self._proc

        def _resolve(f: asyncio.Future) -> None:
            if f.cancelled():
                return
            status, result = f.result()
            if tid:
                TRACER.span(tid, "srv_propose", t0, time.perf_counter(),
                            proc=proc, ok=status.is_ok())
            if out.done():
                return
            if not status.is_ok():
                out.set_exception(KVStoreError(status))
                return
            out.set_result([(Status.OK() if code == 0 else Status(code, msg),
                             res) for code, msg, res in result])

        inner.add_done_callback(_resolve)
        return out

    async def _drain(self) -> None:
        # same drain-until-empty invariant as ReadOnlyService's rounds:
        # ops queued while a batch is in flight are picked up by the
        # next loop iteration, never orphaned
        while self._pending:
            batch = self._pending[:self._apply_batch]
            del self._pending[:len(batch)]
            self.propose_drains += 1
            self.proposed_ops += len(batch)
            tasks = []
            for blob, fut, tid, eager_result in batch:
                closure = KVClosure(fut)
                if eager_result is not _NOT_EAGER:
                    # ack-at-commit: the result is pre-known, so the
                    # closure carries it from the start — the commit
                    # fires it, the apply behind finds the future done
                    closure.result = eager_result
                tasks.append(Task(data=blob, done=closure, trace_id=tid,
                                  ack_at_commit=eager_result
                                  is not _NOT_EAGER))
            try:
                await self.node.apply_batch(tasks)
            except Exception as e:  # noqa: BLE001 — fail THIS batch only
                st = Status.error(RaftError.EINTERNAL, f"apply: {e!r}")
                for _, fut, _tid, _eager in batch:
                    if not fut.done():
                        fut.set_result((st, None))

    async def put(self, key: bytes, value: bytes) -> bool:
        return await self._apply(KVOperation(KVOp.PUT, key, value))

    async def put_if_absent(self, key: bytes, value: bytes) -> Optional[bytes]:
        return await self._apply(KVOperation(KVOp.PUT_IF_ABSENT, key, value))

    async def get_and_put(self, key: bytes, value: bytes) -> Optional[bytes]:
        return await self._apply(KVOperation(KVOp.GET_AND_PUT, key, value))

    async def compare_and_put(self, key: bytes, expect: bytes,
                              update: bytes) -> bool:
        return await self._apply(KVOperation.cas(key, expect, update))

    async def merge(self, key: bytes, value: bytes) -> bool:
        return await self._apply(KVOperation(KVOp.MERGE, key, value))

    async def put_list(self, kvs: list[tuple[bytes, bytes]]) -> bool:
        return await self._apply(KVOperation.put_list(kvs))

    async def delete(self, key: bytes) -> bool:
        return await self._apply(KVOperation(KVOp.DELETE, key))

    async def delete_list(self, keys: list[bytes]) -> bool:
        return await self._apply(KVOperation.delete_list(keys))

    async def delete_range(self, start: bytes, end: bytes) -> bool:
        return await self._apply(KVOperation.delete_range(start, end))

    async def get_sequence(self, key: bytes, step: int) -> Sequence:
        if step < 0:
            raise KVStoreError(Status.error(RaftError.EINVAL, "step < 0"))
        if step == 0:  # pure read of the current value
            start, end = await self._apply(KVOperation.get_sequence(key, 0))
            return Sequence(start, end)
        start, end = await self._apply(KVOperation.get_sequence(key, step))
        return Sequence(start, end)

    async def reset_sequence(self, key: bytes) -> bool:
        return await self._apply(KVOperation(KVOp.RESET_SEQUENCE, key))

    async def try_lock_with(self, key: bytes, locker_id: bytes, lease_ms: int,
                            keep_lease: bool = False
                            ) -> tuple[bool, int, bytes]:
        return await self._apply(
            KVOperation.key_lock(key, locker_id, lease_ms, keep_lease))

    async def release_lock(self, key: bytes, locker_id: bytes) -> bool:
        return await self._apply(KVOperation.key_unlock(key, locker_id))

    async def range_split(self, new_region_id: int, split_key: bytes) -> bool:
        return await self._apply(
            KVOperation.range_split(new_region_id, split_key))

    # -- region-merge choreography (lifecycle plane) -------------------------
    # none of these are blind: the seal barrier's position in the log
    # IS the merge's linearization point, so the proposer must observe
    # its actual apply (and any deterministic rejection), never an
    # eager commit-time ack

    async def merge_seal(self, target_region_id: int) -> bool:
        return await self._apply(KVOperation.merge_seal(target_region_id))

    async def merge_absorb(self, source_region_id: int, source_start: bytes,
                           source_end: bytes, data_blob: bytes) -> bool:
        return await self._apply(KVOperation.merge_absorb(
            source_region_id, source_start, source_end, data_blob))

    async def merge_commit(self, target_region_id: int) -> bool:
        return await self._apply(KVOperation.merge_commit(target_region_id))

    # -- read path (readIndex barrier + local read) --------------------------

    async def _read(self, fn, *args):
        """Fenced local read: read_index barrier, then the store call —
        on the apply lane when one owns the store, else inline."""
        await self.node.read_index()
        if self.lane is not None:
            return await self.lane.submit(fn, *args)
        return fn(*args)

    async def get(self, key: bytes) -> Optional[bytes]:
        return await self._read(self.store.get, key)

    async def multi_get(self, keys: list[bytes]
                        ) -> dict[bytes, Optional[bytes]]:
        return await self._read(self.store.multi_get, keys)

    async def contains_key(self, key: bytes) -> bool:
        return await self._read(self.store.contains_key, key)

    async def scan(self, start: bytes, end: bytes, limit: int = -1,
                   return_value: bool = True
                   ) -> list[tuple[bytes, Optional[bytes]]]:
        return await self._read(self.store.scan, start, end, limit,
                                return_value)

    async def reverse_scan(self, start: bytes, end: bytes, limit: int = -1,
                           return_value: bool = True
                           ) -> list[tuple[bytes, Optional[bytes]]]:
        return await self._read(self.store.reverse_scan, start, end, limit,
                                return_value)
