"""KVOperation: the serialized command replicated through raft.

Reference parity: ``rhea:storage/KVOperation`` — an op-code plus
key/value/extras, created by ``RaftRawKVStore`` and consumed by
``KVStoreStateMachine#onApply`` (SURVEY.md §3.2 "RawKVStore stack").

Wire layout: ``u8 op | u32 klen | key | u32 vlen | value | u32 alen |
aux`` — ``aux`` packs op-specific extras (CAS expect value, scan bounds,
sequence step, lock lease...).
"""

from __future__ import annotations

import enum
import struct
from dataclasses import dataclass, field


class KVOp(enum.IntEnum):
    PUT = 1
    PUT_IF_ABSENT = 2
    DELETE = 3
    COMPARE_PUT = 4            # CAS
    DELETE_RANGE = 5
    GET_SEQUENCE = 6
    MERGE = 7
    PUT_LIST = 8
    DELETE_LIST = 9
    GET_AND_PUT = 10
    RESET_SEQUENCE = 11
    KEY_LOCK = 12
    KEY_LOCK_RELEASE = 13
    RANGE_SPLIT = 14
    # composite: many sub-ops in ONE log entry (the server-side batch
    # plane — kv_command_batch items for one region ride a single
    # quorum round; the FSM applies sub-ops in order with per-op
    # results).  Never sent by clients directly.
    MULTI = 15
    # region-merge choreography (the lifecycle plane): SEAL is the
    # merge barrier replicated through the SOURCE group (writes behind
    # it in the log still apply; writes after it are deterministically
    # rejected on every replica), ABSORB carries the sealed keyspace
    # into the TARGET group's log (range extension + epoch bump apply
    # deterministically on every target replica), COMMIT retires the
    # source group after the target acked the absorb.  Never sent by
    # clients — proposed leader-side by the store engine.
    MERGE_SEAL = 16
    MERGE_ABSORB = 17
    MERGE_COMMIT = 18
    # read ops (only replicated when linearizable-via-log is requested;
    # normally served via readIndex + local read)
    GET = 20
    MULTI_GET = 21
    SCAN = 22
    CONTAINS_KEY = 23


@dataclass
class KVOperation:
    op: int
    key: bytes = b""
    value: bytes = b""
    aux: bytes = b""
    # trace plane: the originating client op's context (util/trace),
    # TRANSIENT — not part of the wire layout above (the batch request
    # carries contexts in its own trailing field); excluded from
    # equality so decoded ops compare equal to their originals
    trace_id: int = field(default=0, compare=False, repr=False)

    def encode(self) -> bytes:
        return (struct.pack("<B", self.op)
                + struct.pack("<I", len(self.key)) + self.key
                + struct.pack("<I", len(self.value)) + self.value
                + struct.pack("<I", len(self.aux)) + self.aux)

    @staticmethod
    def decode(buf: bytes | memoryview) -> "KVOperation":
        buf = memoryview(buf)
        (op,) = struct.unpack_from("<B", buf, 0)
        off = 1
        parts = []
        for _ in range(3):
            (n,) = struct.unpack_from("<I", buf, off)
            off += 4
            parts.append(bytes(buf[off:off + n]))
            off += n
        return KVOperation(op, *parts)

    # -- aux packers ---------------------------------------------------------

    @staticmethod
    def cas(key: bytes, expect: bytes, update: bytes) -> "KVOperation":
        return KVOperation(KVOp.COMPARE_PUT, key, update, expect)

    @staticmethod
    def delete_range(start: bytes, end: bytes) -> "KVOperation":
        return KVOperation(KVOp.DELETE_RANGE, start, end)

    @staticmethod
    def get_sequence(key: bytes, step: int) -> "KVOperation":
        return KVOperation(KVOp.GET_SEQUENCE, key, aux=struct.pack("<q", step))

    @staticmethod
    def key_lock(key: bytes, locker_id: bytes, lease_ms: int,
                 keep_lease: bool) -> "KVOperation":
        return KVOperation(
            KVOp.KEY_LOCK, key, locker_id,
            struct.pack("<qB", lease_ms, int(keep_lease)))

    @staticmethod
    def key_unlock(key: bytes, locker_id: bytes) -> "KVOperation":
        return KVOperation(KVOp.KEY_LOCK_RELEASE, key, locker_id)

    @staticmethod
    def range_split(new_region_id: int, split_key: bytes) -> "KVOperation":
        return KVOperation(KVOp.RANGE_SPLIT, split_key,
                           aux=struct.pack("<q", new_region_id))

    @staticmethod
    def merge_seal(target_region_id: int) -> "KVOperation":
        """Merge barrier for the SOURCE group: aux names the absorbing
        region so every replica records where its keyspace went."""
        return KVOperation(KVOp.MERGE_SEAL,
                           aux=struct.pack("<q", target_region_id))

    @staticmethod
    def merge_absorb(source_region_id: int, source_start: bytes,
                     source_end: bytes, data_blob: bytes) -> "KVOperation":
        """Keyspace handoff for the TARGET group: value carries the
        source's serialized range, aux its id + boundaries so the range
        extension applies deterministically on every replica."""
        aux = (struct.pack("<q", source_region_id)
               + struct.pack("<I", len(source_start)) + source_start
               + struct.pack("<I", len(source_end)) + source_end)
        return KVOperation(KVOp.MERGE_ABSORB, value=data_blob, aux=aux)

    @staticmethod
    def unpack_merge_absorb(aux: bytes) -> tuple[int, bytes, bytes]:
        (src_id,) = struct.unpack_from("<q", aux, 0)
        off = 8
        (sl,) = struct.unpack_from("<I", aux, off)
        off += 4
        start = aux[off:off + sl]
        off += sl
        (el,) = struct.unpack_from("<I", aux, off)
        off += 4
        return src_id, start, aux[off:off + el]

    @staticmethod
    def merge_commit(target_region_id: int) -> "KVOperation":
        """Retirement entry for the SOURCE group, proposed once the
        target acked the absorb."""
        return KVOperation(KVOp.MERGE_COMMIT,
                           aux=struct.pack("<q", target_region_id))

    @staticmethod
    def put_list(kvs: list[tuple[bytes, bytes]]) -> "KVOperation":
        blob = bytearray(struct.pack("<I", len(kvs)))
        for k, v in kvs:
            blob += struct.pack("<I", len(k)) + k
            blob += struct.pack("<I", len(v)) + v
        return KVOperation(KVOp.PUT_LIST, value=bytes(blob))

    @staticmethod
    def unpack_kv_list(blob: bytes) -> list[tuple[bytes, bytes]]:
        (n,) = struct.unpack_from("<I", blob, 0)
        off = 4
        out = []
        for _ in range(n):
            (kl,) = struct.unpack_from("<I", blob, off)
            off += 4
            k = blob[off:off + kl]
            off += kl
            (vl,) = struct.unpack_from("<I", blob, off)
            off += 4
            out.append((k, blob[off:off + vl]))
            off += vl
        return out

    @staticmethod
    def pack_key_list(keys: list[bytes]) -> bytes:
        blob = bytearray(struct.pack("<I", len(keys)))
        for k in keys:
            blob += struct.pack("<I", len(k)) + k
        return bytes(blob)

    @staticmethod
    def delete_list(keys: list[bytes]) -> "KVOperation":
        return KVOperation(KVOp.DELETE_LIST,
                           value=KVOperation.pack_key_list(keys))

    @staticmethod
    def multi(ops: list["KVOperation"]) -> "KVOperation":
        """One log entry carrying many sub-ops (see KVOp.MULTI)."""
        blob = bytearray(struct.pack("<I", len(ops)))
        for op in ops:
            enc = op.encode()
            blob += struct.pack("<I", len(enc)) + enc
        return KVOperation(KVOp.MULTI, value=bytes(blob))

    @staticmethod
    def unpack_multi(blob: bytes) -> list["KVOperation"]:
        (n,) = struct.unpack_from("<I", blob, 0)
        off = 4
        out = []
        for _ in range(n):
            (ln,) = struct.unpack_from("<I", blob, off)
            off += 4
            out.append(KVOperation.decode(blob[off:off + ln]))
            off += ln
        return out

    @staticmethod
    def multi_get(keys: list[bytes]) -> "KVOperation":
        return KVOperation(KVOp.MULTI_GET,
                           value=KVOperation.pack_key_list(keys))

    @staticmethod
    def unpack_key_list(blob: bytes) -> list[bytes]:
        (n,) = struct.unpack_from("<I", blob, 0)
        off = 4
        out = []
        for _ in range(n):
            (kl,) = struct.unpack_from("<I", blob, off)
            off += 4
            out.append(blob[off:off + kl])
            off += kl
        return out
