"""RheaKV: an embedded distributed KV store on multi-raft.

Reference parity: ``jraft-rheakv`` (SURVEY.md §3.2) — regions (key
ranges) each backed by one raft group, a store engine per process
multiplexing many regions over one transport, a placement driver for
region scheduling/splitting.

TPU-first design note: regions map to rows of the MultiRaftEngine's
``[G, P]`` device plane — all regions on a store advance their consensus
math in one fused tick (SURVEY.md §3.5 "multi-group data parallelism").
The KV data path stays host-side (storage + RPC), as in the reference.
"""

from tpuraft.rheakv.client import BatchingOptions, RheaKVStore
from tpuraft.rheakv.kv_operation import KVOp, KVOperation
from tpuraft.rheakv.metadata import Region, RegionEpoch, StoreMeta
from tpuraft.rheakv.raw_store import MemoryRawKVStore, RawKVStore
from tpuraft.rheakv.region_engine import RegionEngine
from tpuraft.rheakv.store_engine import StoreEngine

__all__ = [
    "BatchingOptions",
    "KVOp",
    "KVOperation",
    "MemoryRawKVStore",
    "RawKVStore",
    "Region",
    "RegionEngine",
    "RegionEpoch",
    "RheaKVStore",
    "StoreEngine",
    "StoreMeta",
    "create_raw_kv_store",
]


def create_raw_kv_store(uri: str) -> RawKVStore:
    """SPI factory: ``memory://`` or ``native://<dir>`` (C++ engine).
    Imported lazily so the memory path never touches ctypes."""
    from tpuraft.rheakv.native_store import create_raw_kv_store as _create

    return _create(uri)
