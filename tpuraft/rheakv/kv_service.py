"""Region KV RPC service: wire messages + the store-side processor.

Reference parity: ``rhea:cmd/store/*`` requests +
``rhea:DefaultRegionKVService`` / ``KVCommandProcessor`` (SURVEY.md
§4.5): a request names a region and the client's view of its epoch; the
store checks the epoch (INVALID_REGION_EPOCH → client refreshes route),
then drives the region's RaftRawKVStore.

One generic ``KVCommandRequest`` carries any encoded KVOperation rather
than one message class per op — the op byte inside the blob dispatches.
Results travel as a tagged blob (see ``encode_result``).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Optional

from tpuraft.core.read_only import ReadIndexError
from tpuraft.errors import RaftError, Status
from tpuraft.rheakv.kv_operation import KVOp, KVOperation
from tpuraft.rheakv.metadata import Region
from tpuraft.rheakv.raft_store import KVStoreError
from tpuraft.rpc.messages import register_message
from tpuraft.rpc.transport import RpcError

# RheaKV-layer error codes (reference: rhea:errors/Errors enum)
ERR_INVALID_EPOCH = 2001
ERR_NO_REGION = 2002
ERR_STORE_BUSY = 2003
ERR_KEY_OUT_OF_RANGE = 2004


@dataclass
class KVCommandRequest:
    region_id: int
    conf_ver: int
    version: int
    op_blob: bytes  # encoded KVOperation


@dataclass
class KVCommandResponse:
    code: int = 0
    msg: str = ""
    result: bytes = b""       # tagged result blob
    region_meta: bytes = b""  # current Region encoding on epoch mismatch


@dataclass
class ListRegionsOnStoreRequest:
    pass


@dataclass
class ListRegionsOnStoreResponse:
    regions: list[bytes] = field(default_factory=list)  # Region encodings


register_message(128, KVCommandRequest)
register_message(129, KVCommandResponse)
register_message(130, ListRegionsOnStoreRequest)
register_message(131, ListRegionsOnStoreResponse)


# ---- tagged result codec ---------------------------------------------------

_T_NONE, _T_BOOL, _T_BYTES, _T_SEQ, _T_PAIRS, _T_LOCK = range(6)


def encode_result(result) -> bytes:
    if result is None:
        return struct.pack("<B", _T_NONE)
    if isinstance(result, bool):
        return struct.pack("<BB", _T_BOOL, int(result))
    if isinstance(result, bytes):
        return struct.pack("<B", _T_BYTES) + result
    if isinstance(result, tuple) and len(result) == 2 \
            and all(isinstance(x, int) for x in result):
        return struct.pack("<Bqq", _T_SEQ, result[0], result[1])
    if isinstance(result, tuple) and len(result) == 3:  # lock triple
        ok, token, owner = result
        return (struct.pack("<BBq", _T_LOCK, int(ok), token)
                + struct.pack("<I", len(owner)) + owner)
    if isinstance(result, list):  # list[(key, Optional[value])]
        out = bytearray(struct.pack("<BI", _T_PAIRS, len(result)))
        for k, v in result:
            out += struct.pack("<I", len(k)) + k
            if v is None:
                out += struct.pack("<i", -1)
            else:
                out += struct.pack("<i", len(v)) + v
        return bytes(out)
    raise TypeError(f"cannot encode KV result {result!r}")


def decode_result(blob: bytes):
    buf = memoryview(blob)
    (tag,) = struct.unpack_from("<B", buf, 0)
    if tag == _T_NONE:
        return None
    if tag == _T_BOOL:
        return bool(buf[1])
    if tag == _T_BYTES:
        return bytes(buf[1:])
    if tag == _T_SEQ:
        a, b = struct.unpack_from("<qq", buf, 1)
        return (a, b)
    if tag == _T_LOCK:
        ok, token = struct.unpack_from("<Bq", buf, 1)
        (n,) = struct.unpack_from("<I", buf, 10)
        owner = bytes(buf[14:14 + n])
        return (bool(ok), token, owner)
    if tag == _T_PAIRS:
        (n,) = struct.unpack_from("<I", buf, 1)
        off = 5
        out = []
        for _ in range(n):
            (kl,) = struct.unpack_from("<I", buf, off)
            off += 4
            k = bytes(buf[off:off + kl])
            off += kl
            (vl,) = struct.unpack_from("<i", buf, off)
            off += 4
            if vl < 0:
                out.append((k, None))
            else:
                out.append((k, bytes(buf[off:off + vl])))
                off += vl
        return out
    raise ValueError(f"bad result tag {tag}")


# ---- store-side processor ---------------------------------------------------

# ops a follower may NOT serve; everything routes through the region leader
_WRITE_OPS = {
    KVOp.PUT, KVOp.PUT_IF_ABSENT, KVOp.DELETE, KVOp.COMPARE_PUT,
    KVOp.DELETE_RANGE, KVOp.GET_SEQUENCE, KVOp.MERGE, KVOp.PUT_LIST,
    KVOp.DELETE_LIST, KVOp.GET_AND_PUT, KVOp.RESET_SEQUENCE, KVOp.KEY_LOCK,
    KVOp.KEY_LOCK_RELEASE, KVOp.RANGE_SPLIT,
}


class KVCommandProcessor:
    """Registered as method ``kv_command`` on the store's RpcServer."""

    def __init__(self, store_engine) -> None:
        self._se = store_engine
        store_engine.rpc_server.register("kv_command", self.handle)
        store_engine.rpc_server.register("kv_list_regions",
                                         self.handle_list_regions)

    async def handle_list_regions(self, req: ListRegionsOnStoreRequest
                                  ) -> ListRegionsOnStoreResponse:
        """Region discovery for PD-less clients (split makes new regions
        the static route table has never heard of)."""
        return ListRegionsOnStoreResponse(
            regions=[r.encode() for r in self._se.list_regions()])

    async def handle(self, req: KVCommandRequest) -> KVCommandResponse:
        engine = self._se.get_region_engine(req.region_id)
        if engine is None:
            return KVCommandResponse(
                code=ERR_NO_REGION,
                msg=f"region {req.region_id} not on store {self._se.server_id}")
        region = engine.region
        if (region.epoch.conf_ver != req.conf_ver
                or region.epoch.version != req.version):
            return KVCommandResponse(
                code=ERR_INVALID_EPOCH,
                msg=(f"region {req.region_id} epoch is "
                     f"{region.epoch.conf_ver}.{region.epoch.version}, "
                     f"client sent {req.conf_ver}.{req.version}"),
                region_meta=region.encode())
        op = KVOperation.decode(req.op_blob)
        if not _keys_in_region(op, region):
            # epoch matched but a key escapes the range: the client grouped
            # a batch against a route view that split under it — make it
            # re-shard rather than silently committing through this group
            return KVCommandResponse(
                code=ERR_KEY_OUT_OF_RANGE,
                msg=f"key(s) outside region {req.region_id} range",
                region_meta=region.encode())
        rs = engine.raft_store
        try:
            if op.op in _WRITE_OPS:
                result = await rs._apply(op)
            elif op.op == KVOp.GET:
                result = await rs.get(op.key)
            elif op.op == KVOp.MULTI_GET:
                keys = KVOperation.unpack_key_list(op.value)
                got = await rs.multi_get(keys)
                result = [(k, got[k]) for k in keys]
            elif op.op == KVOp.CONTAINS_KEY:
                result = await rs.contains_key(op.key)
            elif op.op == KVOp.SCAN:
                (limit, rv, reverse) = struct.unpack("<iBB", op.aux)
                scan = rs.reverse_scan if reverse else rs.scan
                result = await scan(op.key, op.value, limit, bool(rv))
            else:
                return KVCommandResponse(code=int(RaftError.EINVAL),
                                         msg=f"bad op {op.op}")
        except KVStoreError as e:
            return KVCommandResponse(code=e.status.code, msg=e.status.error_msg)
        except (RpcError, ReadIndexError) as e:
            # keep the real status code: ETIMEDOUT/EPERM/ERAFTTIMEDOUT are
            # retryable by the client; EINTERNAL would hard-fail the call
            return KVCommandResponse(code=e.status.code, msg=e.status.error_msg)
        except Exception as e:  # noqa: BLE001
            return KVCommandResponse(code=int(RaftError.EINTERNAL), msg=str(e))
        return KVCommandResponse(result=encode_result(result))


_SINGLE_KEY_OPS = {
    KVOp.PUT, KVOp.PUT_IF_ABSENT, KVOp.DELETE, KVOp.COMPARE_PUT,
    KVOp.GET_SEQUENCE, KVOp.MERGE, KVOp.GET_AND_PUT, KVOp.RESET_SEQUENCE,
    KVOp.KEY_LOCK, KVOp.KEY_LOCK_RELEASE, KVOp.RANGE_SPLIT, KVOp.GET,
    KVOp.CONTAINS_KEY,
}


def _keys_in_region(op: KVOperation, region: Region) -> bool:
    code = op.op
    if code in _SINGLE_KEY_OPS:
        return region.contains_key(op.key)
    if code in (KVOp.DELETE_RANGE, KVOp.SCAN):
        return region.contains_range(op.key, op.value)
    if code == KVOp.PUT_LIST:
        return all(region.contains_key(k)
                   for k, _ in KVOperation.unpack_kv_list(op.value))
    if code in (KVOp.DELETE_LIST, KVOp.MULTI_GET):
        return all(region.contains_key(k)
                   for k in KVOperation.unpack_key_list(op.value))
    return True


def scan_op(start: bytes, end: bytes, limit: int = -1,
            return_value: bool = True, reverse: bool = False) -> KVOperation:
    return KVOperation(KVOp.SCAN, start, end,
                       struct.pack("<iBB", limit, int(return_value),
                                   int(reverse)))
