"""Region KV RPC service: wire messages + the store-side processor.

Reference parity: ``rhea:cmd/store/*`` requests +
``rhea:DefaultRegionKVService`` / ``KVCommandProcessor`` (SURVEY.md
§4.5): a request names a region and the client's view of its epoch; the
store checks the epoch (INVALID_REGION_EPOCH → client refreshes route),
then drives the region's RaftRawKVStore.

One generic ``KVCommandRequest`` carries any encoded KVOperation rather
than one message class per op — the op byte inside the blob dispatches.
Results travel as a tagged blob (see ``encode_result``).
"""

from __future__ import annotations

import asyncio
import struct
import time
from dataclasses import dataclass, field
from typing import Optional

from tpuraft.core.read_only import ReadIndexError
from tpuraft.util.trace import RECORDER, TRACER, store_proc, unpack_ctx
from tpuraft.errors import RaftError, Status
from tpuraft.rheakv.kv_operation import KVOp, KVOperation
from tpuraft.rheakv.metadata import Region
from tpuraft.rheakv.raft_store import KVStoreError
from tpuraft.rpc.messages import register_message
from tpuraft.rpc.transport import RpcError

# RheaKV-layer error codes (reference: rhea:errors/Errors enum)
ERR_INVALID_EPOCH = 2001
ERR_NO_REGION = 2002
ERR_STORE_BUSY = 2003
ERR_KEY_OUT_OF_RANGE = 2004


@dataclass
class KVCommandRequest:
    region_id: int
    conf_ver: int
    version: int
    op_blob: bytes  # encoded KVOperation
    # TRAILING trace-plane extension (old decoders stop before it):
    # the client op's trace context; 0 = untraced
    trace_id: int = 0


@dataclass
class KVCommandResponse:
    code: int = 0
    msg: str = ""
    result: bytes = b""       # tagged result blob
    region_meta: bytes = b""  # current Region encoding on epoch mismatch


@dataclass
class ListRegionsOnStoreRequest:
    pass


@dataclass
class ListRegionsOnStoreResponse:
    regions: list[bytes] = field(default_factory=list)  # Region encodings


@dataclass
class KVCommandBatchRequest:
    """Store-grouped command batch: ONE RPC carries many (region, op)
    items — the client groups everything pending by leader store the way
    the raft plane's ``multi_append`` groups log frames by endpoint.
    Each item blob packs (region_id, conf_ver, version, op_blob); see
    :func:`encode_batch_item`.  Epoch checks and result/error codes are
    PER ITEM — one stale region never fails its neighbours."""

    items: list[bytes] = field(default_factory=list)
    # TRAILING trace-plane extension: one packed i64 trace context per
    # item (util/trace.pack_ctx), b"" when nothing is traced — old
    # decoders stop before it, the untraced path pays zero wire bytes
    trace_ctx: bytes = b""


@dataclass
class KVCommandBatchResponse:
    """One reply blob per request item, in order (:func:`encode_batch_reply`)."""

    items: list[bytes] = field(default_factory=list)


@dataclass
class MergeAbsorbRequest:
    """Keyspace handoff (lifecycle plane): the SOURCE region's leader
    store hands the sealed range to the TARGET region's leader, which
    replicates it through the target group as a MERGE_ABSORB entry."""

    target_region_id: int = 0
    source_region_id: int = 0
    source_start: bytes = b""
    source_end: bytes = b""
    data_blob: bytes = b""    # serialized source range (RawKVStore codec)


@dataclass
class MergeAbsorbResponse:
    code: int = 0
    msg: str = ""


register_message(128, KVCommandRequest)
register_message(129, KVCommandResponse)
register_message(130, ListRegionsOnStoreRequest)
register_message(131, ListRegionsOnStoreResponse)
register_message(132, KVCommandBatchRequest)
register_message(133, KVCommandBatchResponse)
register_message(134, MergeAbsorbRequest)
register_message(135, MergeAbsorbResponse)


# ---- batch item / reply codecs ---------------------------------------------

_ITEM_HDR = struct.Struct("<qqq")   # region_id, conf_ver, version


def encode_batch_item(region_id: int, conf_ver: int, version: int,
                      op_blob: bytes) -> bytes:
    return _ITEM_HDR.pack(region_id, conf_ver, version) + op_blob


def decode_batch_item(blob: bytes) -> tuple[int, int, int, bytes]:
    region_id, conf_ver, version = _ITEM_HDR.unpack_from(blob, 0)
    return region_id, conf_ver, version, bytes(blob[_ITEM_HDR.size:])


def encode_batch_reply(code: int, msg: str = "", result: bytes = b"",
                       region_meta: bytes = b"") -> bytes:
    m = msg.encode()
    return (struct.pack("<qI", code, len(m)) + m
            + struct.pack("<I", len(result)) + result
            + struct.pack("<I", len(region_meta)) + region_meta)


def decode_batch_reply(blob: bytes) -> tuple[int, str, bytes, bytes]:
    buf = memoryview(blob)
    code, mlen = struct.unpack_from("<qI", buf, 0)
    off = 12
    msg = bytes(buf[off:off + mlen]).decode()
    off += mlen
    (rlen,) = struct.unpack_from("<I", buf, off)
    off += 4
    result = bytes(buf[off:off + rlen])
    off += rlen
    (glen,) = struct.unpack_from("<I", buf, off)
    off += 4
    return code, msg, result, bytes(buf[off:off + glen])


# ---- tagged result codec ---------------------------------------------------

_T_NONE, _T_BOOL, _T_BYTES, _T_SEQ, _T_PAIRS, _T_LOCK = range(6)


def encode_result(result) -> bytes:
    if result is None:
        return struct.pack("<B", _T_NONE)
    if isinstance(result, bool):
        return struct.pack("<BB", _T_BOOL, int(result))
    if isinstance(result, bytes):
        return struct.pack("<B", _T_BYTES) + result
    if isinstance(result, tuple) and len(result) == 2 \
            and all(isinstance(x, int) for x in result):
        return struct.pack("<Bqq", _T_SEQ, result[0], result[1])
    if isinstance(result, tuple) and len(result) == 3:  # lock triple
        ok, token, owner = result
        return (struct.pack("<BBq", _T_LOCK, int(ok), token)
                + struct.pack("<I", len(owner)) + owner)
    if isinstance(result, list):  # list[(key, Optional[value])]
        out = bytearray(struct.pack("<BI", _T_PAIRS, len(result)))
        for k, v in result:
            out += struct.pack("<I", len(k)) + k
            if v is None:
                out += struct.pack("<i", -1)
            else:
                out += struct.pack("<i", len(v)) + v
        return bytes(out)
    raise TypeError(f"cannot encode KV result {result!r}")


def decode_result(blob: bytes):
    buf = memoryview(blob)
    (tag,) = struct.unpack_from("<B", buf, 0)
    if tag == _T_NONE:
        return None
    if tag == _T_BOOL:
        return bool(buf[1])
    if tag == _T_BYTES:
        return bytes(buf[1:])
    if tag == _T_SEQ:
        a, b = struct.unpack_from("<qq", buf, 1)
        return (a, b)
    if tag == _T_LOCK:
        ok, token = struct.unpack_from("<Bq", buf, 1)
        (n,) = struct.unpack_from("<I", buf, 10)
        owner = bytes(buf[14:14 + n])
        return (bool(ok), token, owner)
    if tag == _T_PAIRS:
        (n,) = struct.unpack_from("<I", buf, 1)
        off = 5
        out = []
        for _ in range(n):
            (kl,) = struct.unpack_from("<I", buf, off)
            off += 4
            k = bytes(buf[off:off + kl])
            off += kl
            (vl,) = struct.unpack_from("<i", buf, off)
            off += 4
            if vl < 0:
                out.append((k, None))
            else:
                out.append((k, bytes(buf[off:off + vl])))
                off += vl
        return out
    raise ValueError(f"bad result tag {tag}")


# ---- store-side processor ---------------------------------------------------

# ops a follower may NOT serve; everything routes through the region leader
_WRITE_OPS = {
    KVOp.PUT, KVOp.PUT_IF_ABSENT, KVOp.DELETE, KVOp.COMPARE_PUT,
    KVOp.DELETE_RANGE, KVOp.GET_SEQUENCE, KVOp.MERGE, KVOp.PUT_LIST,
    KVOp.DELETE_LIST, KVOp.GET_AND_PUT, KVOp.RESET_SEQUENCE, KVOp.KEY_LOCK,
    KVOp.KEY_LOCK_RELEASE, KVOp.RANGE_SPLIT,
}


# graftcheck: loop-confined — handlers run on the store's RPC loop;
# counters are lockless by that confinement
class KVCommandProcessor:
    """Registered as methods ``kv_command`` (one op, one region) and
    ``kv_command_batch`` (store-grouped: many regions' ops in one RPC,
    per-item epoch checks and per-item results) on the store's RpcServer."""

    def __init__(self, store_engine) -> None:
        self._se = store_engine
        # trace-plane process identity: spans emitted by this store's
        # handlers land on their own pid row even when several stores
        # share one OS process (the in-proc bench/test topology)
        self._proc = store_proc(store_engine.server_id)
        # per-region heat intake (fleet observability): writes noted at
        # admission (op count + op-blob bytes in), reads at serve (op
        # count + reply bytes out) — one dict bump per item, the O(1)
        # hot-path contract the bench-gate heat row enforces
        self._heat = store_engine.heat
        store_engine.rpc_server.register("kv_command", self.handle)
        store_engine.rpc_server.register("kv_command_batch",
                                         self.handle_batch)
        store_engine.rpc_server.register("kv_list_regions",
                                         self.handle_list_regions)
        store_engine.rpc_server.register("kv_merge_absorb",
                                         self.handle_merge_absorb)
        # observability (bench counters / wire-compat tests)
        self.batch_rpcs = 0      # kv_command_batch RPCs served
        self.batch_items = 0     # items carried inside them
        self.batch_regions = 0   # distinct regions proposed per batch, summed
        self.single_rpcs = 0     # legacy per-op kv_command RPCs served
        # serving-plane degradation (gray failures): items currently in
        # the propose/apply pipe, and how many we bounced with EBUSY +
        # retry-after because the store was SICK past the backlog bound
        self.inflight_items = 0
        self.shed_items = 0
        # read plane: N batched GETs of one region cost ONE read_index
        # fence (fenced_reads / read_fences = the amortization ratio)
        self.read_fences = 0     # read_index barriers taken for batches
        self.fenced_reads = 0    # read ops served under those barriers

    async def handle_list_regions(self, req: ListRegionsOnStoreRequest
                                  ) -> ListRegionsOnStoreResponse:
        """Region discovery for PD-less clients (split makes new regions
        the static route table has never heard of)."""
        return ListRegionsOnStoreResponse(
            regions=[r.encode() for r in self._se.list_regions()])

    async def handle_merge_absorb(self, req: MergeAbsorbRequest
                                  ) -> MergeAbsorbResponse:
        """Target-side half of a region merge: replicate the handed-over
        keyspace through the target group (store-to-store RPC — the
        source leader calls this after its seal barrier applied)."""
        engine = self._se.get_region_engine(req.target_region_id)
        if engine is None:
            return MergeAbsorbResponse(
                code=ERR_NO_REGION,
                msg=f"target region {req.target_region_id} not on "
                    f"store {self._se.server_id}")
        try:
            await engine.raft_store.merge_absorb(
                req.source_region_id, req.source_start, req.source_end,
                req.data_blob)
        except KVStoreError as e:
            # EPERM (not leader) / ESTATEMACHINE etc. bounce to the
            # source store, which retries against the fresh leader
            return MergeAbsorbResponse(code=e.status.code,
                                       msg=e.status.error_msg)
        except Exception as e:  # noqa: BLE001
            return MergeAbsorbResponse(code=int(RaftError.EINTERNAL),
                                       msg=str(e))
        return MergeAbsorbResponse()

    def _validate(self, region_id: int, conf_ver: int, version: int,
                  op_blob: bytes):
        """Shared per-item admission: returns either ``(None, engine, op)``
        or ``((code, msg, region_meta), None, None)`` on rejection."""
        engine = self._se.get_region_engine(region_id)
        if engine is None:
            return ((ERR_NO_REGION,
                     f"region {region_id} not on store {self._se.server_id}",
                     b""), None, None)
        region = engine.region
        if (region.epoch.conf_ver != conf_ver
                or region.epoch.version != version):
            return ((ERR_INVALID_EPOCH,
                     (f"region {region_id} epoch is "
                      f"{region.epoch.conf_ver}.{region.epoch.version}, "
                      f"client sent {conf_ver}.{version}"),
                     region.encode()), None, None)
        op = KVOperation.decode(op_blob)
        if op.op in _WRITE_OPS \
                and (engine.sealing
                     or getattr(engine.fsm, "sealed_into", -1) >= 0):
            # merge barrier: new writes bounce RETRYABLY the moment the
            # seal is decided (leader-local `sealing` covers the window
            # before the entry applies); reads keep serving off the
            # immutable sealed range until retirement.  The client
            # retries, lands ERR_NO_REGION after retirement, refreshes
            # and reroutes into the absorbing region.
            return ((ERR_STORE_BUSY,
                     f"region {region_id} sealed for merge "
                     f"(retry-after-ms=100)", b""), None, None)
        if not _keys_in_region(op, region):
            # epoch matched but a key escapes the range: the client grouped
            # a batch against a route view that split under it — make it
            # re-shard rather than silently committing through this group
            return ((ERR_KEY_OUT_OF_RANGE,
                     f"key(s) outside region {region_id} range",
                     region.encode()), None, None)
        return None, engine, op

    async def _execute_op(self, rs, op: KVOperation
                          ) -> tuple[int, str, object]:
        """Run one admitted op through the region store; (code, msg, result)."""
        try:
            if op.op in _WRITE_OPS:
                result = await rs.apply(op)
            else:
                # ONE dispatch table for reads: fence here, then the
                # same local-serve path the batched fast path uses —
                # on the apply lane when one owns the store
                await rs.node.read_index()
                if rs.lane is not None:
                    return await rs.lane.submit(_serve_read_local, rs, op)
                return _serve_read_local(rs, op)
        except KVStoreError as e:
            return e.status.code, e.status.error_msg, None
        except (RpcError, ReadIndexError) as e:
            # keep the real status code: ETIMEDOUT/EPERM/ERAFTTIMEDOUT are
            # retryable by the client; EINTERNAL would hard-fail the call
            return e.status.code, e.status.error_msg, None
        except Exception as e:  # noqa: BLE001
            return int(RaftError.EINTERNAL), str(e), None
        return 0, "", result

    async def handle(self, req: KVCommandRequest) -> KVCommandResponse:
        self.single_rpcs += 1
        if self._se.draining:
            # SIGTERM drain: bounce NEW work with a retryable busy (the
            # client re-offers it to the surviving stores) while already
            # admitted items finish and ack — see StoreEngine.drain
            return KVCommandResponse(
                code=ERR_STORE_BUSY,
                msg="store draining (retry-after-ms=100)")
        shed, retry_ms = self._se.should_shed()
        if shed:
            self.shed_items += 1
            # coalesced: shed fires at REQUEST rate during the exact
            # incident the recorder ring must survive
            RECORDER.record_coalesced("shed", str(self._se.server_id),
                                      items=1, retry_ms=retry_ms)
            return KVCommandResponse(
                code=ERR_STORE_BUSY,
                msg=f"store sick: shedding (retry-after-ms={retry_ms})")
        rejected, engine, op = self._validate(
            req.region_id, req.conf_ver, req.version, req.op_blob)
        if rejected is not None:
            code, msg, meta = rejected
            return KVCommandResponse(code=code, msg=msg, region_meta=meta)
        if req.trace_id and TRACER.enabled:
            # same gate as the batch path: a wire-borne context only
            # produces spans where the local tracer is armed
            op.trace_id = req.trace_id
        is_write = op.op in _WRITE_OPS
        if is_write:
            # disk-pressure admission (FULL): shed WRITES retryably,
            # keep serving reads — a full store remains a useful read
            # replica while reclaim frees space (ISSUE 17 layer 3)
            wshed, wretry = self._se.should_shed_writes()
            if wshed:
                self._se.disk_shed_items += 1
                RECORDER.record_coalesced("disk_shed",
                                          str(self._se.server_id),
                                          items=1, retry_ms=wretry)
                return KVCommandResponse(
                    code=ERR_STORE_BUSY,
                    msg=f"store disk full: shedding writes "
                        f"(retry-after-ms={wretry})")
        if self._heat is not None and is_write:
            self._heat.note_write(req.region_id, 1, len(req.op_blob))
        self.inflight_items += 1
        try:
            code, msg, result = await self._execute_op(engine.raft_store, op)
        finally:
            self.inflight_items -= 1
        if code:
            return KVCommandResponse(code=code, msg=msg)
        blob = encode_result(result)
        if self._heat is not None and not is_write:
            self._heat.note_read(req.region_id, 1, len(blob))
        return KVCommandResponse(result=blob)

    async def handle_batch(self, req: KVCommandBatchRequest
                           ) -> KVCommandBatchResponse:
        """The store-grouped fast path: validate every item, then propose
        each region's write sub-batch as ONE multi-op log entry — every
        region's quorum round runs CONCURRENTLY instead of op-by-op
        through sequential ``kv_command`` handlers."""
        self.batch_rpcs += 1
        self.batch_items += len(req.items)
        if self._se.draining:
            bounce = encode_batch_reply(
                ERR_STORE_BUSY, "store draining (retry-after-ms=100)")
            return KVCommandBatchResponse(items=[bounce] * len(req.items))
        # serving-plane degradation: under a SICK local score with the
        # pipe already backed up, SHED — a deadline-aware EBUSY with a
        # retry-after hint beats queueing 256 workers behind a stalling
        # disk into p99=inf (the client treats it as retryable and its
        # jittered backoff spreads the re-offered load; by then
        # evacuation has usually moved leadership off this store)
        shed, retry_ms = self._se.should_shed()
        if shed:
            self.shed_items += len(req.items)
            RECORDER.record_coalesced("shed", str(self._se.server_id),
                                      items=len(req.items),
                                      retry_ms=retry_ms)
            bounce = encode_batch_reply(
                ERR_STORE_BUSY,
                f"store sick: shedding (retry-after-ms={retry_ms})")
            return KVCommandBatchResponse(items=[bounce] * len(req.items))
        self.inflight_items += len(req.items)
        try:
            return await self._handle_batch_admitted(req)
        finally:
            self.inflight_items -= len(req.items)

    async def _handle_batch_admitted(self, req: KVCommandBatchRequest
                                     ) -> KVCommandBatchResponse:
        replies: list[bytes] = [b""] * len(req.items)
        groups: dict[int, list[tuple[int, KVOperation]]] = {}
        # trace plane: per-item contexts ride the trailing trace_ctx
        # field; adopting them onto the decoded ops lets the propose /
        # flush / apply stages downstream join the client's trace
        tids = (unpack_ctx(req.trace_ctx, len(req.items))
                if TRACER.enabled and req.trace_ctx else None)
        v0 = time.perf_counter() if tids else 0.0
        # disk-pressure admission (FULL): per-ITEM, not whole-batch —
        # the batch's reads keep serving while its writes bounce with
        # the retryable busy (ISSUE 17: a full store stays a read
        # replica; the client re-offers writes after retry-after)
        wshed, wretry = self._se.should_shed_writes()
        wsheds = 0
        for i, blob in enumerate(req.items):
            region_id, conf_ver, version, op_blob = decode_batch_item(blob)
            rejected, engine, op = self._validate(
                region_id, conf_ver, version, op_blob)
            if rejected is not None:
                code, msg, meta = rejected
                replies[i] = encode_batch_reply(code, msg, region_meta=meta)
                continue
            if wshed and op.op in _WRITE_OPS:
                wsheds += 1
                replies[i] = encode_batch_reply(
                    ERR_STORE_BUSY,
                    f"store disk full: shedding writes "
                    f"(retry-after-ms={wretry})")
                continue
            if tids and tids[i]:
                op.trace_id = tids[i]
            if self._heat is not None and op.op in _WRITE_OPS:
                self._heat.note_write(region_id, 1, len(op_blob))
            groups.setdefault(region_id, []).append((i, op))
        if wsheds:
            self._se.disk_shed_items += wsheds
            RECORDER.record_coalesced("disk_shed", str(self._se.server_id),
                                      items=wsheds, retry_ms=wretry)
        if tids:
            v1 = time.perf_counter()
            for tid in tids:
                if tid:
                    TRACER.span(tid, "srv_validate", v0, v1,
                                proc=self._proc)
        self.batch_regions += len(groups)

        async def run_region(rid: int, items: list) -> None:
            engine = self._se.get_region_engine(rid)
            if engine is None:   # vanished between validation and here
                for i, _ in items:
                    replies[i] = encode_batch_reply(
                        ERR_NO_REGION, f"region {rid} dropped mid-batch")
                return
            rs = engine.raft_store
            writes = [(i, op) for i, op in items if op.op in _WRITE_OPS]
            reads = [(i, op) for i, op in items if op.op not in _WRITE_OPS]

            async def run_writes():
                try:
                    outs = await rs.apply_multi([op for _, op in writes])
                    for (i, _), (st, result) in zip(writes, outs):
                        replies[i] = (
                            encode_batch_reply(0, result=encode_result(result))
                            if st.is_ok()
                            else encode_batch_reply(st.code, st.error_msg))
                except KVStoreError as e:
                    for i, _ in writes:
                        replies[i] = encode_batch_reply(e.status.code,
                                                        e.status.error_msg)
                except Exception as e:  # noqa: BLE001
                    for i, _ in writes:
                        replies[i] = encode_batch_reply(
                            int(RaftError.EINTERNAL), str(e))

            async def run_reads() -> None:
                # ONE read fence for the whole region sub-batch: every
                # read here was pinned before the fence's confirmation
                # round started, so serving all of them at the fenced
                # index is linearizable — and a kv_command_batch with N
                # GETs for one region costs one confirmation, not N
                rtids = ([op.trace_id for _, op in reads if op.trace_id]
                         if TRACER.enabled else [])
                f0 = time.perf_counter() if rtids else 0.0
                try:
                    await rs.node.read_index()
                except (RpcError, ReadIndexError) as e:
                    # keep the real (retryable) status per item
                    for i, _ in reads:
                        replies[i] = encode_batch_reply(e.status.code,
                                                        e.status.error_msg)
                    return
                except Exception as e:  # noqa: BLE001
                    for i, _ in reads:
                        replies[i] = encode_batch_reply(
                            int(RaftError.EINTERNAL), str(e))
                    return
                self.read_fences += 1
                self.fenced_reads += len(reads)
                if rtids:
                    f1 = time.perf_counter()
                    for tid in rtids:
                        TRACER.span(tid, "srv_read_fence", f0, f1,
                                    proc=self._proc)
                served = out_bytes = 0
                lane = rs.lane
                if lane is not None:
                    # lane mode: the lane thread owns the store — serve
                    # the whole fenced sub-batch in ONE lane hop (one
                    # shared serve-span envelope for traced ops)
                    s0 = time.perf_counter() if rtids else 0.0
                    outs = await lane.submit(_serve_reads_sync, rs, reads)
                    if rtids:
                        s1 = time.perf_counter()
                        for tid in rtids:
                            TRACER.span(tid, "srv_read_serve", s0, s1,
                                        proc=self._proc)
                    for (i, _op), (code, msg, result) in zip(reads, outs):
                        replies[i] = (
                            encode_batch_reply(0,
                                               result=encode_result(result))
                            if code == 0 else encode_batch_reply(code, msg))
                        if code == 0:
                            served += 1
                            out_bytes += len(replies[i])
                else:
                    for i, op in reads:
                        s0 = time.perf_counter() if op.trace_id else 0.0
                        code, msg, result = _serve_read_local(rs, op)
                        if op.trace_id:
                            TRACER.span(op.trace_id, "srv_read_serve", s0,
                                        time.perf_counter(), proc=self._proc)
                        replies[i] = (
                            encode_batch_reply(0,
                                               result=encode_result(result))
                            if code == 0 else encode_batch_reply(code, msg))
                        if code == 0:
                            served += 1
                            out_bytes += len(replies[i])
                if served and self._heat is not None:
                    self._heat.note_read(rid, served, out_bytes)

            if not reads:
                # the pure-write sub-batch (the w256 shape): no gather
                # layer — one less task per region per RPC on the
                # saturated write path
                await run_writes()
            elif not writes:
                await run_reads()
            else:
                await asyncio.gather(run_writes(), run_reads())

        # pure-write region groups skip the task layer ENTIRELY:
        # submit_multi queues the region's ONE MULTI entry synchronously
        # and hands back a plain future — a kv_command_batch spanning
        # hundreds of regions (the w256 shape at 1024 regions) costs one
        # gather over futures instead of one task per region.  Mixed and
        # read groups keep the run_region coroutine (the read fence must
        # be awaited per region).
        lite: list[tuple[list, asyncio.Future]] = []
        tasks = []
        for rid, items in groups.items():
            fut = None
            if all(op.op in _WRITE_OPS for _, op in items):
                engine = self._se.get_region_engine(rid)
                if engine is None:  # vanished between validation and here
                    for i, _ in items:
                        replies[i] = encode_batch_reply(
                            ERR_NO_REGION, f"region {rid} dropped mid-batch")
                    continue
                fut = engine.raft_store.submit_multi(
                    [op for _, op in items])
            if fut is None:
                tasks.append(run_region(rid, items))
            else:
                lite.append((items, fut))
        if lite or tasks:
            results = await asyncio.gather(
                *(f for _, f in lite), *tasks, return_exceptions=True)
            for (items, _f), res in zip(lite, results):
                if isinstance(res, KVStoreError):
                    for i, _ in items:
                        replies[i] = encode_batch_reply(res.status.code,
                                                        res.status.error_msg)
                elif isinstance(res, BaseException):
                    for i, _ in items:
                        replies[i] = encode_batch_reply(
                            int(RaftError.EINTERNAL), str(res))
                else:
                    for (i, _), (st, result) in zip(items, res):
                        replies[i] = (
                            encode_batch_reply(0,
                                               result=encode_result(result))
                            if st.is_ok()
                            else encode_batch_reply(st.code, st.error_msg))
        return KVCommandBatchResponse(items=replies)


def _serve_read_local(rs, op: KVOperation) -> tuple[int, str, object]:
    """Serve one read-only op DIRECTLY off the local store — the caller
    already holds the region's read fence (read_index + wait_applied),
    so no per-op barrier is taken."""
    try:
        if op.op == KVOp.GET:
            result = rs.store.get(op.key)
        elif op.op == KVOp.MULTI_GET:
            keys = KVOperation.unpack_key_list(op.value)
            got = rs.store.multi_get(keys)
            result = [(k, got[k]) for k in keys]
        elif op.op == KVOp.CONTAINS_KEY:
            result = rs.store.contains_key(op.key)
        elif op.op == KVOp.SCAN:
            (limit, rv, reverse) = struct.unpack("<iBB", op.aux)
            scan = rs.store.reverse_scan if reverse else rs.store.scan
            result = scan(op.key, op.value, limit, bool(rv))
        else:
            return int(RaftError.EINVAL), f"bad read op {op.op}", None
    except Exception as e:  # noqa: BLE001
        return int(RaftError.EINTERNAL), str(e), None
    return 0, "", result


def _serve_reads_sync(rs, reads: list) -> list[tuple[int, str, object]]:
    """One lane job serving a whole fenced region read sub-batch."""
    return [_serve_read_local(rs, op) for _, op in reads]


_SINGLE_KEY_OPS = {
    KVOp.PUT, KVOp.PUT_IF_ABSENT, KVOp.DELETE, KVOp.COMPARE_PUT,
    KVOp.GET_SEQUENCE, KVOp.MERGE, KVOp.GET_AND_PUT, KVOp.RESET_SEQUENCE,
    KVOp.KEY_LOCK, KVOp.KEY_LOCK_RELEASE, KVOp.RANGE_SPLIT, KVOp.GET,
    KVOp.CONTAINS_KEY,
}


def _keys_in_region(op: KVOperation, region: Region) -> bool:
    code = op.op
    if code in _SINGLE_KEY_OPS:
        return region.contains_key(op.key)
    if code in (KVOp.DELETE_RANGE, KVOp.SCAN):
        return region.contains_range(op.key, op.value)
    if code == KVOp.PUT_LIST:
        return all(region.contains_key(k)
                   for k, _ in KVOperation.unpack_kv_list(op.value))
    if code in (KVOp.DELETE_LIST, KVOp.MULTI_GET):
        return all(region.contains_key(k)
                   for k in KVOperation.unpack_key_list(op.value))
    return True


def scan_op(start: bytes, end: bytes, limit: int = -1,
            return_value: bool = True, reverse: bool = False) -> KVOperation:
    return KVOperation(KVOp.SCAN, start, end,
                       struct.pack("<iBB", limit, int(return_value),
                                   int(reverse)))
