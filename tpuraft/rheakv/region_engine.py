"""RegionEngine: one raft group member serving one region on a store.

Reference parity: ``rhea:RegionEngine`` (SURVEY.md §3.2 "StoreEngine"
row) — owns the region's raft Node (via RaftGroupService), its
KVStoreStateMachine over the store-shared RawKVStore, and the
RaftRawKVStore async API.
"""

from __future__ import annotations

import logging
from typing import Optional

from tpuraft.conf import Configuration
from tpuraft.core.raft_group_service import RaftGroupService
from tpuraft.entity import PeerId
from tpuraft.options import NodeOptions
from tpuraft.rheakv.metadata import Region, region_group_id
from tpuraft.rheakv.raft_store import RaftRawKVStore
from tpuraft.rheakv.raw_store import RawKVStore
from tpuraft.rheakv.state_machine import KVStoreStateMachine

LOG = logging.getLogger(__name__)


class RegionEngine:
    def __init__(self, region: Region, store_engine) -> None:
        self.region = region
        self.store_engine = store_engine
        self.fsm: Optional[KVStoreStateMachine] = None
        self.raft_store: Optional[RaftRawKVStore] = None
        self._group_service: Optional[RaftGroupService] = None
        # merge barrier, leader-local half (lifecycle plane): set BEFORE
        # the seal entry is proposed so no new write is admitted after
        # the seal's position in the log is decided — the FSM's
        # replicated `sealed_into` takes over once the entry applies
        self.sealing = False

    @property
    def group_id(self) -> str:
        return region_group_id(self.store_engine.cluster_name, self.region.id)

    @property
    def node(self):
        return self._group_service.node if self._group_service else None

    def is_leader(self) -> bool:
        n = self.node
        return bool(n and n.is_leader())

    async def start(self) -> None:
        se = self.store_engine
        self.fsm = KVStoreStateMachine(
            self.region, se.raw_store, se,
            coalesce_applies=se.opts.fsm_coalesce)
        # apply worker lane (StoreEngineOptions.apply_lane): the lane
        # owns the shared raw store — the FSM routes snapshot
        # serialization through it, the raft store its fenced reads
        self.fsm.lane = se.apply_lane
        opts = se.make_node_options(self.region, self.fsm)
        self._group_service = RaftGroupService(
            self.group_id, se.server_id, opts, se.node_manager, se.transport,
            ballot_box_factory=se.ballot_box_factory())
        node = await self._group_service.start()
        if se.read_batcher is not None:
            # store-wide SAFE read amortization: this group's quorum
            # confirmations ride the store's shared beat-plane rounds
            node.read_only_service.attach_confirm_batcher(se.read_batcher)
        if se.append_batcher is not None:
            # store-wide write amortization (the read batcher's mirror):
            # this group's replicators submit their entry windows to the
            # store's windowed per-destination append rounds
            node.append_batcher = se.append_batcher
        self.raft_store = RaftRawKVStore(
            node, se.raw_store, multi_entries=se.opts.multi_op_entries,
            ack_at_commit=se.opts.ack_at_commit, lane=se.apply_lane)
        LOG.info("region engine started: %s on %s", self.region,
                 se.server_id)

    async def shutdown(self) -> None:
        if self._group_service:
            await self._group_service.shutdown()
            self._group_service = None

    async def transfer_leadership_to(self, peer: PeerId):
        return await self.node.transfer_leadership_to(peer)
