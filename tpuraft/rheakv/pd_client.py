"""Placement driver clients: fake (static, pd-less) and remote.

Reference parity: ``rhea:client/pd/AbstractPlacementDriverClient`` with
``FakePlacementDriverClient`` (static conf, no PD cluster) and
``RemotePlacementDriverClient`` (region metadata served by the PD's own
raft group) — SURVEY.md §3.2 "PD client".
"""

from __future__ import annotations

import logging
from typing import Optional

from tpuraft.rheakv.metadata import Region, StoreMeta

LOG = logging.getLogger(__name__)


class PlacementDriverClient:
    """Region metadata source + store-side reporting sink."""

    async def list_regions(self) -> list[Region]:
        raise NotImplementedError

    async def get_store_metas(self) -> list[StoreMeta]:
        return []

    # -- store-side hooks ----------------------------------------------------

    async def report_split(self, parent: Region, child: Region) -> None:
        pass

    async def report_merge(self, source_region_id: int,
                           target_region_id: int) -> None:
        """Lifecycle plane: a completed merge (source sealed, absorbed,
        retired) — no-op for PD-less clients; the static view has no
        merge policy that could have ordered one."""

    async def store_heartbeat(self, meta: StoreMeta,
                              health: str = "") -> None:
        pass

    async def region_heartbeat(self, region: Region, leader: str,
                               metrics: Optional[dict] = None) -> list:
        """Returns PD instructions (e.g. split orders); empty by default."""
        return []

    async def store_heartbeat_batch(
            self, meta: StoreMeta,
            deltas: list[tuple[Region, str, int]],
            full: bool = False, health: str = "",
            heat: Optional[list] = None,
            occupancy: Optional[tuple] = None) -> tuple[list, bool]:
        """Delta-batched reporting: ONE call per interval carrying only
        the CHANGED (region, leader, approximate_keys) rows.  Returns
        (instructions, need_full).  ``health`` is the store's
        self-reported gray-failure level (trailing wire field; "" on
        stores without scoring).  ``heat`` is the noise-gated list of
        (region_id, writes_s, reads_s, bytes_in_s, bytes_out_s) rows
        and ``occupancy`` the (replicas, replicas_quiescent) pair —
        both trailing wire fields of the fleet observability plane.
        Default: decompose into the legacy per-region calls — PD-less /
        legacy clients keep exact semantics while batch-aware clients
        override with one RPC.  need_full is always True here: a legacy
        PD has no delta state and runs its policy (split re-issue,
        leader balancing) off the per-region reports, so every round
        must carry EVERY led region — delta-only reporting would starve
        it, and a failed-over legacy PD leader would stay cold forever
        (it cannot ask for a resync the way the batch protocol can)."""
        meta = StoreMeta(id=meta.id, endpoint=meta.endpoint,
                         regions=[r.copy() for (r, _l, _k) in deltas],
                         zone=meta.zone)
        # legacy decomposition deliberately DROPS health/heat/occupancy:
        # the per-region protocol (and the subclasses that implement
        # it) predates them, and a legacy PD has no drain/heat policy
        # to feed anyway
        await self.store_heartbeat(meta)
        instructions: list = []
        for region, leader, keys in deltas:
            instructions.extend(await self.region_heartbeat(
                region, leader, {"approximate_keys": keys}))
        return instructions, True

    async def cluster_describe(self, top_k: int = 8) -> Optional[dict]:
        """Fleet observability: the PD leader's folded ClusterView as a
        dict (see pd_server.PlacementDriverServer._build_cluster_view).
        None = this client has no PD to ask (PD-less deployments)."""
        return None

    async def describe_metrics(self) -> Optional[str]:
        """Fleet observability: the PD leader's Prometheus text
        (pd_describe_metrics).  None = no PD / pre-observability PD."""
        return None

    async def shutdown(self) -> None:
        pass


class FakePlacementDriverClient(PlacementDriverClient):
    """PD-less mode: the initial region layout is the whole truth; splits
    reported by stores are folded into the static view."""

    def __init__(self, regions: list[Region]):
        self._regions: dict[int, Region] = {r.id: r.copy() for r in regions}

    async def list_regions(self) -> list[Region]:
        return [r.copy() for r in self._regions.values()]

    async def report_split(self, parent: Region, child: Region) -> None:
        self._regions[parent.id] = parent.copy()
        self._regions[child.id] = child.copy()


class RemotePlacementDriverClient(PlacementDriverClient):
    """Talks to the PD server cluster over the shared transport.

    The PD is itself a 1-group raft app (reference:
    ``pd:PlacementDriverServer``); requests go to its leader via the
    pd_* RPC methods (see tpuraft.rheakv.pd_server).
    """

    def __init__(self, transport, pd_endpoints: list[str],
                 timeout_ms: float = 3000):
        self._transport = transport
        self._endpoints = list(pd_endpoints)
        self._timeout_ms = timeout_ms
        self._leader: Optional[str] = None
        # does the PD serve pd_store_heartbeat_batch?  Optimistic until
        # an ENOMETHOD proves otherwise (a pre-delta-batch PD), then the
        # legacy per-region decomposition takes over permanently.
        self._batch_ok = True

    async def _call(self, method: str, request):
        from tpuraft.rpc.transport import RpcError

        rotation = ([self._leader] if self._leader else []) + [
            e for e in self._endpoints if e != self._leader]
        last: Optional[Exception] = None
        next_ep: Optional[str] = None
        # enough attempts to probe every endpoint AND follow a redirect
        # back to one already probed (it may have won the election since)
        for _ in range(2 * len(rotation) + 2):
            ep = next_ep if next_ep is not None else (
                rotation.pop(0) if rotation else None)
            next_ep = None
            if ep is None:
                break
            try:
                resp = await self._transport.call(ep, method, request,
                                                  self._timeout_ms)
            except RpcError as e:
                last = e
                self._leader = None
                continue
            if getattr(resp, "redirect", ""):
                next_ep = resp.redirect
                self._leader = resp.redirect
                continue
            if getattr(resp, "success", True):
                self._leader = ep
                return resp
            last = RuntimeError(getattr(resp, "msg", "pd error"))
            self._leader = None
        raise last if last else RuntimeError("no PD endpoints")

    async def list_regions(self) -> list[Region]:
        from tpuraft.rheakv.pd_messages import ListRegionsRequest

        resp = await self._call("pd_list_regions", ListRegionsRequest())
        return [Region.decode(b) for b in resp.regions]

    async def get_store_metas(self) -> list[StoreMeta]:
        from tpuraft.rheakv.pd_messages import ListStoresRequest

        resp = await self._call("pd_list_stores", ListStoresRequest())
        out = []
        for blob in resp.stores:
            import struct

            from tpuraft.rheakv.pd_messages import decode_store_meta

            sid, ep, zone = decode_store_meta(blob)
            out.append(StoreMeta(id=sid, endpoint=ep, zone=zone))
        return out

    async def report_split(self, parent: Region, child: Region) -> None:
        from tpuraft.rheakv.pd_messages import ReportSplitRequest

        await self._call("pd_report_split", ReportSplitRequest(
            parent=parent.encode(), child=child.encode()))

    async def report_merge(self, source_region_id: int,
                           target_region_id: int) -> None:
        from tpuraft.rheakv.pd_messages import ReportMergeRequest
        from tpuraft.rpc.transport import RpcError, is_no_method

        try:
            await self._call("pd_report_merge", ReportMergeRequest(
                source_region_id=source_region_id,
                target_region_id=target_region_id))
        except RpcError as e:
            if is_no_method(e):
                return  # pre-lifecycle PD (it never orders merges either)
            raise

    async def store_heartbeat(self, meta: StoreMeta,
                              health: str = "") -> None:
        from tpuraft.rheakv.pd_messages import StoreHeartbeatRequest

        await self._call("pd_store_heartbeat", StoreHeartbeatRequest(
            store_id=meta.id, endpoint=meta.endpoint,
            regions=[r.encode() for r in meta.regions],
            zone=meta.zone, health=health))

    async def region_heartbeat(self, region: Region, leader: str,
                               metrics: Optional[dict] = None) -> list:
        from tpuraft.rheakv.pd_messages import (
            Instruction,
            RegionHeartbeatRequest,
        )

        keys = (metrics or {}).get("approximate_keys", 0)
        resp = await self._call("pd_region_heartbeat", RegionHeartbeatRequest(
            region=region.encode(), leader=leader, approximate_keys=keys))
        return [Instruction.decode(b) for b in resp.instructions]

    async def store_heartbeat_batch(
            self, meta: StoreMeta,
            deltas: list[tuple[Region, str, int]],
            full: bool = False, health: str = "",
            heat: Optional[list] = None,
            occupancy: Optional[tuple] = None) -> tuple[list, bool]:
        from tpuraft.rheakv.pd_messages import (
            Instruction,
            StoreHeartbeatBatchRequest,
            encode_region_delta,
        )
        from tpuraft.rpc.transport import RpcError, is_no_method
        from tpuraft.util.heat import encode_heat_rows

        if not self._batch_ok:
            return await super().store_heartbeat_batch(
                meta, deltas, full, health=health)
        replicas, quiescent = occupancy or (0, 0)
        req = StoreHeartbeatBatchRequest(
            store_id=meta.id, endpoint=meta.endpoint,
            deltas=[encode_region_delta(r.encode(), leader, keys)
                    for (r, leader, keys) in deltas],
            full=full, zone=meta.zone, health=health,
            heat=encode_heat_rows(heat or []),
            replicas=replicas, replicas_quiescent=quiescent)
        try:
            resp = await self._call("pd_store_heartbeat_batch", req)
        except RpcError as e:
            if is_no_method(e):
                self._batch_ok = False
                return await super().store_heartbeat_batch(
                    meta, deltas, full, health=health)
            raise
        return ([Instruction.decode(b) for b in resp.instructions],
                bool(getattr(resp, "need_full", False)))

    async def cluster_describe(self, top_k: int = 8) -> Optional[dict]:
        import json

        from tpuraft.rheakv.pd_messages import ClusterDescribeRequest
        from tpuraft.rpc.transport import RpcError, is_no_method

        try:
            resp = await self._call("pd_cluster_describe",
                                    ClusterDescribeRequest(top_k=top_k))
        except RpcError as e:
            if is_no_method(e):
                return None  # pre-observability PD
            raise
        return json.loads(resp.view_json) if resp.view_json else None

    async def describe_metrics(self) -> Optional[str]:
        from tpuraft.rpc.cli_messages import DescribeMetricsRequest
        from tpuraft.rpc.transport import RpcError, is_no_method

        try:
            resp = await self._call("pd_describe_metrics",
                                    DescribeMetricsRequest())
        except RpcError as e:
            if is_no_method(e):
                return None  # pre-observability PD
            raise
        return resp.text
