"""KVStoreStateMachine: applies committed KVOperations to the raw store.

Reference parity: ``rhea:storage/KVStoreStateMachine`` (SURVEY.md §3.2,
§4.5) — batches committed entries, dispatches by op-code to the shared
RawKVStore, sets per-op results on the proposing closure, handles
region snapshots (range-serialized) and RANGE_SPLIT.
"""

from __future__ import annotations

import asyncio
import logging
import struct
from typing import Optional

from tpuraft.core.state_machine import Iterator, StateMachine
from tpuraft.errors import RaftError, Status
from tpuraft.rheakv.kv_operation import KVOp, KVOperation
from tpuraft.rheakv.metadata import Region
from tpuraft.rheakv.raw_store import RawKVStore

LOG = logging.getLogger(__name__)


def range_covers(region: Region, src_start: bytes,
                 src_end: bytes) -> bool:
    """True when ``region``'s range already contains ``[src_start,
    src_end)`` (b"" bounds are -inf/+inf sentinels).  Regions tile the
    keyspace disjointly, so containment of another region's range can
    only mean "absorbed before" — this is the idempotency test both
    the absorb apply and the PD's merge bookkeeping rely on."""
    lo_ok = (region.start_key == b"" if src_start == b""
             else region.start_key == b"" or region.start_key <= src_start)
    hi_ok = (region.end_key == b"" if src_end == b""
             else region.end_key == b"" or src_end <= region.end_key)
    return lo_ok and hi_ok


def extend_region_over(region: Region, src_start: bytes,
                       src_end: bytes) -> None:
    """Extend ``region``'s keyspace over an ADJACENT absorbed range and
    bump its epoch version — the deterministic metadata half of a
    MERGE_ABSORB apply (every target replica runs this with identical
    inputs).  Raises on a non-adjacent range: a PD that proposed one
    has a policy bug, and silently absorbing would tear the keyspace
    tiling invariant.

    Idempotent: a range the region ALREADY covers (a resumed merge
    re-absorbing after a source-leader retry, or log replay over a
    snapshot that post-dates the absorb) is a no-op (``range_covers``)."""
    if range_covers(region, src_start, src_end):
        return
    if src_end != b"" and src_end == region.start_key:
        region.start_key = src_start          # source sat to our LEFT
    elif region.end_key != b"" and region.end_key == src_start:
        region.end_key = src_end              # source sat to our RIGHT
    else:
        raise RuntimeError(
            f"absorb range [{src_start!r}, {src_end!r}) is not adjacent "
            f"to region {region.id} [{region.start_key!r}, "
            f"{region.end_key!r})")
    region.epoch.version += 1


class KVClosure:
    """Proposal completion carrying an op result back to the proposer
    (reference: ``rhea:storage/KVStoreClosure#setData``).

    Thread-safe against worker-lane apply: when the FSM fires it from
    the store's apply lane, the resolution hops back to the proposer's
    loop via ``call_soon_threadsafe``.  ``_fired`` (set before the hop)
    makes the first caller win — the FSMCaller's loop-side
    auto-complete must not override a lane-fired error status whose
    delivery is still in flight."""

    def __init__(self, fut):
        self._fut = fut
        self.result = None
        self._fired = False

    def __call__(self, status: Status) -> None:
        if self._fired:
            return
        self._fired = True
        fut = self._fut
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is fut.get_loop():
            if not fut.done():
                fut.set_result((status, self.result))
        else:
            fut.get_loop().call_soon_threadsafe(self._deliver, status)

    def _deliver(self, status: Status) -> None:
        if not self._fut.done():
            self._fut.set_result((status, self.result))


class KVStoreStateMachine(StateMachine):
    # write ops the apply coalescer folds into one mixed store write
    # (all return True and only touch the data namespace)
    _RUN_OPS = frozenset(
        (KVOp.PUT, KVOp.DELETE, KVOp.PUT_LIST, KVOp.DELETE_LIST))
    # ops a SEALED region still applies: the merge choreography itself
    # plus log-replicated reads (the data keeps serving until the
    # target's absorb commits and this group retires)
    _SEALED_OK = frozenset((KVOp.MERGE_SEAL, KVOp.MERGE_COMMIT,
                            KVOp.GET, KVOp.MULTI_GET, KVOp.CONTAINS_KEY))

    def __init__(self, region: Region, store: RawKVStore,
                 store_engine=None, coalesce_applies: bool = True) -> None:
        self.region = region
        self.store = store
        self.store_engine = store_engine  # for RANGE_SPLIT
        self.leader_term = -1
        # apply worker lane (StoreEngineOptions.apply_lane): when set,
        # the lane thread OWNS the raw store — apply_sync runs there,
        # and snapshot serialization below is submitted through it
        # instead of touching the store from the loop
        self.lane = None
        # coalesced-apply knob + counters (StoreEngineOptions.fsm_coalesce):
        # consecutive PUT/DELETE(-list) entries flush as ONE native batch
        # write instead of one store call per op
        self.coalesce_applies = coalesce_applies
        self.coalesced_flushes = 0   # flushes that merged more than one row
        self.coalesced_ops = 0       # rows that rode a merged flush
        # merge barrier (lifecycle plane): >= 0 once a MERGE_SEAL entry
        # applied, naming the absorbing region.  Derived ONLY from the
        # applied log (+ snapshot), so every replica agrees; writes
        # sequenced after the seal are deterministically rejected
        # (ESTATEMACHINE) — the barrier IS the merge's linearization
        # point in the source group's log
        self.sealed_into = -1

    # -- apply ---------------------------------------------------------------

    def _run_rows(self, op: KVOperation
                  ) -> list[tuple[bytes, Optional[bytes]]]:
        code = op.op
        if code == KVOp.PUT:
            return [(op.key, op.value)]
        if code == KVOp.DELETE:
            return [(op.key, None)]
        if code == KVOp.PUT_LIST:
            return list(KVOperation.unpack_kv_list(op.value))
        return [(k, None) for k in KVOperation.unpack_key_list(op.value)]

    def _flush_run(self, rows: list, dones: list) -> None:
        try:
            self.store.apply_write_batch(rows)
            if len(rows) > 1:
                self.coalesced_flushes += 1
                self.coalesced_ops += len(rows)
            st = Status.OK()
        except Exception as e:  # noqa: BLE001 — run-level failure, not fatal
            LOG.exception("region %d coalesced apply (%d rows) failed",
                          self.region.id, len(rows))
            st = Status.error(RaftError.ESTATEMACHINE, str(e))
        for done, closure in dones:
            if closure is not None and st.is_ok():
                closure.result = True
            if done is not None:
                done(st)
        rows.clear()
        dones.clear()

    async def on_apply(self, it: Iterator) -> None:
        self.on_lane_applied(self.apply_sync(it))

    def on_lane_applied(self, applied_ops: int) -> None:
        """Post-apply bookkeeping that must stay on the loop (the heat
        tracker is loop-confined): the FSMCaller calls this after a
        lane-submitted apply_sync returns; the loop path above calls it
        inline."""
        # per-region heat (fleet observability): the applied lane is the
        # replication-side load — followers see it for regions they
        # never serve, giving the store a full local picture; the PD
        # only ever reads the leaders' serving rates
        heat = getattr(self.store_engine, "heat", None)
        if heat is not None and applied_ops:
            heat.note_applied(self.region.id, applied_ops)

    def apply_sync(self, it: Iterator) -> int:
        """The apply body, synchronous — runnable on the loop (via
        on_apply) or on the store's apply worker lane (FSMCaller submits
        it when StoreEngineOptions.apply_lane is on).  Returns the
        applied op count for on_lane_applied."""
        run_rows: list = []
        run_dones: list = []   # (done, closure) per coalesced entry
        applied_ops = 0        # heat telemetry: replication-side rate
        while it.valid():
            applied_ops += 1
            op = KVOperation.decode(it.data())
            done = it.done()
            closure = done if isinstance(done, KVClosure) else None
            if self.coalesce_applies and op.op in self._RUN_OPS \
                    and self.sealed_into < 0:
                run_rows.extend(self._run_rows(op))
                run_dones.append((done, closure))
                it.next()
                continue
            if run_dones:
                self._flush_run(run_rows, run_dones)
            try:
                result = self._dispatch(op)
                if closure is not None:
                    closure.result = result
                if done is not None:
                    done(Status.OK())
            except Exception as e:  # noqa: BLE001 — op-level failure, not fatal
                LOG.exception("region %d apply op %s failed",
                              self.region.id, op.op)
                if done is not None:
                    done(Status.error(RaftError.ESTATEMACHINE, str(e)))
            it.next()
        if run_dones:
            self._flush_run(run_rows, run_dones)
        return applied_ops

    def _dispatch(self, op: KVOperation):
        s = self.store
        code = op.op
        if self.sealed_into >= 0 and code not in self._SEALED_OK:
            # deterministic on every replica: the seal entry precedes
            # this op in the SAME log, so all replicas reject it — a
            # write that raced the seal and lost reroutes (via the
            # client's bounce path) into the absorbing region
            raise RuntimeError(
                f"region sealed into {self.sealed_into} (merging)")
        if code == KVOp.PUT:
            s.put(op.key, op.value)
            return True
        if code == KVOp.PUT_IF_ABSENT:
            return s.put_if_absent(op.key, op.value)
        if code == KVOp.DELETE:
            s.delete(op.key)
            return True
        if code == KVOp.COMPARE_PUT:
            return s.compare_and_put(op.key, op.aux, op.value)
        if code == KVOp.DELETE_RANGE:
            s.delete_range(op.key, op.value)
            return True
        if code == KVOp.GET_SEQUENCE:
            (step,) = struct.unpack("<q", op.aux)
            seq = s.get_sequence(op.key, step)
            return (seq.start, seq.end)
        if code == KVOp.RESET_SEQUENCE:
            s.reset_sequence(op.key)
            return True
        if code == KVOp.MERGE:
            s.merge(op.key, op.value)
            return True
        if code == KVOp.PUT_LIST:
            s.put_list(KVOperation.unpack_kv_list(op.value))
            return True
        if code == KVOp.DELETE_LIST:
            s.delete_list(KVOperation.unpack_key_list(op.value))
            return True
        if code == KVOp.GET_AND_PUT:
            return s.get_and_put(op.key, op.value)
        if code == KVOp.KEY_LOCK:
            lease_ms, keep = struct.unpack("<qB", op.aux)
            return s.try_lock_with(op.key, op.value, lease_ms, bool(keep))
        if code == KVOp.KEY_LOCK_RELEASE:
            return s.release_lock(op.key, op.value)
        if code == KVOp.MULTI:
            return self._dispatch_multi(KVOperation.unpack_multi(op.value))
        if code == KVOp.RANGE_SPLIT:
            (new_region_id,) = struct.unpack("<q", op.aux)
            if self.store_engine is None:
                raise RuntimeError("split requires a store engine")
            try:
                asyncio.get_running_loop()
            except RuntimeError:
                # lane apply: do_split mutates loop-confined StoreEngine
                # state (region table, heat rows, the new engine's boot
                # task) — hop it back to the engine's loop.  The range
                # narrowing lands a beat later; serving-side range
                # checks re-validate per request, so the window only
                # delays the client's epoch refresh.
                self.store_engine.loop_call_threadsafe(
                    self.store_engine.do_split,
                    self.region.id, new_region_id, op.key)
                return True
            self.store_engine.do_split(self.region.id, new_region_id, op.key)
            return True
        if code == KVOp.MERGE_SEAL:
            (target_id,) = struct.unpack("<q", op.aux)
            # idempotent: a re-proposed seal (leader retry) re-applies
            # to the same state
            self.sealed_into = target_id
            return True
        if code == KVOp.MERGE_ABSORB:
            src_id, src_start, src_end = \
                KVOperation.unpack_merge_absorb(op.aux)
            # containment FIRST: a duplicate absorb (the PD re-issuing
            # the pending pair after a lost ack, racing the first
            # absorb's completion) carries the sealed source's blob —
            # loading it again would roll back writes this region
            # accepted in its extended range since the first absorb
            # (lost updates).  Covered range == absorbed before; skip
            # the data load AND the (no-op) extension.
            if range_covers(self.region, src_start, src_end):
                return True
            # data first, in the store-owning context (idempotent
            # overwrite: on a shared per-store raw store the source's
            # rows are already physically present)
            if op.value:
                s.load_serialized(op.value)
            self._absorb_meta(src_id, src_start, src_end)
            return True
        if code == KVOp.MERGE_COMMIT:
            (target_id,) = struct.unpack("<q", op.aux)
            if self.store_engine is not None:
                try:
                    asyncio.get_running_loop()
                except RuntimeError:
                    # lane apply: retirement mutates loop-confined
                    # StoreEngine state (region table, heat rows, the
                    # engine shutdown task) — hop to the engine's loop
                    self.store_engine.loop_call_threadsafe(
                        self.store_engine.do_retire,
                        self.region.id, target_id)
                    return True
                self.store_engine.do_retire(self.region.id, target_id)
            return True
        if code == KVOp.GET:  # linearizable-via-log read
            return s.get(op.key)
        if code == KVOp.MULTI_GET:
            keys = KVOperation.unpack_key_list(op.value)
            got = s.multi_get(keys)
            return [(k, got[k]) for k in keys]
        if code == KVOp.CONTAINS_KEY:
            return s.contains_key(op.key)
        raise ValueError(f"unknown KV op {code}")

    def _dispatch_multi(self, ops: list[KVOperation]
                        ) -> list[tuple[int, str, object]]:
        """Apply a MULTI entry's sub-ops in order with PER-OP outcomes
        ``(code, msg, result)`` — a sub-op failure fails only its item,
        never the whole entry (the batch handler maps each outcome back
        to its kv_command_batch item).  Consecutive PUT/DELETE(-list)
        sub-ops coalesce into one store write, same as entry-level runs."""
        outs: list = [None] * len(ops)
        i, n = 0, len(ops)
        while i < n:
            if self.coalesce_applies and ops[i].op in self._RUN_OPS \
                    and self.sealed_into < 0:
                j = i
                rows: list = []
                while j < n and ops[j].op in self._RUN_OPS:
                    rows.extend(self._run_rows(ops[j]))
                    j += 1
                try:
                    self.store.apply_write_batch(rows)
                    if len(rows) > 1:
                        self.coalesced_flushes += 1
                        self.coalesced_ops += len(rows)
                    out = (0, "", True)
                except Exception as e:  # noqa: BLE001
                    LOG.exception("region %d multi-apply run (%d rows) failed",
                                  self.region.id, len(rows))
                    out = (int(RaftError.ESTATEMACHINE), str(e), None)
                for k in range(i, j):
                    outs[k] = out
                i = j
                continue
            try:
                outs[i] = (0, "", self._dispatch(ops[i]))
            except Exception as e:  # noqa: BLE001
                LOG.exception("region %d multi-apply op %s failed",
                              self.region.id, ops[i].op)
                outs[i] = (int(RaftError.ESTATEMACHINE), str(e), None)
            i += 1
        return outs

    def _absorb_meta(self, src_id: int, src_start: bytes,
                     src_end: bytes) -> None:
        """Metadata half of a MERGE_ABSORB apply: range extension +
        epoch bump (+ store-engine bookkeeping), hopped to the engine's
        loop when applying on the store's worker lane — same contract
        as the RANGE_SPLIT arm."""
        if self.store_engine is None:
            extend_region_over(self.region, src_start, src_end)
            return
        try:
            asyncio.get_running_loop()
        except RuntimeError:
            self.store_engine.loop_call_threadsafe(
                self.store_engine.do_absorb,
                self.region.id, src_id, src_start, src_end)
            return
        self.store_engine.do_absorb(self.region.id, src_id,
                                    src_start, src_end)

    # -- leadership ----------------------------------------------------------

    async def on_leader_start(self, term: int) -> None:
        self.leader_term = term
        if self.store_engine is not None:
            self.store_engine.on_region_leader_start(self.region.id, term)

    async def on_leader_stop(self, status: Status) -> None:
        self.leader_term = -1
        if self.store_engine is not None:
            self.store_engine.on_region_leader_stop(self.region.id)

    async def on_configuration_committed(self, conf) -> None:
        """Committed conf entries update the region's replica roster and
        bump conf_ver — every replica applies the same entries, so the
        roster/epoch stay deterministic fleet-wide.  Before the
        lifecycle plane region.peers never tracked joint-consensus
        changes, so a MOVEd region kept advertising its old store
        forever.  No-op re-commits (a new leader re-committing the
        stable conf) are skipped so restart replay can't drift conf_ver
        across replicas."""
        w = set(conf.witnesses)
        toks = [f"{p}/witness" if p in w else str(p)
                for p in sorted(conf.peers)]
        toks += [f"{p}/learner" for p in sorted(conf.learners)]
        if not toks or set(toks) == set(self.region.peers):
            return
        self.region.peers = toks
        self.region.epoch.conf_ver += 1
        if self.store_engine is not None:
            self.store_engine.on_region_conf_changed(self.region.id)

    # -- snapshot ------------------------------------------------------------

    async def on_snapshot_save(self, writer, done) -> None:
        try:
            # lane mode: the lane thread owns the store — OTHER regions'
            # applies run there concurrently with this region's save, so
            # the range serialization must ride the lane queue too
            if self.lane is not None:
                blob = await self.lane.submit(
                    self.store.serialize_range,
                    self.region.start_key, self.region.end_key)
            else:
                blob = self.store.serialize_range(self.region.start_key,
                                                  self.region.end_key)
            writer.write_file("kv_data", blob)
            writer.write_file("region_meta", self.region.encode())
            if self.sealed_into >= 0:
                # a replica installing this snapshot must come up SEALED
                # (the seal entry may sit below the snapshot index) —
                # trailing file, absent on pre-lifecycle snapshots
                writer.write_file("merge_state",
                                  struct.pack("<q", self.sealed_into))
            done(Status.OK())
        except Exception as e:  # noqa: BLE001
            done(Status.error(RaftError.EIO, f"kv snapshot save: {e}"))

    async def on_snapshot_load(self, reader) -> bool:
        blob = reader.read_file("kv_data")
        if blob is None:
            return False
        meta = reader.read_file("region_meta")
        if meta is not None:
            saved = Region.decode(meta)
            # adopt the snapshot's view of the range/epoch (it may post-date
            # a split that this lagging replica never applied)
            self.region.start_key = saved.start_key
            self.region.end_key = saved.end_key
            self.region.epoch = saved.epoch
        sealed = reader.read_file("merge_state")
        self.sealed_into = struct.unpack("<q", sealed)[0] \
            if sealed is not None else -1
        # exact state reset of our slice (data + sequences + locks), then
        # load — merging would leave post-snapshot keys behind and make
        # log replay after restart non-deterministic across replicas
        if self.lane is not None:
            await self.lane.submit(self._load_sync, blob)
        else:
            self._load_sync(blob)
        return True

    def _load_sync(self, blob: bytes) -> None:
        self.store.reset_range(self.region.start_key, self.region.end_key)
        self.store.load_serialized(blob)

    async def on_error(self, status: Status) -> None:
        LOG.error("region %d FSM error: %s", self.region.id, status)
