"""RegionRouteTable: client-side key-range -> region routing.

Reference parity: ``rhea:RegionRouteTable`` (SURVEY.md §3.2 "Client")
— a sorted range map from region start keys to Region metadata, patched
from INVALID_REGION_EPOCH responses and PD refreshes; plus range → list
of covering regions for multi-region scans.
"""

from __future__ import annotations

import bisect
from typing import Optional

from tpuraft.rheakv.metadata import Region


class RegionRouteTable:
    def __init__(self) -> None:
        self._starts: list[bytes] = []     # sorted region start keys
        self._regions: dict[bytes, Region] = {}
        self._by_id: dict[int, bytes] = {}  # region id -> start key

    def reset(self, regions: list[Region]) -> None:
        self._starts = []
        self._regions = {}
        self._by_id = {}
        for r in regions:
            self.add_or_update(r)

    def add_or_update(self, region: Region) -> None:
        r = region.copy()
        # never regress: a same-id entry with a fresher epoch wins (a
        # lagging replica's ERR_INVALID_EPOCH meta must not overwrite
        # the post-split view — spread reads hit lagging replicas often)
        for old in self._regions.values():
            if old.id == r.id and \
                    (old.epoch.version, old.epoch.conf_ver) > \
                    (r.epoch.version, r.epoch.conf_ver):
                return
        # drop any stale entry for the same region id under a different start
        for start, old in list(self._regions.items()):
            if old.id == r.id and start != r.start_key:
                self._remove_start(start)
        cur = self._regions.get(r.start_key)
        if cur is not None and cur.id != r.id \
                and (cur.epoch.version > r.epoch.version):
            return  # keep the fresher view
        if cur is not None and cur.id != r.id \
                and self._by_id.get(cur.id) == r.start_key:
            del self._by_id[cur.id]   # displaced by a different region
        if r.start_key not in self._regions:
            bisect.insort(self._starts, r.start_key)
        self._regions[r.start_key] = r
        self._by_id[r.id] = r.start_key

    def _remove_start(self, start: bytes) -> None:
        old = self._regions.get(start)
        if old is not None:
            del self._regions[start]
            if self._by_id.get(old.id) == start:
                del self._by_id[old.id]
            i = bisect.bisect_left(self._starts, start)
            if i < len(self._starts) and self._starts[i] == start:
                self._starts.pop(i)

    def remove_region(self, region_id: int) -> None:
        for start, r in list(self._regions.items()):
            if r.id == region_id:
                self._remove_start(start)

    def find_region_by_key(self, key: bytes) -> Optional[Region]:
        """Rightmost region whose start <= key, if key is inside it."""
        i = bisect.bisect_right(self._starts, key) - 1
        if i < 0:
            return None
        r = self._regions[self._starts[i]]
        return r if r.contains_key(key) else None

    def find_region_by_id(self, region_id: int) -> Optional[Region]:
        """O(1) via the id index — this sits on the client's per-round
        re-shard path, where a linear scan is O(regions) per group per
        attempt at density."""
        start = self._by_id.get(region_id)
        if start is None:
            return None
        r = self._regions.get(start)
        return r if r is not None and r.id == region_id else None

    def find_regions_by_range(self, start: bytes, end: bytes) -> list[Region]:
        """All regions intersecting [start, end); ordered by start key."""
        out = []
        i = max(0, bisect.bisect_right(self._starts, start) - 1)
        for s in self._starts[i:]:
            r = self._regions[s]
            if end and r.start_key >= end:
                break
            if r.end_key and r.end_key <= start:
                continue
            out.append(r)
        return out

    def list_regions(self) -> list[Region]:
        return [self._regions[s] for s in self._starts]

    def is_empty(self) -> bool:
        return not self._starts
