"""Keyspace tiling invariants: the region lifecycle oracle.

The multi-raft KV's load-bearing metadata invariant is that the region
set TILES the keyspace: sorted by start key, the regions cover
[b"", +inf) with no gaps and no overlaps (b"" is the -inf/+inf sentinel
on both bounds).  Splits preserve it by construction (parent shrinks,
child takes the tail) and merges must too (the target extends exactly
over the absorbed source) — a lifecycle bug shows up here first, as a
hole (lost keyspace: keys nobody serves) or an overlap (double
ownership: two groups both accept writes for one key).

Lives under ``tpuraft/`` rather than ``tests/`` so the chaos soak's
LIVE invariant check (examples/soak.py, which can't import tests/)
shares ONE implementation with the tests/oracle.py re-export — the same
arrangement as util/quorum.py and the membership oracle.
"""

from __future__ import annotations

from typing import Iterable


def coverage_errors(regions: Iterable) -> list[str]:
    """Check a region set tiles the keyspace; returns human-readable
    violations ([] = invariant holds).  Accepts any iterable of objects
    with ``id``/``start_key``/``end_key`` (Region or a stand-in)."""
    rows = sorted(regions, key=lambda r: r.start_key)
    errors: list[str] = []
    if not rows:
        return ["no regions: keyspace entirely uncovered"]
    seen: dict[int, object] = {}
    for r in rows:
        if r.id in seen:
            errors.append(f"region id {r.id} appears twice")
        seen[r.id] = r
    if rows[0].start_key != b"":
        errors.append(
            f"keyspace hole before region {rows[0].id}: "
            f"[b'', {rows[0].start_key!r}) is uncovered")
    for prev, cur in zip(rows, rows[1:]):
        if prev.end_key == b"":
            # an unbounded end anywhere but the last slot overlaps
            # everything after it
            errors.append(
                f"region {prev.id} is unbounded but region {cur.id} "
                f"starts at {cur.start_key!r} inside it")
        elif prev.end_key < cur.start_key:
            errors.append(
                f"keyspace hole [{prev.end_key!r}, {cur.start_key!r}) "
                f"between regions {prev.id} and {cur.id}")
        elif prev.end_key > cur.start_key:
            errors.append(
                f"regions {prev.id} and {cur.id} overlap on "
                f"[{cur.start_key!r}, {prev.end_key!r})")
    if rows[-1].end_key != b"":
        errors.append(
            f"keyspace hole after region {rows[-1].id}: "
            f"[{rows[-1].end_key!r}, +inf) is uncovered")
    return errors


def assert_covers(regions: Iterable, context: str = "") -> None:
    """Raise AssertionError with every violation when the region set
    does not tile the keyspace."""
    errors = coverage_errors(regions)
    assert not errors, (
        (f"{context}: " if context else "") + "; ".join(errors))
