"""Region / store / cluster metadata.

Reference parity: ``rhea:metadata/*`` — ``Region`` (id, key range,
epoch, peers), ``RegionEpoch`` (confVer bumped on membership change,
version bumped on split/merge), ``Store``, ``Cluster`` (SURVEY.md §3.2
"PD client" row).  Keys are ``bytes``; an empty ``start_key`` means -inf
and an empty ``end_key`` means +inf.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Optional


@dataclass(order=True)
class RegionEpoch:
    """Staleness fence for routing: requests carry the client's view; the
    server rejects mismatches with INVALID_REGION_EPOCH."""

    conf_ver: int = 1
    version: int = 1

    def copy(self) -> "RegionEpoch":
        return RegionEpoch(self.conf_ver, self.version)


@dataclass
class Region:
    id: int = 0
    start_key: bytes = b""  # inclusive; b"" = -inf
    end_key: bytes = b""    # exclusive; b"" = +inf
    epoch: RegionEpoch = field(default_factory=RegionEpoch)
    peers: list[str] = field(default_factory=list)  # PeerId strings

    def contains_key(self, key: bytes) -> bool:
        if self.start_key and key < self.start_key:
            return False
        if self.end_key and key >= self.end_key:
            return False
        return True

    def contains_range(self, start: bytes, end: bytes) -> bool:
        """True if [start, end) falls entirely inside this region."""
        if self.start_key and start < self.start_key:
            return False
        if self.end_key:
            if not end or end > self.end_key:
                return False
        return True

    def copy(self) -> "Region":
        return Region(self.id, self.start_key, self.end_key,
                      self.epoch.copy(), list(self.peers))

    def encode(self) -> bytes:
        out = bytearray(struct.pack("<qqq", self.id, self.epoch.conf_ver,
                                    self.epoch.version))
        for b in (self.start_key, self.end_key):
            out += struct.pack("<I", len(b)) + b
        out += struct.pack("<H", len(self.peers))
        for p in self.peers:
            pb = p.encode()
            out += struct.pack("<H", len(pb)) + pb
        return bytes(out)

    @staticmethod
    def decode(buf: bytes | memoryview) -> "Region":
        buf = memoryview(buf)
        rid, conf_ver, version = struct.unpack_from("<qqq", buf, 0)
        off = 24
        keys = []
        for _ in range(2):
            (n,) = struct.unpack_from("<I", buf, off)
            off += 4
            keys.append(bytes(buf[off:off + n]))
            off += n
        (np,) = struct.unpack_from("<H", buf, off)
        off += 2
        peers = []
        for _ in range(np):
            (n,) = struct.unpack_from("<H", buf, off)
            off += 2
            peers.append(bytes(buf[off:off + n]).decode())
            off += n
        return Region(rid, keys[0], keys[1], RegionEpoch(conf_ver, version),
                      peers)

    def __str__(self) -> str:
        return (f"Region[{self.id} [{self.start_key!r}, {self.end_key!r}) "
                f"epoch={self.epoch.conf_ver}.{self.epoch.version}]")


@dataclass
class StoreMeta:
    """One storage process: endpoint + the regions it hosts.

    ``zone`` is the store's failure-domain label (geo deployment):
    the PD spreads leaders across zones and operators place witnesses
    by it.  Empty = unlabeled (single-zone legacy deployments)."""

    id: int = 0
    endpoint: str = ""
    regions: list[Region] = field(default_factory=list)
    zone: str = ""


@dataclass
class ClusterMeta:
    id: int = 0
    name: str = "rheakv"
    stores: list[StoreMeta] = field(default_factory=list)


def region_group_id(cluster_name: str, region_id: int) -> str:
    """groupId convention for a region's raft group (reference:
    ``rhea:JRaftHelper#getJRaftGroupId``: ``clusterName + '-' + regionId``)."""
    return f"{cluster_name}--{region_id}"
