"""RheaKVStore: the user-facing distributed KV client.

Reference parity: ``rhea:client/DefaultRheaKVStore`` (SURVEY.md §3.2
"Client", §4.5): key → region lookup via RegionRouteTable, request to
the region leader's store, bounded retry with epoch-stale route patching
and not-leader failover; multi-region scan/delete_range fan-out; the
distributed lock and sequence APIs.

All methods are async (the reference's closure style); the reference's
blocking ``b*`` variants are just ``asyncio.run``-style waits in Python.
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
import uuid
from dataclasses import dataclass
from typing import Optional

from tpuraft.errors import RaftError, Status
from tpuraft.rheakv.kv_operation import KVOp, KVOperation
from tpuraft.rheakv.kv_service import (
    ERR_INVALID_EPOCH,
    ERR_KEY_OUT_OF_RANGE,
    ERR_NO_REGION,
    ERR_STORE_BUSY,
    KVCommandBatchRequest,
    KVCommandRequest,
    ListRegionsOnStoreRequest,
    decode_batch_reply,
    decode_result,
    encode_batch_item,
    scan_op,
)
from tpuraft.rheakv.metadata import Region
from tpuraft.rheakv.pd_client import PlacementDriverClient
from tpuraft.rheakv.raw_store import Sequence
from tpuraft.rheakv.region_route_table import RegionRouteTable
from tpuraft.rpc.transport import RpcError, is_no_method
from tpuraft.util.trace import TRACER, pack_ctx, wire_ctx

LOG = logging.getLogger(__name__)

# ops any replica can serve linearizably (readIndex barrier + local read)
_READONLY_OPS = {KVOp.GET, KVOp.MULTI_GET, KVOp.CONTAINS_KEY, KVOp.SCAN}

# not leader / electing / readIndex round timed out under load: worth
# another attempt against a different store.  ERR_STORE_BUSY is the
# gray-failure SHED bounce (a SICK store failing fast instead of
# queueing) — retryable, and by the jittered backoff later leadership
# has usually evacuated to a healthy store.
_RETRYABLE_CODES = {
    int(RaftError.EPERM), int(RaftError.EBUSY), int(RaftError.EAGAIN),
    int(RaftError.ERAFTTIMEDOUT), int(RaftError.ETIMEDOUT),
    ERR_STORE_BUSY,
}


class RheaKVError(Exception):
    def __init__(self, status: Status):
        super().__init__(str(status))
        self.status = status


@dataclass
class BatchingOptions:
    """Client-side op coalescing (reference: ``rhea:options/
    BatchingOptions`` + the ``Batching`` ring buffers in
    DefaultRheaKVStore).  The asyncio analog of the reference's
    disruptor consumers: concurrent ``put``/``get`` calls issued within
    the same event-loop iteration are drained into one ``put_list`` /
    ``multi_get`` per region instead of one RPC each."""

    enabled: bool = False
    max_write_batch: int = 128
    max_read_batch: int = 128
    # cap on (region, op) items per store-grouped ``kv_command_batch``
    # RPC (the serving-plane analog of the send plane's
    # MAX_ITEMS_PER_RPC: bounds the receiver's per-RPC fan-out burst)
    max_store_batch: int = 1024
    # concurrent kv_command_batch RPCs per store: ops are independent
    # (no per-region ordering to preserve), so a window stalled on one
    # slow region's quorum must not idle the whole store pipe — same
    # reasoning as the send plane's multi-lane vote dispatch
    max_store_inflight: int = 4
    # optional WorkerLane (tpuraft.core.lanes): batch-item encode moves
    # off the event loop onto the lane thread, one hop per send window —
    # the client-side half of the store's apply lane (a hot client loop
    # spends a measurable slice purely serializing op blobs).  Items
    # whose encode fails are failed INDIVIDUALLY at send time, same
    # attribution contract as the inline encode path.
    encode_lane: Optional[object] = None


# graftcheck: loop-confined
class _Batcher:
    """Coalesces items queued in one loop iteration into chunked flushes.

    Rounds fire concurrently (one per loop iteration): the per-STORE
    windowing that adapts batch size to the serving rate lives in
    :class:`_StoreSender`, which every round's flush submits through."""

    def __init__(self, max_batch: int, flush_fn):
        self._max = max_batch
        self._flush_fn = flush_fn
        self._pending: list = []  # (item, future)
        self._scheduled = False

    def add(self, item) -> asyncio.Future:
        fut = asyncio.get_running_loop().create_future()
        self._pending.append((item, fut))
        if not self._scheduled:
            self._scheduled = True
            asyncio.ensure_future(self._drain())
        return fut

    async def _drain(self) -> None:
        # one microtask hop: everything enqueued by tasks runnable in
        # this loop iteration joins the batch
        await asyncio.sleep(0)
        self._scheduled = False
        batch, self._pending = self._pending, []

        async def flush(chunk):
            try:
                await self._flush_fn(chunk)
            except Exception as e:  # noqa: BLE001 — fail the whole chunk
                for _, fut in chunk:
                    if not fut.done():
                        fut.set_exception(e)

        # the common round fits one chunk: await it directly instead of
        # paying a gather + task wrap per drain (per-op task-fan thinning
        # — at w256 this is ~one task per loop iteration saved per
        # batcher, and the drain itself is already a task)
        if len(batch) <= self._max:
            await flush(batch)
            return
        # chunks are independent: flush them concurrently
        await asyncio.gather(*[
            flush(batch[i:i + self._max])
            for i in range(0, len(batch), self._max)])


# graftcheck: loop-confined
class _StoreSender:
    """One batched ``kv_command_batch`` sender per store endpoint — the
    serving-plane analog of the send plane's EndpointSender: a bounded
    window of RPC lanes per store (``max_store_inflight``), and
    everything submitted while the window is full rides the next lane
    together.  Batch size adapts to the store's service rate, a slow
    region on one store never convoys items bound for another, and
    items resolve INDIVIDUALLY (future per item) the moment their RPC
    returns."""

    def __init__(self, client: "RheaKVStore", endpoint: str):
        self._client = client
        self.endpoint = endpoint
        self._q: list = []   # (region, peer_str, op, fut)
        self._task: Optional[asyncio.Task] = None
        self._lanes: set = set()   # in-flight send tasks
        # nudges the drain out of its lane-completion wait when a NEW
        # item arrives with lane slots free: without it, items submitted
        # while the drain parks on FIRST_COMPLETED convoy behind the
        # slowest in-flight RPC even though slots are open — the same
        # stalled-wait shape ReadConfirmBatcher._drain fixed in the
        # gray-failure round (write-path latency under load dropped
        # ~25% when this landed)
        self._arrival = asyncio.Event()

    def submit(self, region: Region, peer: str, op: KVOperation,
               spread: bool = False) -> asyncio.Future:
        """``spread=True`` marks a read routed OFF the leader (read_from
        follower/learner fan-out): its outcome must not touch the
        leader cache — a follower serving (or bouncing) a read says
        nothing about who leads."""
        fut = asyncio.get_running_loop().create_future()
        # encode HERE, not in the send path: a malformed op (bad key
        # type) must fail its OWN caller, never poison the unrelated
        # items sharing its lane (the same invariant RaftRawKVStore.
        # apply holds one layer down).  With an encode_lane configured
        # the serialize moves to the lane thread at send time — _send
        # keeps the same per-item attribution there.
        if self._client._batch_opts.encode_lane is None:
            try:
                blob = encode_batch_item(region.id, region.epoch.conf_ver,
                                         region.epoch.version, op.encode())
            except Exception as e:  # noqa: BLE001
                fut.set_result(RheaKVError(Status.error(
                    RaftError.EINVAL, f"malformed op: {e!r}")))
                return fut
        else:
            blob = None
        # trace plane: only a SAMPLED op's context rides the row (and
        # the wire) — unsampled slow-candidates keep the serving path
        # untouched (wire_ctx masks them to 0)
        tid = wire_ctx(op.trace_id)
        self._q.append((region, peer, blob, fut, spread, tid,
                        time.perf_counter() if tid else 0.0, op))
        self._arrival.set()
        if self._task is None or self._task.done():
            self._task = asyncio.ensure_future(self._drain())
        return fut

    async def _drain(self) -> None:
        # microtask hop so a burst submitted in this loop iteration
        # rides one RPC; then windowed drain — up to max_store_inflight
        # lanes in flight, each lane stop-and-wait over its own batch
        await asyncio.sleep(0)
        cap = max(1, self._client._batch_opts.max_store_batch)
        lanes = max(1, self._client._batch_opts.max_store_inflight)
        while self._q or self._lanes:
            while self._q and len(self._lanes) < lanes:
                batch = self._q[:cap]
                del self._q[:len(batch)]
                t = asyncio.ensure_future(self._send_safe(batch))
                self._lanes.add(t)
                t.add_done_callback(self._lanes.discard)
            if self._lanes:
                # wake on a lane completing OR a new item arriving:
                # with lane slots free a fresh item must ship NOW, not
                # convoy behind the slowest in-flight RPC
                self._arrival.clear()
                arrival = asyncio.ensure_future(self._arrival.wait())
                try:
                    await asyncio.wait(set(self._lanes) | {arrival},
                                       return_when=asyncio.FIRST_COMPLETED)
                finally:
                    arrival.cancel()

    async def _send_safe(self, batch: list) -> None:
        try:
            await self._send(batch)
        except Exception as e:  # noqa: BLE001 — fail THIS batch only
            st = Status.error(RaftError.EINTERNAL, f"batch send: {e!r}")
            for row in batch:
                if not row[3].done():
                    row[3].set_result(RheaKVError(st))

    async def _send(self, batch: list) -> None:
        client = self._client
        lane = client._batch_opts.encode_lane
        if lane is not None:
            # one lane hop serializes the whole window off-loop; a row
            # whose encode raises fails its OWN future here (same
            # attribution the inline path gives at submit time) and is
            # dropped from the RPC
            blobs = await lane.submit(_encode_rows, batch)
            keep = []
            for row, blob in zip(batch, blobs):
                if isinstance(blob, Exception):
                    if not row[3].done():
                        row[3].set_result(RheaKVError(Status.error(
                            RaftError.EINVAL, f"malformed op: {blob!r}")))
                    continue
                keep.append(row[:2] + (blob,) + row[3:])
            batch = keep
            if not batch:
                return
        req = KVCommandBatchRequest(
            items=[row[2] for row in batch])
        rpc0 = 0.0
        if TRACER.enabled:
            rpc0 = time.perf_counter()
            for row in batch:
                if row[5]:  # client-queue stage: submit -> this send
                    TRACER.span(row[5], "client_queue", row[6], rpc0,
                                proc="client", store=self.endpoint)
            # per-item contexts as the trailing wire field (b"" when
            # nothing in the batch is traced)
            req.trace_ctx = pack_ctx([row[5] for row in batch])
        t0 = asyncio.get_running_loop().time()
        try:
            resp = await client.transport.call(
                self.endpoint, "kv_command_batch", req, client.timeout_ms)
        except RpcError as e:
            if is_no_method(e):
                # a pre-batch store: downgrade permanently, serve this
                # batch through the per-op path
                client._batch_ok = False
                client.batch_fallbacks += 1
                outs = await asyncio.gather(
                    *(client._call_region_outcome(region, op)
                      for region, _p, _b, _f, _s, _t, _ts, op in batch))
                for row, out in zip(batch, outs):
                    if not row[3].done():
                        row[3].set_result(out)
                return
            for region, _p, _b, fut, spread, _t, _ts, _op in batch:
                if not spread:          # dead store: retryable
                    client._leaders.pop(region.id, None)
                if not fut.done():
                    fut.set_result(_Retry(status=e.status))
            return
        client.batch_rpcs += 1
        client.batch_items += len(batch)
        if rpc0:
            rpc1 = time.perf_counter()
            for row in batch:
                if row[5]:
                    TRACER.span(row[5], "kv_batch_rpc", rpc0, rpc1,
                                proc="client", store=self.endpoint,
                                items=len(batch))
        # feed the endpoint EMA only when the store actually SERVED
        # something: a SICK store's instant shed bounces (or a follower
        # instantly answering EPERM) would otherwise read as "fast" and
        # drag a gray endpoint's EMA back under the slow floor, undoing
        # the routing signal the EMA exists for
        if any(len(b) >= 8 and decode_batch_reply(b)[0] == 0
               for b in resp.items):
            client._note_ep_latency(self.endpoint,
                                    asyncio.get_running_loop().time() - t0)
        if len(resp.items) != len(batch):
            # a short (or over-long) reply must FAIL the batch, not zip-
            # truncate: unmatched futures would otherwise never resolve
            # and their callers wedge forever (the send plane applies the
            # same len(acks) != len(items) guard)
            st = Status.error(
                RaftError.EINTERNAL,
                f"kv_command_batch reply carried {len(resp.items)} items "
                f"for {len(batch)} requests")
            for row in batch:
                if not row[3].done():
                    row[3].set_result(RheaKVError(st))
            return
        for (region, peer, _b, fut, spread, _t, _ts, _op), blob \
                in zip(batch, resp.items):
            if not fut.done():
                fut.set_result(client._decode_outcome(region, peer, blob,
                                                      spread=spread))


def _encode_rows(rows: list) -> list:
    """Serialize a send window's op blobs (runs ON the encode lane
    thread — touches only the rows' immutable region/op fields).  A
    failed encode yields its exception in place so the caller can fail
    that item individually."""
    out = []
    for region, _p, _b, _f, _s, _t, _ts, op in rows:
        try:
            out.append(encode_batch_item(region.id, region.epoch.conf_ver,
                                         region.epoch.version, op.encode()))
        except Exception as e:  # noqa: BLE001 — attributed per item
            out.append(e)
    return out


# graftcheck: loop-confined — route table, batchers and store senders
# are all touched from the client's event loop only
class RheaKVStore:
    def __init__(self, pd_client: PlacementDriverClient, transport,
                 timeout_ms: float = 5000, max_retries: int = 8,
                 retry_interval_ms: float = 50,
                 batching: Optional[BatchingOptions] = None,
                 read_preference: str = "leader",
                 read_from: str = "",
                 jitter_seed: Optional[int] = None):
        if read_preference not in ("leader", "any"):
            raise ValueError(f"read_preference {read_preference!r} "
                             "(must be 'leader' or 'any')")
        # read_from: where GETs (and other read-only ops) are served —
        #   "leader"   (default) leader store, batched with writes;
        #   "follower" nearest non-leader voter (local serve after a
        #              forwarded-ReadIndex fence), batched per store;
        #   "learner"  learner read replicas first (PR 2's membership
        #              learners as real read capacity), batched;
        #   "any"      legacy round-robin over ALL data replicas via the
        #              per-op path (read_preference="any" alias).
        # Witness replicas hold no state and are never read targets.
        if read_from == "":
            read_from = "any" if read_preference == "any" else "leader"
        if read_from not in ("leader", "follower", "learner", "any"):
            raise ValueError(f"read_from {read_from!r} (must be 'leader', "
                             "'follower', 'learner' or 'any')")
        self.pd = pd_client
        self.transport = transport
        self.route_table = RegionRouteTable()
        self.timeout_ms = timeout_ms
        self.max_retries = max_retries
        self.retry_interval_ms = retry_interval_ms
        # seeded jitter on every outer retry backoff: a bounced
        # 256-worker batch re-probing in lockstep is a synchronized
        # retry herd that a gray (slow-but-alive) leader turns into a
        # thundering retry storm — each sleep spreads over
        # [0.5, 1.5) x the linear schedule instead
        self._backoff_rng = random.Random(jitter_seed)
        self.read_from = read_from
        # per-endpoint service latency EMA (ms): fed by every batch RPC
        # and per-op call, consulted by the read fan-out so spread reads
        # route OFF slow (gray) replicas — client-side mirror of the
        # store-side per-peer health scores
        self._ep_lat_ms: dict[str, float] = {}
        # legacy alias (pre-read_from callers introspect this)
        self.read_preference = "any" if read_from == "any" else "leader"
        # read fan-out observability: who actually SERVED spread reads
        self.read_serves = {"leader": 0, "follower": 0, "learner": 0}
        self._read_rr: dict[int, int] = {}   # region id -> rotation cursor
        # region id -> endpoint of the last known leader's store
        self._leaders: dict[int, str] = {}
        self._started = False
        self._batch_opts = batching if batching is not None \
            else BatchingOptions()
        self._put_batcher: Optional[_Batcher] = None
        self._get_batcher: Optional[_Batcher] = None
        if batching is not None and batching.enabled:
            self._put_batcher = _Batcher(batching.max_write_batch,
                                         self._flush_put_batch)
            self._get_batcher = _Batcher(batching.max_read_batch,
                                         self._flush_get_batch)
        # does the fleet serve kv_command_batch?  Optimistic until an
        # ENOMETHOD proves otherwise (a pre-batch store), then the
        # legacy per-region kv_command path takes over PERMANENTLY —
        # the same wire-compat pattern as the PD delta-batch fallback
        self._batch_ok = True
        self.batch_rpcs = 0        # kv_command_batch RPCs sent
        self.batch_items = 0       # (region, op) items carried in them
        self.batch_fallbacks = 0   # ENOMETHOD downgrades observed
        self.batch_retries: dict[int, int] = {}  # bounced items by code
        # endpoint -> windowed batch sender (one RPC in flight each)
        self._senders: dict[str, _StoreSender] = {}
        self._refresh_inflight: Optional[asyncio.Task] = None
        # region lifecycle (merges): region ids whose stores bounced
        # ERR_NO_REGION — candidates for merged-away eviction.  The next
        # PD-answered refresh adjudicates: still listed = alive (a
        # lagging split child), gone = absorbed by a neighbor, evict it
        # so the absorbing region's extended range takes over the route.
        self._merge_suspects: set[int] = set()
        self.merged_evictions = 0

    # ------------------------------------------------------------------
    # store-grouped batch dispatch (the kv_command_batch fast path)
    # ------------------------------------------------------------------

    def _store_candidates(self, region: Region, attempt: int) -> list[str]:
        """Per-attempt candidate stores for a region, leader hint first,
        then EVERY voter (rotated by attempt so a retry herd doesn't
        camp on one store) — same coverage contract as _endpoints_for:
        one attempt cycle must be able to reach the real leader even
        when the cached hint is stale."""
        # witnesses can never lead: probing one as a leader candidate is
        # a guaranteed EPERM bounce (they forward nothing)
        voters = [p for p in region.peers if not p.endswith("/learner")
                  and not p.endswith("/witness")]
        if not voters:
            return [region.peers[0]] if region.peers else []
        k = attempt % len(voters)
        cands = []
        leader = self._leaders.get(region.id)
        if leader and leader in voters:
            cands.append(leader)
        cands.extend(p for p in voters[k:] + voters[:k] if p not in cands)
        return cands

    async def _call_region_outcome(self, region: Region, op: KVOperation):
        """_call_region with its control flow reified as a value so batch
        dispatch can zip outcomes back to pairs: ("ok", result) |
        _Retry | RheaKVError."""
        try:
            return ("ok", await self._call_region(region, op))
        except _Retry as r:
            return r
        except RheaKVError as e:
            return e

    def _decode_outcome(self, region: Region, peer: str, blob: bytes,
                        spread: bool = False):
        code, msg, result, meta = decode_batch_reply(blob)
        if code == 0:
            if spread:
                # fan-out observability — and NO leader-cache update: a
                # follower/learner serving a read says nothing about
                # who leads
                self._note_read_serve(region, peer)
            else:
                self._leaders[region.id] = peer
            return ("ok", decode_result(result))
        st = Status(code, msg)
        self.batch_retries[code] = self.batch_retries.get(code, 0) + 1
        if code in (ERR_INVALID_EPOCH, ERR_KEY_OUT_OF_RANGE):
            if meta:
                fresh = Region.decode(meta)
                if spread and (fresh.epoch.version, fresh.epoch.conf_ver) \
                        < (region.epoch.version, region.epoch.conf_ver):
                    # a LAGGING replica (pre-split view): its meta is
                    # useless and a sibling replica can still serve —
                    # bounce to the next candidate, no route refresh
                    return _Retry(status=st)
                self.route_table.add_or_update(fresh)
            return _Retry(refresh=True, status=st)
        if code == ERR_NO_REGION:
            if not spread:
                self._leaders.pop(region.id, None)
            self._merge_suspects.add(region.id)
            return _Retry(refresh=True, status=st)
        if code in _RETRYABLE_CODES:
            if not spread:
                self._leaders.pop(region.id, None)
            return _Retry(status=st)
        return RheaKVError(st)

    def _note_read_serve(self, region: Region, peer: str) -> None:
        """Classify which replica class served a spread read (fan-out
        observability, read_serves counters)."""
        if peer.endswith("/learner"):
            self.read_serves["learner"] += 1
        elif peer == self._leaders.get(region.id):
            self.read_serves["leader"] += 1
        else:
            self.read_serves["follower"] += 1

    def _backoff_s(self, attempt: int) -> float:
        """Outer retry backoff: linear schedule x seeded jitter in
        [0.5, 1.5) — bounced herds spread instead of re-probing in
        lockstep."""
        return (self.retry_interval_ms * (attempt + 1)
                * (0.5 + self._backoff_rng.random()) / 1000.0)

    def _note_ep_latency(self, endpoint: str, dur_s: float) -> None:
        ms = dur_s * 1000.0
        cur = self._ep_lat_ms.get(endpoint)
        self._ep_lat_ms[endpoint] = ms if cur is None \
            else cur + 0.25 * (ms - cur)

    def _order_by_speed(self, pool: list[str]) -> list[str]:
        """Stable-partition a read-candidate pool: endpoints observed
        SLOW (EMA > 3x the pool's fastest and over an absolute floor)
        go last — spread reads route off gray replicas while the
        rotation inside each partition keeps spreading load.

        Self-healing: a demoted endpoint no longer serves, so it gets
        no fresh samples and a frozen EMA would exile it FOREVER after
        a healed transient limp.  Each demotion decays its stored EMA
        slightly; after ~O(100) reads it drops under the floor, gets
        re-probed, and one real sample either clears it or (alpha
        0.25 on a still-slow reply) demotes it again within a few
        reads — bounded re-probe cost, no permanent capacity loss."""
        emas = [self._ep_lat_ms.get(_endpoint(p)) for p in pool]
        known = [e for e in emas if e is not None]
        if len(known) < 2:
            return pool
        floor = max(3.0 * min(known), 20.0)
        fast = [p for p, e in zip(pool, emas) if e is None or e <= floor]
        slow = [p for p, e in zip(pool, emas) if not (e is None or e <= floor)]
        for p in slow:
            self._ep_lat_ms[_endpoint(p)] *= 0.98
        return fast + slow

    def _sender(self, endpoint: str) -> _StoreSender:
        s = self._senders.get(endpoint)
        if s is None:
            s = self._senders[endpoint] = _StoreSender(self, endpoint)
        return s

    async def _dispatch_region_ops(self, pairs: list, attempt: int = 0
                                   ) -> list:
        """One attempt cycle over many (region, op) pairs, each routed
        through its leader store's :class:`_StoreSender` — everything
        pending fleet-wide for one store rides ONE kv_command_batch per
        window (the raft plane's ``multi_append`` pattern one layer up),
        and every pair resolves independently (a slow region on one
        store never convoys its neighbours).  Pairs that can't ride a
        batch — 'any'-spread reads (per-region round-robin) or a
        downgraded fleet — go through _call_region.  Returns one
        outcome per pair (see _call_region_outcome).

        Task-fan shape: sender submits are SYNCHRONOUS (each returns a
        plain future), so a round over N pairs is N submit calls plus
        ONE gather of futures — no per-pair coroutine/task.  A pair
        bounced RETRYABLY (not leader, electing) advances to its next
        candidate store in the next round, the batch analog of
        _call_region probing every endpoint within one attempt cycle:
        a cold leader cache costs extra round trips, never the outer
        backoff sleep."""
        if TRACER.enabled:
            # one trace per (region, op) dispatch cycle: the root span
            # opens here (sampling + slow-op candidacy decided inside)
            # and closes when the cycle's outcome lands below
            for _region, op in pairs:
                if not op.trace_id:
                    op.trace_id = TRACER.begin_op("kv_op", proc="client")
        outs: list = [None] * len(pairs)
        direct: list[int] = []
        live: list[list] = []   # [pair index, candidates, cursor, spread]
        for i, (region, op) in enumerate(pairs):
            if (not self._batch_ok
                    or (self.read_from == "any"
                        and op.op in _READONLY_OPS)):
                direct.append(i)
                continue
            spread = (self.read_from in ("follower", "learner")
                      and op.op in _READONLY_OPS)
            cands = (self._read_candidates(region, attempt) if spread
                     else self._store_candidates(region, attempt))
            live.append([i, cands, 0, spread])
        # the per-op escape hatch still needs real tasks (one coroutine
        # each); batched pairs never do
        direct_gather = asyncio.gather(
            *(self._call_region_outcome(*pairs[i]) for i in direct)) \
            if direct else None
        while live:
            futs = [self._sender(_endpoint(row[1][row[2]])).submit(
                        pairs[row[0]][0], row[1][row[2]],
                        pairs[row[0]][1], spread=row[3])
                    for row in live]
            round_outs = await asyncio.gather(*futs)
            nxt = []
            for row, out in zip(live, round_outs):
                outs[row[0]] = out
                # a mid-flight ENOMETHOD downgrade means the sender
                # already served the item through the per-op path:
                # outcome is final regardless of shape
                if (self._batch_ok
                        and isinstance(out, _Retry) and not out.refresh
                        and row[2] + 1 < len(row[1])):
                    row[2] += 1
                    nxt.append(row)
            live = nxt
        if direct_gather is not None:
            for i, out in zip(direct, await direct_gather):
                outs[i] = out
        if TRACER.enabled:
            for (_region, op), out in zip(pairs, outs):
                if op.trace_id:
                    TRACER.end_op(op.trace_id, ok=isinstance(out, tuple))
        return outs

    # ------------------------------------------------------------------
    # client-side batcher flushes (one drain round)
    # ------------------------------------------------------------------

    async def _flush_batched_ops(self, chunk, key_fn, op_fn, deliver) -> None:
        """Drain one batcher chunk: resolve each item's region ONCE per
        round (the round's route cache — invalidated only through the
        retry path on epoch/region errors), group regions by leader
        store into kv_command_batch RPCs, deliver per-item results, and
        re-shard ONLY the failed/escaped items after a refresh."""
        pending = list(chunk)
        last = Status.error(RaftError.EAGAIN, "exhausted retries")
        for attempt in range(self.max_retries):
            groups: dict[int, tuple[Region, list]] = {}
            unroutable: list = []
            for item, fut in pending:
                try:
                    r = self.route_table.find_region_by_key(key_fn(item))
                except Exception as e:  # noqa: BLE001 — malformed key:
                    # fail ITS caller, not the whole chunk
                    if not fut.done():
                        fut.set_exception(RheaKVError(Status.error(
                            RaftError.EINVAL, f"malformed key: {e!r}")))
                    continue
                if r is None:
                    unroutable.append((item, fut))
                else:
                    groups.setdefault(r.id, (r, []))[1].append((item, fut))
            retry: list = list(unroutable)
            need_refresh = bool(unroutable)
            parts = list(groups.values())
            outcomes = await self._dispatch_region_ops(
                [(region, op_fn(items)) for region, items in parts], attempt)
            for (region, items), out in zip(parts, outcomes):
                if isinstance(out, tuple):
                    deliver(items, out[1])
                elif isinstance(out, _Retry):
                    need_refresh = need_refresh or out.refresh
                    if out.status is not None:
                        last = out.status
                    retry.extend(items)
                else:   # hard error fails ITS region's calls only
                    for _, fut in items:
                        if not fut.done():
                            fut.set_exception(out)
            if not retry:
                return
            pending = retry
            if need_refresh:
                await self._refresh_routes()
            await asyncio.sleep(self._backoff_s(attempt))
        err = RheaKVError(last)
        for _, fut in pending:
            if not fut.done():
                fut.set_exception(err)

    async def _flush_put_batch(self, chunk) -> None:
        def deliver(items, result):
            for _, fut in items:
                if not fut.done():
                    fut.set_result(bool(result))

        await self._flush_batched_ops(
            chunk, key_fn=lambda kv: kv[0],
            op_fn=lambda items: KVOperation.put_list(
                [kv for kv, _ in items]),
            deliver=deliver)

    async def _flush_get_batch(self, chunk) -> None:
        def deliver(items, result):
            res = dict(result)   # list[(key, Optional[value])]
            for k, fut in items:
                if not fut.done():
                    fut.set_result(res.get(k))

        await self._flush_batched_ops(
            chunk, key_fn=lambda k: k,
            op_fn=lambda items: KVOperation.multi_get(
                list(dict.fromkeys(k for k, _ in items))),
            deliver=deliver)

    async def start(self) -> None:
        # best-effort initial route pull: a PD that is still booting (or
        # electing) must not fail client startup — ops refresh routes on
        # demand through _execute's ENOENT path
        try:
            self.route_table.reset(await self.pd.list_regions())
        except Exception as e:  # noqa: BLE001
            # visible at default level: a typo'd PD endpoint would
            # otherwise surface only as per-op ENOENT after timeouts
            LOG.warning("initial route pull from PD failed (%s); "
                        "deferring to on-demand refresh", e)
        self._started = True

    async def shutdown(self) -> None:
        self._started = False

    # ------------------------------------------------------------------
    # routing & retry engine
    # ------------------------------------------------------------------

    async def _refresh_routes(self) -> None:
        """Single-flight wrapper: at region density one refresh decodes
        every store's whole region list, so a retry herd must share ONE
        O(regions) pass instead of running one each."""
        if self._refresh_inflight is None or self._refresh_inflight.done():
            self._refresh_inflight = asyncio.ensure_future(
                self._refresh_routes_once())
        # shield: one caller timing out must not cancel the shared pass
        await asyncio.shield(self._refresh_inflight)

    async def _refresh_routes_once(self) -> None:
        """Re-pull the region layout: PD first, then store-reported truth
        (PD-less mode — and PD outages — discover split regions this way).
        Best-effort: a down PD must not fail ops the cached routes or the
        stores themselves can still serve."""
        regions: list[Region] = []
        pd_ids: Optional[set[int]] = None
        try:
            regions = await self.pd.list_regions()
            pd_ids = {r.id for r in regions}
        except Exception:  # noqa: BLE001 — PD unreachable / electing
            LOG.debug("pd route refresh failed; falling back to stores",
                      exc_info=True)
        # dedupe on the store endpoint: the same store may be a voter in
        # one region and a '/learner' in another
        endpoints = {_endpoint(p) for r in regions for p in r.peers}
        # also ask every store we already know about (covers PD-down case)
        endpoints.update(_endpoint(p) for r in self.route_table.list_regions()
                         for p in r.peers)
        async def ask(ep: str):
            return await self.transport.call(
                ep, "kv_list_regions",
                ListRegionsOnStoreRequest(), self.timeout_ms)

        answers = await asyncio.gather(
            *(ask(ep) for ep in endpoints), return_exceptions=True)
        for resp in answers:
            if isinstance(resp, BaseException):
                continue
            for blob in resp.regions:
                regions.append(Region.decode(blob))
        # fold: keep the freshest epoch per region id — seeded with the
        # table we already hold, so a refresh answered only by lagging
        # replicas (leader down, PD stale) can never regress the view
        regions.extend(self.route_table.list_regions())
        best: dict[int, Region] = {}
        for r in regions:
            cur = best.get(r.id)
            if cur is None or (r.epoch.version, r.epoch.conf_ver) > \
                    (cur.epoch.version, cur.epoch.conf_ver):
                best[r.id] = r
        # merged-away eviction (region lifecycle): a region the stores
        # bounce with ERR_NO_REGION and a PD answer no longer lists was
        # absorbed into a neighbor — drop it from the fold so the
        # absorbing region's extended range (same start key, and NOT
        # necessarily a higher version — the absorbed side may have
        # split more) can take over the route.  A suspect the PD still
        # lists is alive (a lagging split child); PD-down refreshes
        # adjudicate nothing (conservative — both cases look the same
        # from the stores alone).
        if pd_ids is not None and self._merge_suspects:
            for rid in list(self._merge_suspects):
                self._merge_suspects.discard(rid)
                if rid not in pd_ids and rid in best:
                    best.pop(rid)
                    self._leaders.pop(rid, None)
                    self.route_table.remove_region(rid)
                    self.merged_evictions += 1
                    LOG.debug("evicted merged-away region %d", rid)
        if best:  # never wipe a usable cache with an empty refresh
            self.route_table.reset(list(best.values()))

    def _endpoints_for(self, region: Region) -> list[str]:
        """Leader-first candidate ordering of the region's store endpoints.

        Learner replicas (``/learner``-suffixed peers — read-only, never
        leaders) go last: they can only serve by forwarding, so they are
        a fallback when no voter answers, not a first hop.  Witness
        voters (``/witness``) are skipped entirely: they never lead and
        hold no data to serve or forward from.
        """
        eps = []
        voters = [p for p in region.peers if not p.endswith("/learner")
                  and not p.endswith("/witness")]
        leader = self._leaders.get(region.id)
        if leader and leader in voters:
            eps.append(leader)
        eps.extend(p for p in voters if p not in eps)
        eps.extend(p for p in region.peers if p.endswith("/learner"))
        return eps

    def _read_endpoints_for(self, region: Region) -> list[str]:
        """Round-robin over the DATA replicas (voters, learners, leader
        alike) for read-only ops under read_from='any' — witness
        replicas hold no state and are never read targets.  Like the
        follower/learner fan-out, observed-slow (gray) endpoints drop
        to the back of the rotation."""
        peers = [p for p in region.peers if not p.endswith("/witness")]
        cur = self._read_rr.get(region.id, region.id)
        self._read_rr[region.id] = cur + 1
        rotated = [peers[(cur + i) % len(peers)] for i in range(len(peers))]
        return self._order_by_speed(rotated)

    def _read_candidates(self, region: Region, attempt: int) -> list[str]:
        """read_from='follower'|'learner' candidate ordering: the
        preferred replica class first (rotated per region so fan-out
        spreads), then the remaining data replicas as fallback — a
        region with no replica of the preferred class still serves.
        Witnesses are never read targets (no state to serve)."""
        learners = [p for p in region.peers if p.endswith("/learner")]
        voters = [p for p in region.peers if not p.endswith("/learner")
                  and not p.endswith("/witness")]
        leader = self._leaders.get(region.id)
        followers = [p for p in voters if p != leader]
        leader_tail = [leader] if leader in voters else []
        if self.read_from == "learner":
            pool, rest = learners, followers + leader_tail
        else:
            pool, rest = followers, leader_tail + learners
        if not pool:
            pool, rest = voters, learners
        if not pool:
            return [p for p in region.peers if not p.endswith("/witness")]
        cur = self._read_rr.get(region.id, region.id)
        self._read_rr[region.id] = cur + 1
        k = (cur + attempt) % len(pool)
        rotated = pool[k:] + pool[:k]
        # gray replicas last: observed-slow endpoints only serve when
        # every faster candidate bounced (per-endpoint latency EMA)
        return self._order_by_speed(rotated) \
            + [p for p in rest if p not in pool]

    async def _call_region(self, region: Region, op: KVOperation):
        """One attempt cycle over a region's stores; raises on hard error."""
        last_status = Status.error(RaftError.EAGAIN, "no store reachable")
        spread_read = (self.read_from != "leader"
                       and op.op in _READONLY_OPS)
        if not spread_read:
            eps = self._endpoints_for(region)
        elif self.read_from == "any":
            eps = self._read_endpoints_for(region)
        else:
            eps = self._read_candidates(region, 0)
        for ep_str in eps:
            # peers are PeerId strings; the store serves on ip:port
            endpoint = _endpoint(ep_str)
            req = KVCommandRequest(
                region_id=region.id,
                conf_ver=region.epoch.conf_ver,
                version=region.epoch.version,
                op_blob=op.encode(),
                trace_id=wire_ctx(op.trace_id))
            rpc0 = time.perf_counter() if wire_ctx(op.trace_id) else 0.0
            t0 = asyncio.get_running_loop().time()
            try:
                resp = await self.transport.call(endpoint, "kv_command", req,
                                                 self.timeout_ms)
            except RpcError as e:
                last_status = e.status
                if not spread_read:   # a dead read replica says nothing
                    self._leaders.pop(region.id, None)   # about the leader
                continue
            if rpc0:
                TRACER.span(op.trace_id, "kv_rpc", rpc0,
                            time.perf_counter(), proc="client",
                            store=endpoint, code=resp.code)
            if resp.code == 0:
                # EMA only on served replies (an instant error bounce
                # must not make a gray endpoint look fast again)
                self._note_ep_latency(
                    endpoint, asyncio.get_running_loop().time() - t0)
                if not spread_read:
                    self._leaders[region.id] = ep_str
                else:
                    self._note_read_serve(region, ep_str)
                return decode_result(resp.result)
            if resp.code in (ERR_INVALID_EPOCH, ERR_KEY_OUT_OF_RANGE):
                fresh = Region.decode(resp.region_meta)
                if spread_read and (fresh.epoch.version,
                                    fresh.epoch.conf_ver) < \
                        (region.epoch.version, region.epoch.conf_ver):
                    # a LAGGING replica (pre-split view): its meta is
                    # useless and the other replicas can still serve —
                    # don't abort the cycle into a full route refresh
                    last_status = Status(resp.code, resp.msg)
                    continue
                self.route_table.add_or_update(fresh)
                raise _Retry(refresh=True)
            if resp.code == ERR_NO_REGION:
                self._leaders.pop(region.id, None)
                self._merge_suspects.add(region.id)
                raise _Retry(refresh=True)
            if resp.code in _RETRYABLE_CODES:
                # not leader / electing / readIndex round timed out under
                # load: try the next store
                last_status = Status(resp.code, resp.msg)
                if not spread_read:
                    self._leaders.pop(region.id, None)
                continue
            raise RheaKVError(Status(resp.code, resp.msg))
        raise _Retry(status=last_status)

    async def _execute(self, key: bytes, op: KVOperation):
        """Route by key, run with bounded retries."""
        tid = TRACER.begin_op("kv_op", proc="client") \
            if TRACER.enabled and not op.trace_id else 0
        if tid:
            op.trace_id = tid
        try:
            return await self._execute_traced(key, op)
        finally:
            if tid:
                TRACER.end_op(tid)

    async def _execute_traced(self, key: bytes, op: KVOperation):
        last = Status.error(RaftError.EAGAIN, "exhausted retries")
        for attempt in range(self.max_retries):
            region = self.route_table.find_region_by_key(key)
            if region is None:
                await self._refresh_routes()
                region = self.route_table.find_region_by_key(key)
                if region is None:
                    raise RheaKVError(Status.error(
                        RaftError.ENOENT, f"no region covers key {key!r}"))
            try:
                return await self._call_region(region, op)
            except _Retry as r:
                if r.refresh:
                    await self._refresh_routes()
                if r.status is not None:
                    last = r.status
                # linear backoff (jittered): elections take a few
                # election timeouts, and lockstep re-probes would herd
                await asyncio.sleep(self._backoff_s(attempt))
        raise RheaKVError(last)

    # ------------------------------------------------------------------
    # single-key ops
    # ------------------------------------------------------------------

    async def get(self, key: bytes) -> Optional[bytes]:
        if self._get_batcher is not None:
            return await self._get_batcher.add(key)
        return await self._execute(key, KVOperation(KVOp.GET, key))

    async def contains_key(self, key: bytes) -> bool:
        return await self._execute(key, KVOperation(KVOp.CONTAINS_KEY, key))

    async def put(self, key: bytes, value: bytes) -> bool:
        if self._put_batcher is not None:
            return await self._put_batcher.add((key, value))
        return await self._execute(key, KVOperation(KVOp.PUT, key, value))

    async def put_if_absent(self, key: bytes, value: bytes) -> Optional[bytes]:
        return await self._execute(
            key, KVOperation(KVOp.PUT_IF_ABSENT, key, value))

    async def get_and_put(self, key: bytes, value: bytes) -> Optional[bytes]:
        return await self._execute(
            key, KVOperation(KVOp.GET_AND_PUT, key, value))

    async def compare_and_put(self, key: bytes, expect: bytes,
                              update: bytes) -> bool:
        return await self._execute(key, KVOperation.cas(key, expect, update))

    async def merge(self, key: bytes, value: bytes) -> bool:
        return await self._execute(key, KVOperation(KVOp.MERGE, key, value))

    async def delete(self, key: bytes) -> bool:
        return await self._execute(key, KVOperation(KVOp.DELETE, key))

    # ------------------------------------------------------------------
    # multi-key ops (fan out by owning region)
    # ------------------------------------------------------------------

    async def _run_sharded(self, items: list, key_fn, op_fn):
        """Group items by owning region, run each group, and — crucially —
        RE-SHARD whatever failed after every route refresh: a split that
        races the batch must never commit keys through the wrong group
        (the server also range-checks, returning ERR_KEY_OUT_OF_RANGE).
        Returns the list of per-group results.

        A thin wrapper over _flush_batched_ops (one retry engine for the
        batcher flushes AND the multi-key APIs): each item gets a
        future, per-group results accumulate via deliver."""
        results: list = []
        chunk = [(it, asyncio.get_running_loop().create_future())
                 for it in items]

        def deliver(group_items, result):
            results.append(result)
            for _, fut in group_items:
                if not fut.done():
                    fut.set_result(True)

        await self._flush_batched_ops(
            chunk, key_fn=key_fn,
            op_fn=lambda pairs: op_fn([it for it, _ in pairs]),
            deliver=deliver)
        errs = [err for _, fut in chunk
                if (err := fut.exception()) is not None]
        if errs:
            raise errs[0]
        return results

    async def multi_get(self, keys: list[bytes]
                        ) -> dict[bytes, Optional[bytes]]:
        parts = await self._run_sharded(
            keys, lambda k: k, KVOperation.multi_get)
        out: dict[bytes, Optional[bytes]] = {}
        for pairs in parts:
            out.update(dict(pairs))
        return out

    async def put_list(self, kvs: list[tuple[bytes, bytes]]) -> bool:
        parts = await self._run_sharded(
            kvs, lambda kv: kv[0], KVOperation.put_list)
        return all(parts)

    async def delete_list(self, keys: list[bytes]) -> bool:
        parts = await self._run_sharded(
            keys, lambda k: k, KVOperation.delete_list)
        return all(parts)

    # ------------------------------------------------------------------
    # range ops (span regions)
    # ------------------------------------------------------------------

    def _clip(self, region: Region, start: bytes, end: bytes
              ) -> tuple[bytes, bytes]:
        s = max(start, region.start_key) if region.start_key else start
        if region.end_key:
            e = region.end_key if not end else min(end, region.end_key)
        else:
            e = end
        return s, e

    async def _ranged(self, start: bytes, end: bytes, make_op,
                      reverse: bool = False,
                      remaining=lambda results: -1) -> list:
        """Cursor walk over the regions intersecting [start, end).

        The region AND its clip are re-resolved from the current route
        table on every attempt, so a split racing the walk narrows the
        next step instead of wedging the whole call on a permanently
        out-of-range pre-clipped op (the server range-checks every op).
        ``make_op(s, e, remaining)`` builds the per-slice op;
        ``remaining(results)`` returns the item budget left (-1 =
        unlimited, 0 = stop).
        """
        results: list = []
        attempts = 0
        last = Status.error(RaftError.EAGAIN, "exhausted retries")
        cursor = end if reverse else start
        while remaining(results) != 0:
            lo, hi = (start, cursor) if reverse else (cursor, end)
            regions = self.route_table.find_regions_by_range(lo, hi)
            if not regions:
                await self._refresh_routes()
                regions = self.route_table.find_regions_by_range(lo, hi)
                if not regions:
                    break
            region = regions[-1] if reverse else regions[0]
            s, e = self._clip(region, lo, hi)
            try:
                results.append(await self._call_region(
                    region, make_op(s, e, remaining(results))))
                attempts = 0  # per-slice retry budget, not per-walk
            except _Retry as r:
                attempts += 1
                if attempts >= self.max_retries:
                    raise RheaKVError(r.status or last)
                if r.status is not None:
                    last = r.status
                if r.refresh:
                    await self._refresh_routes()
                await asyncio.sleep(self._backoff_s(attempts - 1))
                continue
            if reverse:
                if not region.start_key or (start and region.start_key <= start):
                    break
                cursor = region.start_key
            else:
                if not region.end_key or (end and region.end_key >= end):
                    break
                cursor = region.end_key
        return results

    @staticmethod
    def _scan_budget(limit: int):
        def remaining(parts: list) -> int:
            if limit < 0:
                return -1
            return max(limit - sum(len(p) for p in parts), 0)
        return remaining

    async def scan(self, start: bytes, end: bytes, limit: int = -1,
                   return_value: bool = True
                   ) -> list[tuple[bytes, Optional[bytes]]]:
        parts = await self._ranged(
            start, end,
            lambda s, e, rem: scan_op(s, e, rem, return_value),
            remaining=self._scan_budget(limit))
        return [kv for p in parts for kv in p]

    async def reverse_scan(self, start: bytes, end: bytes, limit: int = -1,
                           return_value: bool = True
                           ) -> list[tuple[bytes, Optional[bytes]]]:
        parts = await self._ranged(
            start, end,
            lambda s, e, rem: scan_op(s, e, rem, return_value, reverse=True),
            reverse=True,
            remaining=self._scan_budget(limit))
        return [kv for p in parts for kv in p]

    def iterator(self, start: bytes, end: bytes, buf_size: int = 64,
                 return_value: bool = True):
        """Paged async iterator over [start, end) (reference:
        ``DefaultRheaKVStore#iterator`` / ``RheaIterator``): fetches
        ``buf_size`` entries per scan RPC and yields ``(key, value)``
        in order, transparently crossing region boundaries::

            async for k, v in kv.iterator(b"a", b"z"):
                ...
        """
        if buf_size <= 0:
            raise ValueError("buf_size must be positive")
        return self._iterate(start, end, buf_size, return_value)

    async def _iterate(self, start: bytes, end: bytes, buf_size: int,
                       return_value: bool):
        cursor = start
        while True:
            page = await self.scan(cursor, end, limit=buf_size,
                                   return_value=return_value)
            for kv in page:
                yield kv
            if len(page) < buf_size:
                return
            cursor = page[-1][0] + b"\x00"   # smallest key after the last

    async def delete_range(self, start: bytes, end: bytes) -> bool:
        parts = await self._ranged(
            start, end,
            lambda s, e, rem: KVOperation.delete_range(s, e))
        return all(parts)

    # ------------------------------------------------------------------
    # sequences & locks
    # ------------------------------------------------------------------

    async def get_sequence(self, key: bytes, step: int) -> Sequence:
        start, end = await self._execute(key,
                                         KVOperation.get_sequence(key, step))
        return Sequence(start, end)

    async def get_latest_sequence(self, key: bytes) -> int:
        return (await self.get_sequence(key, 0)).start

    async def reset_sequence(self, key: bytes) -> bool:
        return await self._execute(key, KVOperation(KVOp.RESET_SEQUENCE, key))

    def get_distributed_lock(self, key: bytes, lease_ms: int = 30_000
                             ) -> "DistributedLock":
        return DistributedLock(self, key, lease_ms)


class _Retry(Exception):
    def __init__(self, refresh: bool = False,
                 status: Optional[Status] = None):
        self.refresh = refresh
        self.status = status


def _endpoint(peer_str: str) -> str:
    """PeerId string ('ip:port[:idx[:priority]][/learner]') -> endpoint."""
    return ":".join(peer_str.split("/", 1)[0].split(":")[:2])


class DistributedLock:
    """Lease-based distributed lock with fencing tokens.

    Reference parity: ``rhea:client/DefaultRheaKVStore#getDistributedLock``
    + ``KVOperation.KEY_LOCK`` (SURVEY.md §3.2 "Distributed lock &
    sequence").  ``watchdog`` renews the lease at lease/3 cadence while
    held (the reference leaves renewal to the caller's scheduler).
    """

    def __init__(self, store: RheaKVStore, key: bytes, lease_ms: int):
        self._store = store
        self.key = key
        self.lease_ms = lease_ms
        self.locker_id = uuid.uuid4().bytes
        self.fencing_token: int = -1
        self._held = False
        self._watchdog: Optional[asyncio.Task] = None

    @property
    def held(self) -> bool:
        return self._held

    async def try_lock(self, watchdog: bool = False) -> bool:
        ok, token, _owner = await self._store._execute(
            self.key,
            KVOperation.key_lock(self.key, self.locker_id, self.lease_ms,
                                 keep_lease=False))
        if ok:
            self.fencing_token = token
            self._held = True
            if watchdog and (self._watchdog is None or self._watchdog.done()):
                self._watchdog = asyncio.ensure_future(self._renew_loop())
        return ok

    async def lock(self, watchdog: bool = False,
                   retry_interval_ms: float = 200,
                   timeout_ms: Optional[float] = None) -> bool:
        """Block until acquired (or timeout)."""
        loop = asyncio.get_running_loop()
        deadline = None
        if timeout_ms is not None:
            # graftcheck: allow(raw-clock) — client-side retry budget:
            # the CALLER's real deadline
            deadline = loop.time() + timeout_ms / 1000.0
        while True:
            if await self.try_lock(watchdog=watchdog):
                return True
            # graftcheck: allow(raw-clock) — client-side retry budget: the CALLER's real deadline
            if deadline is not None and loop.time() >= deadline:
                return False
            await asyncio.sleep(retry_interval_ms / 1000.0)

    async def _renew_loop(self) -> None:
        try:
            while self._held:
                await asyncio.sleep(self.lease_ms / 3000.0)
                if not self._held:
                    break
                try:
                    ok, token, _ = await self._store._execute(
                        self.key,
                        KVOperation.key_lock(self.key, self.locker_id,
                                             self.lease_ms, keep_lease=True))
                except Exception:  # noqa: BLE001 — transient (election etc.)
                    # retry quickly; the lease may still be alive
                    await asyncio.sleep(self.lease_ms / 6000.0)
                    continue
                if not ok:
                    # someone else owns it now — we lost the lease for real
                    self._held = False
                    break
                if token != self.fencing_token:
                    # our lease lapsed and the store silently re-granted
                    # under a NEW fencing token: someone else may have held
                    # (and released) the lock in the gap, so continuity is
                    # broken — surrender the accidental re-acquisition
                    # rather than masquerade as an unbroken hold
                    self._held = False
                    try:
                        await self._store._execute(
                            self.key, KVOperation.key_unlock(
                                self.key, self.locker_id))
                    except Exception:  # noqa: BLE001 — lease will expire
                        pass
                    break
        except asyncio.CancelledError:
            pass
        finally:
            self._watchdog = None

    async def unlock(self) -> bool:
        self._held = False
        if self._watchdog is not None:
            self._watchdog.cancel()
            self._watchdog = None
        return await self._store._execute(
            self.key, KVOperation.key_unlock(self.key, self.locker_id))
