"""Placement driver server: cluster metadata + region scheduling.

Reference parity: ``pd:DefaultPlacementDriverService`` /
``pd:PlacementDriverServer`` / ``pd:MetadataStore`` /
``pd:ClusterStatsManager`` (SURVEY.md §3.2 "PD server") — the PD is
itself a one-group raft application: store/region heartbeats mutate
replicated metadata; the PD leader answers routing queries and emits
Instructions (RANGE_SPLIT, TRANSFER_LEADER) back to stores.

Determinism note: replicated FSM state holds only logical metadata
(stores, regions, id allocator).  Liveness clocks and split decisions
live on the PD *leader* outside the FSM — they are re-derived after
failover from fresh heartbeats, exactly like the reference's in-memory
ClusterStatsManager.
"""

from __future__ import annotations

import logging
import struct
import time
from dataclasses import dataclass, field
from typing import Optional

from tpuraft.conf import Configuration
from tpuraft.core.node_manager import NodeManager
from tpuraft.core.raft_group_service import RaftGroupService
from tpuraft.core.state_machine import Iterator, StateMachine
from tpuraft.entity import PeerId, Task
from tpuraft.errors import RaftError, Status
from tpuraft.options import NodeOptions
from tpuraft.rheakv.metadata import Region
from tpuraft.rheakv.pd_messages import (
    CreateRegionIdRequest,
    CreateRegionIdResponse,
    Instruction,
    ListRegionsRequest,
    ListRegionsResponse,
    ListStoresRequest,
    ListStoresResponse,
    RegionHeartbeatRequest,
    RegionHeartbeatResponse,
    ReportSplitRequest,
    ReportSplitResponse,
    StoreHeartbeatRequest,
    StoreHeartbeatResponse,
    decode_store_meta,
    encode_store_meta,
)

LOG = logging.getLogger(__name__)

PD_GROUP_ID = "__pd__"

# PD command kinds (the PD group's replicated ops)
_CMD_STORE_UPSERT = 1
_CMD_REGION_UPSERT = 2
_CMD_SPLIT = 3
_CMD_ALLOC_ID = 4
_CMD_SPLIT_ISSUED = 5   # alloc child id + record the pending decision
_CMD_MERGE_ISSUED = 6   # record a pending (source -> target) merge
_CMD_MERGE = 7          # merge completed: fold source into target


def _cmd(kind: int, payload: bytes = b"") -> bytes:
    return struct.pack("<B", kind) + payload


@dataclass
class _StoreRecord:
    store_id: int
    endpoint: str
    zone: str = ""   # geo failure-domain label ("" = unlabeled)


def _peer_endpoint(peer_str: str) -> str:
    """Peer string ('ip:port[:idx[:prio]][/learner|/witness]') -> endpoint."""
    return ":".join(peer_str.split("/", 1)[0].split(":")[:2])


def zone_leader_histogram(region_leaders: dict[int, str],
                          zones: dict[str, str]) -> dict[str, int]:
    """Leaders per zone — computed ONCE per heartbeat batch and shared
    across every pick_transfer_target call in the request."""
    counts: dict[str, int] = {}
    for ep in region_leaders.values():
        z = zones.get(_peer_endpoint(ep), "")
        counts[z] = counts.get(z, 0) + 1
    return counts


class PDMetadataFSM(StateMachine):
    """Replicated PD state: stores, regions, region-id allocator."""

    def __init__(self) -> None:
        self.stores: dict[str, _StoreRecord] = {}   # endpoint -> record
        self.regions: dict[int, Region] = {}
        self.region_leaders: dict[int, str] = {}
        self.next_region_id: int = 1024  # user regions allocate upward
        # REPLICATED split decisions (VERDICT r1 #8): parent region ->
        # allocated child id.  A PD failover must not re-decide a split
        # that was already ordered — the new leader re-issues the SAME
        # child id (idempotent at the store) instead of allocating a
        # duplicate.  Cleared when the split is reported done.
        self.pending_splits: dict[int, int] = {}
        # REPLICATED merge decisions (lifecycle plane, same failover
        # argument): source region -> target region.  The new PD leader
        # re-issues the SAME pair until the merge completes — a merge
        # is a multi-step store-side protocol and must never be
        # half-forgotten or re-decided against a different neighbor.
        self.pending_merges: dict[int, int] = {}
        # REPLICATED merge tombstones: retired source region -> the
        # target that absorbed it.  A full resync from the (now
        # retiring) source leader can still carry the dead region's
        # row; without the tombstone that upsert would resurrect it in
        # the PD view and double-cover the keyspace.  Bounded by the
        # merge count (region ids are never reused).
        self.retired_regions: dict[int, int] = {}

    async def on_apply(self, it: Iterator) -> None:
        while it.valid():
            data = it.data()
            done = it.done()
            result = None
            try:
                result = self._dispatch(data)
                status = Status.OK()
            except Exception as e:  # noqa: BLE001
                LOG.exception("pd apply failed")
                status = Status.error(RaftError.ESTATEMACHINE, str(e))
            if done is not None:
                if hasattr(done, "result"):
                    done.result = result
                done(status)
            it.next()

    def _dispatch(self, data: bytes):
        (kind,) = struct.unpack_from("<B", data, 0)
        payload = data[1:]
        if kind == _CMD_STORE_UPSERT:
            sid, ep, zone = decode_store_meta(payload)
            self.stores[ep] = _StoreRecord(sid, ep, zone)
            return True
        if kind == _CMD_REGION_UPSERT:
            (ln,) = struct.unpack_from("<H", payload, 0)
            leader = payload[2:2 + ln].decode()
            region = Region.decode(payload[2 + ln:])
            if region.id in self.retired_regions:
                return True  # merged away: never resurrect
            cur = self.regions.get(region.id)
            if cur is None or (region.epoch.version, region.epoch.conf_ver) \
                    >= (cur.epoch.version, cur.epoch.conf_ver):
                self.regions[region.id] = region
                if leader:
                    self.region_leaders[region.id] = leader
            return True
        if kind == _CMD_SPLIT_ISSUED:
            (parent_id,) = struct.unpack_from("<q", payload, 0)
            already = self.pending_splits.get(parent_id)
            if already is not None:
                return already  # idempotent: same child id re-issued
            rid = self.next_region_id
            self.next_region_id += 1
            self.pending_splits[parent_id] = rid
            return rid
        if kind == _CMD_SPLIT:
            (pn,) = struct.unpack_from("<I", payload, 0)
            parent = Region.decode(payload[4:4 + pn])
            child = Region.decode(payload[4 + pn:])
            # clear only the MATCHING decision: a stale replayed report
            # (client retry) must not erase a newer pending split
            if self.pending_splits.get(parent.id) == child.id:
                self.pending_splits.pop(parent.id, None)
            # epoch-guarded like _CMD_REGION_UPSERT: a replayed
            # report_split (client retry after a lost response) must not
            # stomp fresher metadata from heartbeats or a later split —
            # and, like the heartbeat path, must never RESURRECT a
            # region that has since merged away (a re-issued split
            # instruction makes the store re-report an old split long
            # after both halves may have gone cold and been absorbed;
            # cur is None after the tombstone pop, so without this
            # check the stale mint-era record would land unguarded and
            # overlap the absorber's extended range)
            for region in (parent, child):
                if region.id in self.retired_regions:
                    continue
                cur = self.regions.get(region.id)
                if cur is None or (region.epoch.version,
                                   region.epoch.conf_ver) >= \
                        (cur.epoch.version, cur.epoch.conf_ver):
                    self.regions[region.id] = region
            self.next_region_id = max(self.next_region_id, child.id + 1)
            return True
        if kind == _CMD_MERGE_ISSUED:
            src_id, tgt_id = struct.unpack_from("<qq", payload, 0)
            already = self.pending_merges.get(src_id)
            if already is not None:
                return already  # idempotent: same target re-issued
            self.pending_merges[src_id] = tgt_id
            return tgt_id
        if kind == _CMD_MERGE:
            from tpuraft.rheakv.state_machine import extend_region_over

            src_id, tgt_id = struct.unpack_from("<qq", payload, 0)
            src = self.regions.pop(src_id, None)
            self.region_leaders.pop(src_id, None)
            tgt = self.regions.get(tgt_id)
            if src is not None and tgt is not None:
                # same deterministic extension the target replicas ran
                # (idempotent: a heartbeat may have upserted the
                # already-extended target first).  NEVER throw out of
                # on_apply: a non-adjacent pair (a policy bug, or
                # metadata skew from a stale report) must degrade to a
                # logged violation, not crash the apply loop on every
                # PD replica — the next target heartbeat re-upserts the
                # true range either way.
                try:
                    extend_region_over(tgt, src.start_key, src.end_key)
                except RuntimeError:
                    LOG.error(
                        "merge finalize %d -> %d: source range "
                        "[%r, %r) not adjacent to target [%r, %r); "
                        "keyspace left to heartbeat repair", src_id,
                        tgt_id, src.start_key, src.end_key,
                        tgt.start_key, tgt.end_key)
            if self.pending_merges.get(src_id) == tgt_id:
                self.pending_merges.pop(src_id, None)
            # True only for the FIRST finalization of this source: the
            # report-RPC path and the heartbeat finalization arm can
            # race the same merge through here, and both count from
            # this return value (replicated state is the tiebreak)
            fresh = src_id not in self.retired_regions
            self.retired_regions[src_id] = tgt_id
            return fresh
        if kind == _CMD_ALLOC_ID:
            rid = self.next_region_id
            self.next_region_id += 1
            return rid
        raise ValueError(f"unknown pd cmd {kind}")

    # -- snapshot ------------------------------------------------------------

    async def on_snapshot_save(self, writer, done) -> None:
        out = bytearray(struct.pack("<q", self.next_region_id))
        out += struct.pack("<I", len(self.stores))
        for rec in self.stores.values():
            out += encode_store_meta(rec.store_id, rec.endpoint)
        out += struct.pack("<I", len(self.regions))
        for rid, region in self.regions.items():
            blob = region.encode()
            leader = self.region_leaders.get(rid, "").encode()
            out += struct.pack("<I", len(blob)) + blob
            out += struct.pack("<H", len(leader)) + leader
        out += struct.pack("<I", len(self.pending_splits))
        for parent_id, child_id in self.pending_splits.items():
            out += struct.pack("<qq", parent_id, child_id)
        # trailing (geo zones) — absent in pre-zone snapshots; store
        # records above stay in the legacy zoneless format so old
        # readers parse the stream unchanged
        zoned = [(ep, rec.zone) for ep, rec in self.stores.items()
                 if rec.zone]
        out += struct.pack("<I", len(zoned))
        for ep, zone in zoned:
            epb, zb = ep.encode(), zone.encode()
            out += struct.pack("<H", len(epb)) + epb
            out += struct.pack("<H", len(zb)) + zb
        # trailing (lifecycle plane) — absent in pre-merge snapshots
        out += struct.pack("<I", len(self.pending_merges))
        for src_id, tgt_id in self.pending_merges.items():
            out += struct.pack("<qq", src_id, tgt_id)
        out += struct.pack("<I", len(self.retired_regions))
        for src_id, tgt_id in self.retired_regions.items():
            out += struct.pack("<qq", src_id, tgt_id)
        writer.write_file("pd_meta", bytes(out))
        done(Status.OK())

    async def on_snapshot_load(self, reader) -> bool:
        blob = reader.read_file("pd_meta")
        if blob is None:
            return False
        buf = memoryview(blob)
        (self.next_region_id,) = struct.unpack_from("<q", buf, 0)
        off = 8
        (ns,) = struct.unpack_from("<I", buf, off)
        off += 4
        self.stores = {}
        for _ in range(ns):
            (sid,) = struct.unpack_from("<q", buf, off)
            off += 8
            (n,) = struct.unpack_from("<H", buf, off)
            off += 2
            ep = bytes(buf[off:off + n]).decode()
            off += n
            self.stores[ep] = _StoreRecord(sid, ep)
        (nr,) = struct.unpack_from("<I", buf, off)
        off += 4
        self.regions = {}
        self.region_leaders = {}
        for _ in range(nr):
            (bn,) = struct.unpack_from("<I", buf, off)
            off += 4
            region = Region.decode(buf[off:off + bn])
            off += bn
            (ln,) = struct.unpack_from("<H", buf, off)
            off += 2
            leader = bytes(buf[off:off + ln]).decode()
            off += ln
            self.regions[region.id] = region
            if leader:
                self.region_leaders[region.id] = leader
        self.pending_splits = {}
        if off + 4 <= len(buf):  # absent in pre-pending-split snapshots
            (np_,) = struct.unpack_from("<I", buf, off)
            off += 4
            for _ in range(np_):
                parent_id, child_id = struct.unpack_from("<qq", buf, off)
                off += 16
                self.pending_splits[parent_id] = child_id
        if off + 4 <= len(buf):  # absent in pre-zone snapshots
            (nz,) = struct.unpack_from("<I", buf, off)
            off += 4
            for _ in range(nz):
                (en,) = struct.unpack_from("<H", buf, off)
                off += 2
                ep = bytes(buf[off:off + en]).decode()
                off += en
                (zn,) = struct.unpack_from("<H", buf, off)
                off += 2
                zone = bytes(buf[off:off + zn]).decode()
                off += zn
                if ep in self.stores:
                    self.stores[ep].zone = zone
        self.pending_merges = {}
        if off + 4 <= len(buf):  # absent in pre-merge snapshots
            (nm,) = struct.unpack_from("<I", buf, off)
            off += 4
            for _ in range(nm):
                src_id, tgt_id = struct.unpack_from("<qq", buf, off)
                off += 16
                self.pending_merges[src_id] = tgt_id
        self.retired_regions = {}
        if off + 4 <= len(buf):  # absent in pre-merge snapshots
            (nt,) = struct.unpack_from("<I", buf, off)
            off += 4
            for _ in range(nt):
                src_id, tgt_id = struct.unpack_from("<qq", buf, off)
                off += 16
                self.retired_regions[src_id] = tgt_id
        return True


@dataclass
class RegionStats:
    """ONE region-stats record per region — the unified intake the PD
    split policy reads.  Key counts (the legacy ``approximate_keys``
    path) and heat rates (the fleet observability plane) land in the
    SAME record, so ``should_split`` — and item 2's heat-driven
    split/merge/move policy after it — has one place to look."""

    keys: int = 0
    writes_s: float = 0.0
    reads_s: float = 0.0
    bytes_in_s: float = 0.0
    bytes_out_s: float = 0.0
    # monotonic stamp of the last heat intake (0.0 = keys-only entry);
    # the staleness sweep zeroes rates whose reporter went silent — a
    # moved/evacuated leadership must not leave hot rates behind forever
    heat_at: float = 0.0

    @property
    def score(self) -> float:
        from tpuraft.util.heat import heat_score

        return heat_score(self.writes_s, self.reads_s,
                          self.bytes_in_s, self.bytes_out_s)


# graftcheck: loop-confined — every intake/policy path (heartbeat
# handlers, the staleness sweep, balancing) runs on the PD node's RPC
# loop; the metrics HTTP thread reads SNAPSHOT copies only (render
# methods list()/copy live dicts before iterating — the PR 13 rule)
class ClusterStatsManager:
    """Leader-side (non-replicated) stats: per-region key counts + heat
    rates (ONE record per region — see :class:`RegionStats`) and
    split/transfer decisions.

    Reference: ``pd:ClusterStatsManager`` — finds the region with the
    most keys above the split threshold; extended here with the heat
    intake the heartbeats report, top-K hot/cold ranking for the
    ClusterView, and hot-region detection (a region whose score crosses
    the fleet's heat percentile fires a ``hot_region`` flight-recorder
    event — the exact signal a split/move policy consumes).
    """

    # hot-region detection: a region is HOT when its score exceeds
    # max(hot_min_score, hot_factor x the fleet's BACKGROUND percentile
    # — the median, NOT a tail percentile: in a small fleet the hot
    # regions ARE the tail, so anchoring on p90 would set the bar at
    # 4x the hot set's own score and unflag exactly the regions the
    # detector exists to find); it cools at half the threshold
    # (hysteresis, no event flapping).  Below ``hot_min_population``
    # scored regions the threshold is undefined (infinity): a
    # half-reported bootstrap fleet must not mass-flag on a floor
    # computed from the first few rows.
    hot_percentile = 50.0
    hot_factor = 4.0
    hot_min_score = 2.0
    hot_min_population = 8
    # rates not re-reported for this long are zeroed by the sweep
    # (leadership moved and the new leader's heat sits under the noise
    # gate, or the region left the fleet) — keys are kept, matching
    # the legacy keys-only intake which never expired either
    heat_stale_s = 30.0

    def __init__(self, split_threshold_keys: int) -> None:
        self.split_threshold_keys = split_threshold_keys
        self._stats: dict[int, RegionStats] = {}
        self._inflight_splits: dict[int, float] = {}  # region -> deadline
        self._transfer_cooldown: dict[int, float] = {}  # region -> deadline
        # region -> (from_ep, to_ep, expiry): ordered but not yet
        # observed in region_leaders (overlaid onto balancing counts)
        self._pending_moves: dict[int, tuple[str, str, float]] = {}
        self._leader_term = -1      # last PD term balancing ran under
        self._grace_until = 0.0     # post-failover balancing pause
        # hot-region state: currently-hot set + cached threshold (the
        # percentile scan is O(regions), so it refreshes at most once
        # per second, not per intake row; None = undefined — heated
        # population below hot_min_population)
        self._hot: set[int] = set()
        self._hot_threshold: Optional[float] = None
        self._hot_recalc_at = 0.0
        self.hot_events = 0

    def note_leadership(self, term: int, cooldown_s: float) -> None:
        """Deterministic cooldown rebuild on PD leadership change
        (VERDICT r2 #9): cooldowns and pending moves are leader-local,
        so a new leader cannot know which transfers its predecessor
        ordered seconds ago — instead EVERY region starts the new term
        on one full cooldown, making an immediate re-transfer of a
        just-moved region structurally impossible."""
        if term == self._leader_term:
            return
        self._leader_term = term
        # graftcheck: allow(raw-clock) — PD-side post-failover grace window (real time)
        self._grace_until = time.monotonic() + cooldown_s
        self._transfer_cooldown.clear()
        self._pending_moves.clear()

    def _ent(self, region_id: int) -> RegionStats:
        ent = self._stats.get(region_id)
        if ent is None:
            ent = self._stats[region_id] = RegionStats()
        return ent

    def record(self, region_id: int, approximate_keys: int) -> None:
        self._ent(region_id).keys = approximate_keys

    def record_heat(self, region_id: int, writes_s: float, reads_s: float,
                    bytes_in_s: float, bytes_out_s: float) -> None:
        """Heat intake (heartbeat trailing field) into the SAME record
        the split policy reads; fires the hot_region detector."""
        ent = self._ent(region_id)
        ent.writes_s = writes_s
        ent.reads_s = reads_s
        ent.bytes_in_s = bytes_in_s
        ent.bytes_out_s = bytes_out_s
        # graftcheck: allow(raw-clock) — PD-side heat-report age stamp (real time)
        ent.heat_at = time.monotonic()
        self._note_hot(region_id, ent.score)

    def _note_hot(self, region_id: int, score: float) -> None:
        from tpuraft.util.trace import RECORDER

        self.maybe_sweep()
        thr = self._hot_threshold
        if thr is None:
            # threshold undefined (heated population below the gate):
            # flag nothing new AND cool nothing — standing flags must
            # not flap on a population-count transient
            return
        if region_id in self._hot:
            if score < thr / 2.0:
                self._hot.discard(region_id)
            return
        if score >= thr:
            self._hot.add(region_id)
            self.hot_events += 1
            # coalesced: a hotspot shift can re-flag a whole shard
            # family inside one heartbeat burst
            RECORDER.record_coalesced(
                "hot_region", str(region_id),
                score=round(score, 2), threshold=round(thr, 2))

    def maybe_sweep(self) -> None:
        """Run the staleness/threshold sweep if one is due (rate-bound
        to 1/s); called from heat intake AND from the view build, so a
        fleet that went silent still ages its standing rates out."""
        # graftcheck: allow(raw-clock) — PD-side heat staleness sweep (real time)
        now = time.monotonic()
        if now >= self._hot_recalc_at:
            self._hot_sweep(now)

    def _hot_sweep(self, now: float) -> None:
        """At most once per second: zero stale heat (a silent reporter
        must not leave standing rates in the view or the percentile
        base), refresh the threshold, and re-judge every currently
        flagged region against it — cooling must not wait for an
        intake row the noise gate may never send."""
        self._hot_recalc_at = now + 1.0
        stale = now - self.heat_stale_s
        heated = 0
        for ent in self._stats.values():
            if ent.heat_at <= 0.0:
                continue
            if ent.heat_at < stale:
                ent.writes_s = ent.reads_s = 0.0
                ent.bytes_in_s = ent.bytes_out_s = 0.0
                ent.heat_at = 0.0
            else:
                heated += 1
        if heated < self.hot_min_population:
            # undefined: too few live reporters to anchor a background
            # percentile.  No new flags, and LIVE standing flags stand
            # — a brief reporter dropout must not erase (then re-fire)
            # them; only flags whose own reporter went stale cool
            # (their rates were just zeroed — we know nothing anymore)
            self._hot_threshold = None
            for rid in list(self._hot):
                ent = self._stats.get(rid)
                if ent is None or ent.heat_at <= 0.0:
                    self._hot.discard(rid)
            return
        self._hot_threshold = max(
            self.hot_min_score,
            self.hot_factor * self._score_percentile(
                self.hot_percentile))
        for rid in list(self._hot):
            ent = self._stats.get(rid)
            if ent is None or ent.score < self._hot_threshold / 2.0:
                self._hot.discard(rid)

    def _score_percentile(self, p: float) -> float:
        """Nearest-rank percentile over the heated regions' scores
        (keys-only entries carry no load information and would drag
        the background estimate to zero)."""
        import math

        scores = sorted(ent.score for ent in self._stats.values()
                        if ent.heat_at > 0.0)
        if not scores:
            return 0.0
        idx = max(0, min(len(scores) - 1,
                         math.ceil(p / 100.0 * len(scores)) - 1))
        return scores[idx]

    def drop(self, region_id: int) -> None:
        """Region left the fleet (merged away): forget its stats so the
        cold ranking and hot set stop listing a dead id."""
        self._stats.pop(region_id, None)
        self._inflight_splits.pop(region_id, None)
        self._transfer_cooldown.pop(region_id, None)
        self._pending_moves.pop(region_id, None)
        self._hot.discard(region_id)

    def hot_regions(self) -> set[int]:
        return set(self._hot)

    def hot_count(self) -> int:
        """Flagged-region count via len() (GIL-atomic) — safe from the
        metrics exposition thread, unlike copying the live set."""
        return len(self._hot)

    def region_stats(self, region_id: int) -> RegionStats:
        return self._stats.get(region_id) or RegionStats()

    def top_hot(self, k: int) -> list[tuple[int, RegionStats]]:
        """Hottest k regions by score, descending (zero-score regions
        excluded — a silent fleet has no hot regions)."""
        return sorted(((rid, ent) for rid, ent in self._stats.items()
                       if ent.score > 0.0),
                      key=lambda kv: -kv[1].score)[:max(0, k)]

    def top_cold(self, k: int) -> list[tuple[int, RegionStats]]:
        """Coldest k regions by score, ascending — merge candidates."""
        return sorted(self._stats.items(),
                      key=lambda kv: kv[1].score)[:max(0, k)]

    def last_keys(self, region_id: int) -> int:
        """Last reported key count (delta-batched stores skip unchanged
        regions, so the policy pass reads the standing estimate)."""
        ent = self._stats.get(region_id)
        return ent.keys if ent is not None else 0

    def split_pacing_ok(self, region_id: int) -> bool:
        """Split pacing gate shared by the key-count path and the
        lifecycle plane's heat-driven path: False while a split of this
        region is in flight / cooling down."""
        # graftcheck: allow(raw-clock) — PD-side split cooldown window (real time)
        now = time.monotonic()
        self._inflight_splits = {r: d for r, d in
                                 self._inflight_splits.items() if d > now}
        return region_id not in self._inflight_splits

    def should_split(self, region_id: int) -> bool:
        if self.split_threshold_keys <= 0:
            return False
        if not self.split_pacing_ok(region_id):
            return False
        return self.last_keys(region_id) >= self.split_threshold_keys

    def mark_split_issued(self, region_id: int, cooldown_s: float = 30.0
                          ) -> None:
        # graftcheck: allow(raw-clock) — PD-side split cooldown window (real time)
        self._inflight_splits[region_id] = time.monotonic() + cooldown_s
        ent = self._stats.get(region_id)
        if ent is not None:
            # keys reset (the split empties the parent's estimate); the
            # heat rates stay — load keeps landing until clients re-route
            ent.keys = 0

    # -- leader balancing (reference: ClusterStatsManager's busiest-store
    # accounting feeding rebalance) ------------------------------------

    def pick_transfer_target(self, region: Region, leader_ep: str,
                             region_leaders: dict[int, str],
                             cooldown_s: float,
                             zones: Optional[dict[str, str]] = None,
                             zone_counts: Optional[dict[str, int]] = None,
                             health: Optional[dict[str, str]] = None
                             ) -> Optional[str]:
        """If ``leader_ep`` leads at least 2 more regions than the
        least-loaded peer of ``region``, return that peer as the
        transfer target (with a per-region cooldown so one imbalance
        doesn't spray repeated transfers).  Ties between equally-loaded
        targets break FIRST on zone leader counts when store zone
        labels are known (``zones``: endpoint -> zone) — leaders spread
        across failure domains, not just across stores — then on a
        per-region hash so concurrent decisions spread across stores
        instead of herding onto the first one.  Witness replicas
        (``/witness``-suffixed peers) can never lead and are never
        targets, like learners.

        Decisions overlay the PENDING moves this manager already
        ordered but has not yet observed in ``region_leaders`` —
        without that, one heartbeat burst sees the same stale counts
        for every region and orders the whole imbalance moved at once,
        overshooting into a permanent oscillation (observed as
        (6,0,0) → (0,2,4) → (2,4,0) → ... thrash every cooldown
        period).

        Gray failures (``health``: endpoint -> self-reported level):
        a SICK store is never a transfer TARGET (moving leadership onto
        a gray store helps nobody), DEGRADED stores lose ties, and a
        SICK *leader* is DRAINED — the least-loaded healthy peer is
        picked even when the usual >=2 leader-count imbalance is
        absent (cooldown and post-failover grace still pace it)."""
        # graftcheck: allow(raw-clock) — PD-side cooldown pacing; the PD is not a store and has no injected clock
        now = time.monotonic()
        if now < self._grace_until:
            return None  # post-failover grace (note_leadership)
        self._transfer_cooldown = {
            r: d for r, d in self._transfer_cooldown.items() if d > now}
        self._pending_moves = {
            r: m for r, m in self._pending_moves.items()
            if m[2] > now and region_leaders.get(r) != m[1]}
        if region.id in self._transfer_cooldown:
            return None
        counts: dict[str, int] = {}
        for _, ep in region_leaders.items():
            counts[ep] = counts.get(ep, 0) + 1
        # overlay in-flight moves: the source already "lost" the lease,
        # the destination already "gained" it
        for rid, (src, dst, _) in self._pending_moves.items():
            if region_leaders.get(rid) == src:
                counts[src] = counts.get(src, 0) - 1
                counts[dst] = counts.get(dst, 0) + 1
        my = counts.get(leader_ep, 0)
        health = health or {}
        _H_RANK = {"": 0, "healthy": 0, "degraded": 1, "sick": 2}

        def h_rank(p: str) -> int:
            return _H_RANK.get(health.get(_peer_endpoint(p), ""), 0)

        leader_sick = health.get(_peer_endpoint(leader_ep), "") == "sick"
        # learners are read-only replicas and witnesses hold no payload
        # — neither can lead, so neither is a leadership target; a SICK
        # store is excluded too (never place leaders onto gray stores)
        candidates = [p for p in region.peers
                      if p != leader_ep and not p.endswith("/learner")
                      and not p.endswith("/witness")
                      and h_rank(p) < 2]
        if not candidates:
            return None
        if zones and zone_counts is None:
            # single-region path builds its own histogram; the BATCH
            # heartbeat precomputes it once per request (an O(regions)
            # scan here per region made the batch pass O(regions^2))
            zone_counts = zone_leader_histogram(region_leaders, zones)

        def zone_load(p: str) -> int:
            if not zones:
                return 0
            return zone_counts.get(zones.get(_peer_endpoint(p), ""), 0)

        target = min(candidates,
                     key=lambda p: (h_rank(p), counts.get(p, 0),
                                    zone_load(p),
                                    hash((region.id, p)) & 0xffff))
        if not leader_sick and my - counts.get(target, 0) < 2:
            return None
        self._transfer_cooldown[region.id] = now + cooldown_s
        self._pending_moves[region.id] = (
            leader_ep, target, now + 2 * cooldown_s)
        return target


@dataclass
class PlacementDriverOptions:
    endpoints: list[str] = field(default_factory=list)  # PD cluster peers
    election_timeout_ms: int = 1000
    data_path: str = ""
    # emit a RANGE_SPLIT instruction when a region reports >= this many
    # keys (0 disables auto-split)
    split_threshold_keys: int = 0
    # emit TRANSFER_LEADER instructions to even out per-store leader
    # counts (reference: CliServiceImpl#rebalance driven by PD stats)
    balance_leaders: bool = False
    # per-region pause between ordered transfers, so one imbalance
    # doesn't spray repeated TRANSFER_LEADER at a region mid-move
    transfer_cooldown_s: float = 5.0
    initial_regions: list[Region] = field(default_factory=list)
    # fleet observability: serve PD-side Prometheus text at GET
    # /metrics on the PD's OWN stdlib listener (None = off, 0 =
    # ephemeral — the bound port lands in
    # PlacementDriverServer.metrics_http_port, N = that port).  The
    # same render answers the ``pd_describe_metrics`` RPC regardless.
    metrics_port: Optional[int] = None
    metrics_host: str = "127.0.0.1"
    # -- region lifecycle engine (ISSUE 20) ----------------------------------
    # master switch: run the placement policy (heat-driven splits, cold
    # merges, cross-store moves) over the heartbeat stream.  The policy
    # itself lives in tpuraft/rheakv/placement.py; the knobs below
    # mirror LifecycleOptions.
    lifecycle: bool = False
    lifecycle_heat_split_min_keys: int = 32
    lifecycle_merge_max_score: float = 0.05
    lifecycle_merge_max_keys: int = 4096
    lifecycle_merge_cooldown_s: float = 10.0
    lifecycle_max_inflight_merges: int = 2
    lifecycle_min_regions: int = 4
    lifecycle_move_imbalance: int = 2
    lifecycle_move_cooldown_s: float = 10.0
    lifecycle_max_inflight_moves: int = 2


class PlacementDriverServer:
    """One PD cluster member: raft node + pd_* RPC processors."""

    def __init__(self, opts: PlacementDriverOptions, server_id: str,
                 rpc_server, transport) -> None:
        self.opts = opts
        self.server_id = PeerId.parse(server_id)
        self.rpc_server = rpc_server
        self.transport = transport
        self.node_manager = NodeManager(rpc_server)
        self.fsm = PDMetadataFSM()
        self.stats = ClusterStatsManager(opts.split_threshold_keys)
        # region lifecycle engine (ISSUE 20): the policy half lives in
        # placement.py; None = lifecycle off (legacy PD behavior)
        self.placement = None
        if opts.lifecycle:
            from tpuraft.rheakv.placement import (LifecycleOptions,
                                                  PlacementEngine)

            self.placement = PlacementEngine(LifecycleOptions(
                heat_split_min_keys=opts.lifecycle_heat_split_min_keys,
                merge_max_score=opts.lifecycle_merge_max_score,
                merge_max_keys=opts.lifecycle_merge_max_keys,
                merge_cooldown_s=opts.lifecycle_merge_cooldown_s,
                max_inflight_merges=opts.lifecycle_max_inflight_merges,
                min_regions=opts.lifecycle_min_regions,
                move_imbalance=opts.lifecycle_move_imbalance,
                move_cooldown_s=opts.lifecycle_move_cooldown_s,
                max_inflight_moves=opts.lifecycle_max_inflight_moves))
        self._group: Optional[RaftGroupService] = None
        for method, handler in [
            ("pd_list_regions", self._list_regions),
            ("pd_list_stores", self._list_stores),
            ("pd_store_heartbeat", self._store_heartbeat),
            ("pd_region_heartbeat", self._region_heartbeat),
            ("pd_store_heartbeat_batch", self._store_heartbeat_batch),
            ("pd_report_split", self._report_split),
            ("pd_report_merge", self._report_merge),
            ("pd_create_region_id", self._create_region_id),
            ("pd_cluster_describe", self._cluster_describe),
            ("pd_describe_metrics", self._describe_metrics),
        ]:
            rpc_server.register(method, handler)
        # delta-batch protocol state (leader-local, like ClusterStats):
        # store endpoint -> PD term of the last FULL batch seen.  A new
        # PD leader's stats are cold, so it answers need_full until each
        # store resyncs — deltas alone can't rebuild the key counts its
        # split/balance decisions read.
        self._batch_synced: dict[str, int] = {}
        # gray-failure state (leader-local, ephemeral like ClusterStats
        # — re-derived from heartbeats after failover): store endpoint
        # -> self-reported health level ("healthy"/"degraded"/"sick")
        self._store_health: dict[str, str] = {}
        # tick-plane occupancy (leader-local, from heartbeat trailing
        # fields): store endpoint -> (replicas, replicas_quiescent);
        # folded into the ClusterView's fleet hibernation fraction
        self._store_occupancy: dict[str, tuple[int, int]] = {}
        # fleet-observability counters (pd_describe_metrics / HTTP)
        self.hb_rpcs = 0            # legacy per-store heartbeats
        self.hb_region_rpcs = 0     # legacy per-region heartbeats
        self.hb_batch_rpcs = 0      # delta-batched heartbeats
        self.hb_delta_rows = 0      # region delta rows carried
        self.hb_heat_rows = 0       # heat rows carried
        self.splits_ordered = 0
        self.transfers_ordered = 0
        self.cluster_describes = 0
        # lifecycle counters (the soak exit gate + admin plane read
        # these; heat_splits_ordered also counts into splits_ordered)
        self.heat_splits_ordered = 0
        self.merges_ordered = 0       # KIND_MERGE instructions issued
        self.merges_completed = 0     # _CMD_MERGE finalized
        self.moves_ordered = 0        # KIND_MOVE instructions issued
        self._metrics_httpd = None
        self.metrics_http_port: Optional[int] = None

    @property
    def node(self):
        return self._group.node if self._group else None

    async def start(self) -> None:
        node_opts = NodeOptions(
            election_timeout_ms=self.opts.election_timeout_ms,
            initial_conf=Configuration.parse(",".join(self.opts.endpoints)),
            fsm=self.fsm,
        )
        if self.opts.data_path:
            base = (f"{self.opts.data_path}/pd_"
                    f"{self.server_id.ip}_{self.server_id.port}")
            node_opts.log_uri = f"file://{base}/log"
            node_opts.raft_meta_uri = f"file://{base}/meta"
            node_opts.snapshot_uri = f"file://{base}/snapshot"
        else:
            node_opts.log_uri = "memory://"
            node_opts.raft_meta_uri = "memory://"
        self._group = RaftGroupService(
            PD_GROUP_ID, self.server_id, node_opts, self.node_manager,
            self.transport)
        node = await self._group.start()
        # seed the initial region layout once the PD leader emerges
        if self.opts.initial_regions:
            self._seed_regions = list(self.opts.initial_regions)
        else:
            self._seed_regions = []
        if self.opts.metrics_port is not None:
            from tpuraft.util.metrics_http import MetricsHttpServer

            self._metrics_httpd = MetricsHttpServer(
                self.opts.metrics_host, self.opts.metrics_port,
                self.metrics_text,
                name=f"pd-metrics-http-{self.server_id}")
            self.metrics_http_port = self._metrics_httpd.port

    async def shutdown(self) -> None:
        if self._metrics_httpd is not None:
            import asyncio

            httpd = self._metrics_httpd
            self._metrics_httpd = None
            await asyncio.get_running_loop().run_in_executor(
                None, httpd.shutdown_blocking)
        if self._group:
            await self._group.shutdown()
            self._group = None

    # -- raft plumbing -------------------------------------------------------

    def _not_leader(self, resp_cls):
        leader = self.node.get_leader_id() if self.node else None
        redirect = ""
        if leader is not None and not leader.is_empty():
            redirect = leader.endpoint
        return resp_cls(success=False, redirect=redirect, msg="not PD leader")

    async def _apply(self, data: bytes):
        import asyncio

        fut = asyncio.get_running_loop().create_future()

        class _Done:
            result = None

            def __call__(self, status: Status) -> None:
                if not fut.done():
                    fut.set_result((status, self.result))

        await self.node.apply(Task(data=data, done=_Done()))
        status, result = await fut
        if not status.is_ok():
            raise RuntimeError(str(status))
        return result

    async def _maybe_seed(self) -> None:
        """Replicate the initial region layout once (leader, first contact)."""
        if not self._seed_regions or not self.fsm or self.fsm.regions:
            return
        for region in self._seed_regions:
            payload = struct.pack("<H", 0) + region.encode()
            await self._apply(_cmd(_CMD_REGION_UPSERT, payload))
        self._seed_regions = []

    # -- processors ----------------------------------------------------------

    async def _list_regions(self, req: ListRegionsRequest
                            ) -> ListRegionsResponse:
        node = self.node
        if node is None or not node.is_leader():
            return self._not_leader(ListRegionsResponse)
        await self._maybe_seed()
        await node.read_index()
        return ListRegionsResponse(
            regions=[r.encode() for r in self.fsm.regions.values()])

    async def _list_stores(self, req: ListStoresRequest) -> ListStoresResponse:
        node = self.node
        if node is None or not node.is_leader():
            return self._not_leader(ListStoresResponse)
        await node.read_index()
        return ListStoresResponse(
            stores=[encode_store_meta(r.store_id, r.endpoint, r.zone)
                    for r in self.fsm.stores.values()])

    def _region_changed(self, region: Region, leader: str = "") -> bool:
        cur = self.fsm.regions.get(region.id)
        if cur is None:
            return True
        if (cur.epoch.conf_ver, cur.epoch.version,
                cur.start_key, cur.end_key, cur.peers) != \
                (region.epoch.conf_ver, region.epoch.version,
                 region.start_key, region.end_key, region.peers):
            return True
        return bool(leader) and \
            self.fsm.region_leaders.get(region.id) != leader

    async def _store_heartbeat(self, req: StoreHeartbeatRequest
                               ) -> StoreHeartbeatResponse:
        node = self.node
        if node is None or not node.is_leader():
            return self._not_leader(StoreHeartbeatResponse)
        self.hb_rpcs += 1
        await self._maybe_seed()
        # only replicate *changes* — heartbeats repeat at 1s cadence and
        # must not grow the PD log when nothing moved
        zone = getattr(req, "zone", "")
        self._note_store_health(req.endpoint, getattr(req, "health", ""))
        cur = self.fsm.stores.get(req.endpoint)
        if cur is None or cur.store_id != req.store_id \
                or (zone and cur.zone != zone):
            await self._apply(_cmd(
                _CMD_STORE_UPSERT,
                encode_store_meta(req.store_id, req.endpoint, zone)))
        for blob in req.regions:
            region = Region.decode(blob)
            if self._region_changed(region):
                payload = struct.pack("<H", 0) + region.encode()
                await self._apply(_cmd(_CMD_REGION_UPSERT, payload))
        return StoreHeartbeatResponse()

    async def _region_heartbeat(self, req: RegionHeartbeatRequest
                                ) -> RegionHeartbeatResponse:
        node = self.node
        if node is None or not node.is_leader():
            return self._not_leader(RegionHeartbeatResponse)
        self.hb_region_rpcs += 1
        await self._maybe_seed()
        instructions = await self._region_hb_core(
            Region.decode(req.region), req.leader, req.approximate_keys)
        return RegionHeartbeatResponse(
            instructions=[i.encode() for i in instructions])

    async def _store_heartbeat_batch(self, req) -> "object":
        """Delta-batched store reporting: one RPC per store per interval
        with only CHANGED region rows — the PD-plane counterpart of
        group quiescence (idle stores cost one near-empty RPC/s, not
        O(regions)).  Replication stays change-driven exactly as the
        per-region path: an empty batch applies nothing."""
        from tpuraft.rheakv.pd_messages import (
            StoreHeartbeatBatchResponse,
            decode_region_delta,
        )

        node = self.node
        if node is None or not node.is_leader():
            return self._not_leader(StoreHeartbeatBatchResponse)
        self.hb_batch_rpcs += 1
        self.hb_delta_rows += len(req.deltas)
        await self._maybe_seed()
        zone = getattr(req, "zone", "")
        self._note_store_health(req.endpoint, getattr(req, "health", ""))
        # fleet observability intake: heat rows ride their own trailing
        # field (independent of deltas — heat changes at its own
        # cadence), occupancy feeds the hibernation fraction
        from tpuraft.util.heat import decode_heat_rows

        heat_rows = decode_heat_rows(getattr(req, "heat", b""))
        self.hb_heat_rows += len(heat_rows)
        for rid, w, r, bi, bo in heat_rows:
            self.stats.record_heat(rid, w, r, bi, bo)
        replicas = getattr(req, "replicas", 0)
        if replicas:
            self._store_occupancy[req.endpoint] = (
                replicas, getattr(req, "replicas_quiescent", 0))
        else:
            self._store_occupancy.pop(req.endpoint, None)
        cur = self.fsm.stores.get(req.endpoint)
        if cur is None or cur.store_id != req.store_id \
                or (zone and cur.zone != zone):
            await self._apply(_cmd(
                _CMD_STORE_UPSERT,
                encode_store_meta(req.store_id, req.endpoint, zone)))
        instructions: list[Instruction] = []
        reported: set[int] = set()
        # zone bookkeeping is invariant across the batch: compute the
        # endpoint->zone map and the leaders-per-zone histogram ONCE
        # instead of per region (O(regions^2) on a 2K-region resync)
        zones = self._store_zones()
        zone_counts = zone_leader_histogram(
            self.fsm.region_leaders, zones) if zones else None
        for blob in req.deltas:
            region_blob, leader, keys = decode_region_delta(blob)
            region = Region.decode(region_blob)
            reported.add(region.id)
            instructions.extend(await self._region_hb_core(
                region, leader, keys, zones, zone_counts))
        # policy pass over the store's UNREPORTED led regions: deltas
        # only flow when something changed, but split re-issue and
        # leader balancing are PD-side decisions that must keep running
        # over the idle majority (the per-region path got this for free
        # by re-reporting every region every interval) — pure in-memory
        # checks, no replication for unchanged rows
        for rid, leader in list(self.fsm.region_leaders.items()):
            if rid in reported:
                continue
            region = self.fsm.regions.get(rid)
            if region is None or \
                    PeerId.parse(leader).endpoint != req.endpoint:
                continue
            instructions.extend(await self._region_hb_core(
                region, leader, self.stats.last_keys(rid),
                zones, zone_counts))
        # lifecycle decisions (ISSUE 20): one merge + one move pick per
        # batch, scoped to regions THIS store leads (instructions ride
        # its heartbeat response).  Decisions replicate before the
        # instruction leaves, so a PD failover re-issues the same pair.
        if self.placement is not None:
            instructions.extend(await self._lifecycle_pass(
                req.endpoint, zones))
        term = node.current_term
        if req.full:
            self._batch_synced[req.endpoint] = term
        # this PD leader's stats (key counts, cooldowns) are term-local:
        # until the store resyncs under THIS term, ask for a full batch
        # so split/balance decisions never run on a cold picture
        need_full = self._batch_synced.get(req.endpoint) != term
        return StoreHeartbeatBatchResponse(
            instructions=[i.encode() for i in instructions],
            need_full=need_full)

    def _store_zones(self) -> dict[str, str]:
        return {ep: rec.zone for ep, rec in self.fsm.stores.items()
                if rec.zone}

    def _note_store_health(self, endpoint: str, health: str) -> None:
        if health:
            self._store_health[endpoint] = health
        else:
            # "" = the store runs no scoring (or predates it): unknown,
            # treated healthy — never leave a stale SICK verdict behind
            self._store_health.pop(endpoint, None)

    async def _region_hb_core(self, region: Region, leader: str,
                              approximate_keys: int,
                              zones: Optional[dict] = None,
                              zone_counts: Optional[dict] = None
                              ) -> list[Instruction]:
        """Shared by the per-region and delta-batched paths: epoch-
        guarded metadata upsert, stats, split/balance instructions.
        ``zones``/``zone_counts`` are precomputed ONCE per batch by the
        batch handler (None = compute here, the single-region path)."""
        node = self.node
        if self._region_changed(region, leader):
            lp = leader.encode()
            payload = struct.pack("<H", len(lp)) + lp + region.encode()
            await self._apply(_cmd(_CMD_REGION_UPSERT, payload))
        self.stats.record(region.id, approximate_keys)
        instructions: list[Instruction] = []
        # NOTE: the PD never finalizes a pending merge from the
        # TARGET's coverage alone.  The target's extended range proves
        # the absorb committed, but NOT that the source's MERGE_COMMIT
        # is durable — if the source leader crashed in that window,
        # tombstoning here would stop the KIND_MERGE re-issue (the only
        # path that proposes MERGE_COMMIT) and leave the sealed source
        # group alive forever, serving stale linearizable GETs for
        # keyspace the target now owns.  Finalization waits for a
        # pd_report_merge from the source group (its leader after
        # commit, every replica at MERGE_COMMIT apply, and any store
        # answering a re-issued instruction for a region it already
        # retired); until one lands, the re-issue arm below keeps
        # driving the source to completion.
        # -- lifecycle: pending-merge re-issue ------------------------------
        pending_merge_tgt = self.fsm.pending_merges.get(region.id)
        if pending_merge_tgt is not None:
            # merging away: re-issue the replicated decision (paced —
            # the store defers mid-conf-change, the absorb can bounce
            # on a stale target leader) and run NO other policy on it
            if self.placement is not None \
                    and self.placement.merge_reissue_due(region.id):
                self.merges_ordered += 1
                instructions.append(Instruction(
                    kind=Instruction.KIND_MERGE, region_id=region.id,
                    new_region_id=pending_merge_tgt,
                    target_peer=self.fsm.region_leaders.get(
                        pending_merge_tgt, "")))
            return instructions
        # an absorb TARGET must not split mid-merge (the extension and
        # the split would race over the same metadata)
        merge_target = region.id in set(self.fsm.pending_merges.values())
        keys_fire = not merge_target and self.stats.should_split(region.id)
        heat_fire = (self.placement is not None and not merge_target
                     and self.placement.should_heat_split(
                         region.id, self.stats)
                     and self.stats.split_pacing_ok(region.id))
        pending_child = self.fsm.pending_splits.get(region.id)
        if pending_child is not None:
            # a split was already ORDERED (possibly by a previous PD
            # leader — the decision is replicated): re-issue the SAME
            # child id while the region still reports oversize (or the
            # heat detector still flags it), paced by the leader-local
            # cooldown.  Never allocate a duplicate.
            if keys_fire or heat_fire:
                self.stats.mark_split_issued(region.id)
                self.splits_ordered += 1
                instructions.append(Instruction(
                    kind=Instruction.KIND_SPLIT, region_id=region.id,
                    new_region_id=pending_child))
        elif keys_fire or heat_fire:
            new_id = await self._apply(_cmd(
                _CMD_SPLIT_ISSUED, struct.pack("<q", region.id)))
            self.stats.mark_split_issued(region.id)
            self.splits_ordered += 1
            if heat_fire and not keys_fire:
                from tpuraft.util.trace import RECORDER

                # heat-DRIVEN split: the detector fired below the
                # key-count threshold — the lifecycle plane's signal
                self.heat_splits_ordered += 1
                if self.placement is not None:
                    self.placement.note_decision(
                        "heat_split", region=region.id, child=new_id)
                RECORDER.record_coalesced("heat_split", str(region.id),
                                          child=new_id)
            instructions.append(Instruction(
                kind=Instruction.KIND_SPLIT, region_id=region.id,
                new_region_id=new_id))
        elif self.opts.balance_leaders or (
                self._store_health.get(_peer_endpoint(leader)) == "sick"):
            # the second arm is the gray-failure DRAIN: even with
            # balancing off, a SICK leader store sheds its leases onto
            # healthy peers (pick_transfer_target skips the >=2
            # imbalance threshold for a sick source and never targets
            # another sick store)
            self.stats.note_leadership(node.current_term,
                                       self.opts.transfer_cooldown_s)
            if zones is None:
                zones = self._store_zones()
            target = self.stats.pick_transfer_target(
                region, leader, self.fsm.region_leaders,
                cooldown_s=self.opts.transfer_cooldown_s,
                zones=zones, zone_counts=zone_counts,
                health=self._store_health)
            if target is not None:
                self.transfers_ordered += 1
                instructions.append(Instruction(
                    kind=Instruction.KIND_TRANSFER_LEADER,
                    region_id=region.id, target_peer=target))
        return instructions

    async def _lifecycle_pass(self, store_ep: str,
                              zones: Optional[dict] = None
                              ) -> list[Instruction]:
        """Batch-scoped lifecycle decisions: at most one cold-merge pick
        and one cross-store move pick per heartbeat batch, both limited
        to regions led from ``store_ep`` (the instruction rides this
        store's response).  A merge decision replicates as a pending
        (source -> target) pair BEFORE the instruction leaves the PD —
        a failover re-issues the same pair; a move needs no replication
        (apply_move is retry-safe and re-picked from live imbalance)."""
        from tpuraft.util.trace import RECORDER

        placement = self.placement
        node = self.node
        placement.note_term(node.current_term,
                            max(placement.opts.merge_cooldown_s,
                                placement.opts.move_cooldown_s))
        out: list[Instruction] = []
        self.stats.maybe_sweep()
        pick = placement.pick_merge(
            self.fsm.regions, self.fsm.region_leaders, store_ep,
            self.stats, self.fsm.pending_merges, self.fsm.pending_splits)
        if pick is not None:
            src, tgt = pick
            tgt = await self._apply(_cmd(
                _CMD_MERGE_ISSUED, struct.pack("<qq", src, tgt)))
            self.merges_ordered += 1
            placement.note_decision("merge", region=src, into=tgt)
            RECORDER.record("region_merge_ordered", str(src), into=tgt)
            out.append(Instruction(
                kind=Instruction.KIND_MERGE, region_id=src,
                new_region_id=tgt,
                target_peer=self.fsm.region_leaders.get(tgt, "")))
        mv = placement.pick_move(
            self.fsm.regions, self.fsm.region_leaders, store_ep,
            list(self.fsm.stores.keys()),
            zones if zones is not None else self._store_zones(),
            self._store_health, self.fsm.pending_merges,
            self.fsm.pending_splits)
        if mv is not None:
            rid, src_p, dst_ep = mv
            self.moves_ordered += 1
            placement.note_decision("move", region=rid, src=src_p,
                                    dst=dst_ep)
            RECORDER.record("region_move_ordered", str(rid),
                            src=src_p, dst=dst_ep)
            out.append(Instruction(
                kind=Instruction.KIND_MOVE, region_id=rid,
                target_peer=dst_ep, src_peer=src_p))
        return out

    # -- fleet observability: cluster view + metrics exposition --------------

    def _build_cluster_view(self, top_k: int = 8) -> dict:
        """Fold everything the PD leader knows into one dict: per-store
        roster (zone, health, leader count, occupancy), per-zone access
        rates, top-K hot/cold regions, the sick-store roster and the
        fleet hibernation fraction.  Leader-local like ClusterStats —
        rebuilt from heartbeats after a failover."""
        top_k = max(1, min(top_k or 8, 64))
        self.stats.maybe_sweep()
        leaders_per_ep: dict[str, int] = {}
        for leader in self.fsm.region_leaders.values():
            ep = _peer_endpoint(leader)
            leaders_per_ep[ep] = leaders_per_ep.get(ep, 0) + 1
        stores = []
        for rec in self.fsm.stores.values():
            occ = self._store_occupancy.get(rec.endpoint)
            stores.append({
                "endpoint": rec.endpoint,
                "zone": rec.zone,
                "health": self._store_health.get(rec.endpoint, ""),
                "leaders": leaders_per_ep.get(rec.endpoint, 0),
                "replicas": occ[0] if occ else 0,
                "replicas_quiescent": occ[1] if occ else 0,
            })
        # per-zone rates: each led region's heat lands on its leader's
        # zone ("" = unlabeled stores)
        zones = self._store_zones()
        zone_rates: dict[str, dict] = {}
        for rid, leader in self.fsm.region_leaders.items():
            ent = self.stats.region_stats(rid)
            if ent.writes_s == 0.0 and ent.reads_s == 0.0:
                continue
            z = zones.get(_peer_endpoint(leader), "")
            zr = zone_rates.setdefault(z, {"writes_s": 0.0, "reads_s": 0.0})
            zr["writes_s"] += ent.writes_s
            zr["reads_s"] += ent.reads_s
        zone_rates = {z: {k: round(v, 2) for k, v in zr.items()}
                      for z, zr in zone_rates.items()}

        def _region_row(rid: int, ent) -> dict:
            return {
                "region": rid,
                "leader": self.fsm.region_leaders.get(rid, ""),
                "score": round(ent.score, 2),
                "writes_s": round(ent.writes_s, 2),
                "reads_s": round(ent.reads_s, 2),
                "bytes_in_s": round(ent.bytes_in_s, 1),
                "bytes_out_s": round(ent.bytes_out_s, 1),
                "keys": ent.keys,
            }

        replicas = sum(o[0] for o in self._store_occupancy.values())
        quiescent = sum(o[1] for o in self._store_occupancy.values())
        lifecycle = None
        if self.placement is not None:
            lifecycle = {
                "pending_merges": {str(s): t for s, t
                                   in self.fsm.pending_merges.items()},
                "retired_regions": len(self.fsm.retired_regions),
                "recent": self.placement.recent_decisions(),
                "heat_splits_ordered": self.heat_splits_ordered,
                "merges_ordered": self.merges_ordered,
                "merges_completed": self.merges_completed,
                "moves_ordered": self.moves_ordered,
            }
        return {
            "term": self.node.current_term if self.node else 0,
            "stores": stores,
            "regions": len(self.fsm.regions),
            "zone_rates": zone_rates,
            "hot": [_region_row(rid, ent)
                    for rid, ent in self.stats.top_hot(top_k)],
            "cold": [_region_row(rid, ent)
                     for rid, ent in self.stats.top_cold(top_k)],
            "hot_flagged": sorted(self.stats.hot_regions()),
            "sick_stores": sorted(
                ep for ep, lvl in self._store_health.items()
                if lvl == "sick"),
            "hibernation": {
                "replicas": replicas,
                "quiescent": quiescent,
                "fraction": round(quiescent / replicas, 4)
                if replicas else 0.0,
            },
            # lifecycle plane (None = policy off — legacy PD behavior)
            "lifecycle": lifecycle,
        }

    async def _cluster_describe(self, req) -> "object":
        import json

        from tpuraft.rheakv.pd_messages import ClusterDescribeResponse

        node = self.node
        if node is None or not node.is_leader():
            return self._not_leader(ClusterDescribeResponse)
        self.cluster_describes += 1
        view = self._build_cluster_view(getattr(req, "top_k", 8))
        return ClusterDescribeResponse(view_json=json.dumps(view))

    def metrics_text(self) -> str:
        """PD-side Prometheus text: heartbeat/instruction counters plus
        fleet gauges (stores, regions, sick stores, hot regions,
        hibernation).  Served by the ``pd_describe_metrics`` RPC and
        the optional HTTP listener; reads are plain ints/floats
        (best-effort consistency from the exposition thread)."""
        from tpuraft.util.metrics import prometheus_text

        counters = {
            "pd_hb_rpcs": self.hb_rpcs,
            "pd_hb_region_rpcs": self.hb_region_rpcs,
            "pd_hb_batch_rpcs": self.hb_batch_rpcs,
            "pd_hb_delta_rows": self.hb_delta_rows,
            "pd_hb_heat_rows": self.hb_heat_rows,
            "pd_splits_ordered": self.splits_ordered,
            "pd_transfers_ordered": self.transfers_ordered,
            "pd_cluster_describes": self.cluster_describes,
            "pd_hot_region_events": self.stats.hot_events,
            "pd_heat_splits_ordered": self.heat_splits_ordered,
            "pd_merges_ordered": self.merges_ordered,
            "pd_merges_completed": self.merges_completed,
            "pd_moves_ordered": self.moves_ordered,
        }
        # C-atomic list() snapshots: this render runs on the metrics
        # HTTP daemon thread while heartbeats mutate these dicts on the
        # event loop — a bytecode-level genexpr over the live .values()
        # view can raise "dictionary changed size during iteration"
        # (the store side fixed this class with counters_snapshot())
        occ = list(self._store_occupancy.values())
        health = list(self._store_health.values())
        replicas = sum(o[0] for o in occ)
        quiescent = sum(o[1] for o in occ)
        node = self.node
        gauges = {
            "pd_is_leader": int(bool(node and node.is_leader())),
            "pd_stores": len(self.fsm.stores),
            "pd_regions": len(self.fsm.regions),
            "pd_sick_stores": sum(1 for lvl in health if lvl == "sick"),
            "pd_hot_regions": self.stats.hot_count(),
            "pd_pending_merges": len(self.fsm.pending_merges),
            "pd_replicas": replicas,
            "pd_replicas_quiescent": quiescent,
            "pd_hibernation_fraction":
                round(quiescent / replicas, 4) if replicas else 0.0,
        }
        return prometheus_text(counters, gauges,
                               labels={"pd": str(self.server_id)})

    async def _describe_metrics(self, req) -> "object":
        from tpuraft.rpc.cli_messages import DescribeMetricsResponse

        return DescribeMetricsResponse(text=self.metrics_text())

    async def _report_split(self, req: ReportSplitRequest
                            ) -> ReportSplitResponse:
        node = self.node
        if node is None or not node.is_leader():
            return self._not_leader(ReportSplitResponse)
        parent = req.parent
        payload = struct.pack("<I", len(parent)) + parent + req.child
        await self._apply(_cmd(_CMD_SPLIT, payload))
        return ReportSplitResponse()

    async def _report_merge(self, req) -> "object":
        """Lifecycle plane: the source store reports a COMPLETED merge
        (seal + absorb + commit all applied) — finalize the replicated
        metadata.  Idempotent: a client retry (or the heartbeat-driven
        finalization racing this report) finds the source already
        popped and applies a no-op."""
        from tpuraft.rheakv.pd_messages import ReportMergeResponse

        node = self.node
        if node is None or not node.is_leader():
            return self._not_leader(ReportMergeResponse)
        fresh = await self._apply(_cmd(_CMD_MERGE, struct.pack(
            "<qq", req.source_region_id, req.target_region_id)))
        if fresh:
            self.merges_completed += 1
            self.stats.drop(req.source_region_id)
        return ReportMergeResponse()

    async def _create_region_id(self, req: CreateRegionIdRequest
                                ) -> CreateRegionIdResponse:
        node = self.node
        if node is None or not node.is_leader():
            return self._not_leader(CreateRegionIdResponse)
        rid = await self._apply(_cmd(_CMD_ALLOC_ID))
        return CreateRegionIdResponse(region_id=rid)
