"""Placement driver RPC messages.

Reference parity: the PD request/response protocol under
``rhea:cmd/pd/*`` (GetClusterInfo, StoreHeartbeat, RegionHeartbeat,
CreateRegionId...) — SURVEY.md §3.2 "PD server".  Type ids 140+.

All PD responses carry ``success`` + optional ``redirect`` (the PD
leader's endpoint) because the PD metadata store is itself a raft group.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

from tpuraft.rpc.messages import register_message


def _pd(tid: int):
    def deco(cls):
        return register_message(tid, dataclass(cls))
    return deco


@_pd(140)
class ListRegionsRequest:
    pass


@_pd(141)
class ListRegionsResponse:
    regions: list[bytes] = field(default_factory=list)  # Region encodings
    success: bool = True
    redirect: str = ""
    msg: str = ""


@_pd(142)
class ListStoresRequest:
    pass


@_pd(143)
class ListStoresResponse:
    stores: list[bytes] = field(default_factory=list)  # StoreMeta encodings
    success: bool = True
    redirect: str = ""
    msg: str = ""


@_pd(144)
class StoreHeartbeatRequest:
    store_id: int
    endpoint: str
    regions: list[bytes] = field(default_factory=list)  # Region encodings
    # trailing extension (geo): the store's zone label; old senders
    # decode to "" (unlabeled)
    zone: str = ""
    # trailing extension (gray failures): the store's self-reported
    # health level ("healthy"/"degraded"/"sick"; "" = no scoring) —
    # the PD stops placing leaders onto SICK stores and drains them
    health: str = ""


@_pd(145)
class StoreHeartbeatResponse:
    success: bool = True
    redirect: str = ""
    msg: str = ""


@_pd(146)
class RegionHeartbeatRequest:
    region: bytes  # Region encoding
    leader: str    # PeerId string of the region leader
    approximate_keys: int = 0


@_pd(147)
class RegionHeartbeatResponse:
    instructions: list[bytes] = field(default_factory=list)
    success: bool = True
    redirect: str = ""
    msg: str = ""


@_pd(148)
class ReportSplitRequest:
    parent: bytes  # Region encoding
    child: bytes


@_pd(149)
class ReportSplitResponse:
    success: bool = True
    redirect: str = ""
    msg: str = ""


@_pd(150)
class CreateRegionIdRequest:
    pass


@_pd(151)
class CreateRegionIdResponse:
    region_id: int = 0
    success: bool = True
    redirect: str = ""
    msg: str = ""


@_pd(152)
class StoreHeartbeatBatchRequest:
    """Delta-batched PD reporting (quiescent multi-raft): ONE RPC per
    store per interval carrying only CHANGED region rows — an idle
    2K-region store's PD traffic collapses from O(regions) RPCs/s to
    one near-empty batch/s.  ``full=True`` marks a complete resync
    (first contact, or the PD answered ``need_full``)."""

    store_id: int
    endpoint: str
    # changed-region rows, each encode_region_delta() (leader peer,
    # approximate keys, Region encoding)
    deltas: list[bytes] = field(default_factory=list)
    full: bool = False
    # trailing extension (geo): the store's zone label
    zone: str = ""
    # trailing extension (gray failures): self-reported health level
    # ("" = store predates health scoring, treated as healthy)
    health: str = ""
    # trailing extension (fleet observability): packed per-region heat
    # rows (util/heat.encode_heat_rows — region_id + EWMA writes/s,
    # reads/s, bytes in/out per s) for led regions whose heat moved
    # past the noise gate this interval; b"" = nothing moved (zero
    # wire cost) or a pre-heat sender.  Rows are independent of
    # ``deltas``: heat changes at its own cadence.
    heat: bytes = b""
    # trailing extension (fleet observability): tick-plane occupancy —
    # how many region replicas this store hosts and how many of them
    # are hibernating (group quiescence).  The PD folds these into the
    # ClusterView's fleet hibernation fraction.  0/0 = pre-occupancy
    # sender or a timer-mode store that doesn't track it.
    replicas: int = 0
    replicas_quiescent: int = 0


@_pd(153)
class StoreHeartbeatBatchResponse:
    # flat list: each Instruction already names its region_id
    instructions: list[bytes] = field(default_factory=list)
    # the PD leader has no full picture of this store (new leader /
    # store unknown): send a full batch next round
    need_full: bool = False
    success: bool = True
    redirect: str = ""
    msg: str = ""


@_pd(154)
class ClusterDescribeRequest:
    """Fleet observability: ask the PD leader for its folded
    :class:`~tpuraft.rheakv.pd_server.ClusterView` — top-K hot/cold
    regions, per-zone access rates, store health roster, leader
    histograms and the fleet hibernation fraction."""

    top_k: int = 8


@_pd(155)
class ClusterDescribeResponse:
    # JSON rendering of the ClusterView (an admin/read surface: JSON
    # keeps it extensible without wire-schema churn per added field)
    view_json: str = ""
    success: bool = True
    redirect: str = ""
    msg: str = ""


def encode_region_delta(region_blob: bytes, leader: str,
                        approximate_keys: int) -> bytes:
    lp = leader.encode()
    return (struct.pack("<H", len(lp)) + lp
            + struct.pack("<q", approximate_keys) + region_blob)


def decode_region_delta(blob: bytes) -> tuple[bytes, str, int]:
    """Returns (region_encoding, leader, approximate_keys)."""
    (n,) = struct.unpack_from("<H", blob, 0)
    leader = bytes(blob[2:2 + n]).decode()
    (keys,) = struct.unpack_from("<q", blob, 2 + n)
    return bytes(blob[10 + n:]), leader, keys


@_pd(156)
class ReportMergeRequest:
    """Lifecycle plane: a SOURCE region's store reports a completed
    merge (seal + absorb + commit all applied) so the PD finalizes its
    replicated metadata — extend the target's range over the source's,
    drop the source region, clear the pending-merge entry.  This report
    is the ONLY finalization trigger (the target's extended range
    proves the absorb, not that the source's MERGE_COMMIT is durable),
    so it is sent redundantly: by the source leader after commit, by
    every replica at its MERGE_COMMIT apply, and by any store answering
    a re-issued KIND_MERGE for a region it already retired.  Idempotent
    at the PD (the retirement tombstone counts once)."""

    source_region_id: int = 0
    target_region_id: int = 0


@_pd(157)
class ReportMergeResponse:
    success: bool = True
    redirect: str = ""
    msg: str = ""


@dataclass
class Instruction:
    """A PD order to a store (reference: ``rhea:metadata/Instruction`` —
    e.g. RANGE_SPLIT with the new region id)."""

    KIND_SPLIT = 1
    KIND_TRANSFER_LEADER = 2
    # lifecycle plane: merge region_id INTO new_region_id, whose leader
    # (the absorb RPC destination) rides target_peer
    KIND_MERGE = 3
    # lifecycle plane: move region_id's replica src_peer -> target_peer
    # (add-learner, catch up, promote + remove on joint consensus)
    KIND_MOVE = 4

    kind: int = 0
    region_id: int = 0
    new_region_id: int = 0
    target_peer: str = ""
    # trailing extension (KIND_MOVE): the replica being replaced.  Old
    # decoders never see MOVE instructions (a PD only issues them to
    # stores that report moves working), and trailing bytes are safe —
    # each instruction travels as its own length-delimited blob.
    src_peer: str = ""

    def encode(self) -> bytes:
        tp = self.target_peer.encode()
        out = struct.pack("<Bqq", self.kind, self.region_id,
                          self.new_region_id) \
            + struct.pack("<H", len(tp)) + tp
        if self.src_peer:
            sp = self.src_peer.encode()
            out += struct.pack("<H", len(sp)) + sp
        return out

    @staticmethod
    def decode(blob: bytes) -> "Instruction":
        kind, rid, nrid = struct.unpack_from("<Bqq", blob, 0)
        (n,) = struct.unpack_from("<H", blob, 17)
        target = blob[19:19 + n].decode()
        off = 19 + n
        src = ""
        if off + 2 <= len(blob):
            (sn,) = struct.unpack_from("<H", blob, off)
            src = bytes(blob[off + 2:off + 2 + sn]).decode()
        return Instruction(kind, rid, nrid, target, src)


def encode_store_meta(store_id: int, endpoint: str, zone: str = "") -> bytes:
    """Store-meta blob; the zone block is a TRAILING extension written
    only when a zone is set, so zoneless metas keep the old byte format
    and old decoders ignore a labeled meta's tail (each meta travels as
    its own length-delimited blob, so trailing bytes are safe)."""
    ep = endpoint.encode()
    out = struct.pack("<q", store_id) + struct.pack("<H", len(ep)) + ep
    if zone:
        zb = zone.encode()
        out += struct.pack("<H", len(zb)) + zb
    return out


def decode_store_meta(blob: bytes) -> tuple[int, str, str]:
    """Returns (store_id, endpoint, zone); zone defaults to "" for
    pre-zone blobs (tolerant trailing decode)."""
    (sid,) = struct.unpack_from("<q", blob, 0)
    (n,) = struct.unpack_from("<H", blob, 8)
    ep = bytes(blob[10:10 + n]).decode()
    off = 10 + n
    zone = ""
    if off + 2 <= len(blob):
        (zn,) = struct.unpack_from("<H", blob, off)
        zone = bytes(blob[off + 2:off + 2 + zn]).decode()
    return sid, ep, zone
