"""Checker 3: wire-schema drift.

``decode_message`` walks a dataclass's fields IN DECLARATION ORDER and
fills missing TRAILING defaulted fields from their defaults (the
mixed-fleet contract PR 3 added).  That makes the field list part of the
wire format: inserting, reordering, removing or retyping a field — or
appending one without a default — silently breaks decoding against any
older peer, and nothing at the call site looks wrong.  PR 3 only guards
this at DECODE time; this checker guards it at lint time.

The snapshot (``wire_schema.lock.json``) maps every registered type id
to its class, module and ordered field list (name, annotation, default
presence + source).  Extraction is AST-only (no imports — a lint run
must not load jax); the runtime meta-test in tests/test_analysis.py
proves the extraction faithful against the live ``_MSG_TYPES`` registry.

Registration forms recognized (all in use today):

  register_message(128, KVCommandRequest)            # literal call
  @_cli(64) / @_pd(140)                              # tid-decorators that
      class GetLeaderRequest: ...                    # wrap register_message
  for i, t in enumerate([A, B, ...]):                # the raft-core block
      register_message(i, t)

Intentional changes re-record with ``python -m tpuraft.analysis
--record`` (docs/operations.md "Wire-format changes"); --record refuses
nothing but the check tells a compatible extension (append WITH default:
record it) apart from a wire-breaking edit (everything else: redesign it
or version the message)."""

from __future__ import annotations

import ast
import json
import os

from tpuraft.analysis.core import Finding, Module

RULE = "wire-schema"
LOCK_FILE = "wire_schema.lock.json"


def lock_file_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), LOCK_FILE)


# ---- AST extraction ---------------------------------------------------------


def _class_fields(cls: ast.ClassDef) -> list[dict]:
    fields = []
    for node in cls.body:
        if isinstance(node, ast.AnnAssign) and isinstance(
                node.target, ast.Name):
            ann = ast.unparse(node.annotation)
            if ann.startswith("ClassVar"):
                continue
            fields.append({
                "name": node.target.id,
                "type": ann,
                "default": ast.unparse(node.value) if node.value else None,
            })
    return fields


def _tid_decorator_names(mod: Module) -> set[str]:
    """Names of module functions that wrap register_message with a tid
    (the _cli/_pd pattern): ``def f(tid): ... register_message(tid, ...)``."""
    out = set()
    for node in mod.tree.body:
        if isinstance(node, ast.FunctionDef):
            for inner in ast.walk(node):
                if isinstance(inner, ast.Call) and isinstance(
                        inner.func, ast.Name) \
                        and inner.func.id == "register_message":
                    out.add(node.name)
                    break
    return out


def extract_module(mod: Module) -> dict[int, dict]:
    """tid -> {cls, module, line, fields} for every registration in one
    module."""
    classes = {n.name: n for n in ast.walk(mod.tree)
               if isinstance(n, ast.ClassDef)}
    tid_decos = _tid_decorator_names(mod)
    found: dict[int, dict] = {}

    def add(tid: int, cls_name: str, line: int) -> None:
        cls = classes.get(cls_name)
        found[tid] = {
            "cls": cls_name,
            "module": mod.rel.replace(os.sep, "/"),
            "line": cls.lineno if cls else line,
            "fields": _class_fields(cls) if cls else [],
        }

    for node in ast.walk(mod.tree):
        # literal call: register_message(128, KVCommandRequest)
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id == "register_message" \
                and len(node.args) == 2 \
                and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, int) \
                and isinstance(node.args[1], ast.Name):
            add(node.args[0].value, node.args[1].id, node.lineno)
        # decorator form: @_cli(64) class Foo: ...
        if isinstance(node, ast.ClassDef):
            for deco in node.decorator_list:
                if isinstance(deco, ast.Call) and isinstance(
                        deco.func, ast.Name) \
                        and deco.func.id in tid_decos \
                        and len(deco.args) == 1 \
                        and isinstance(deco.args[0], ast.Constant) \
                        and isinstance(deco.args[0].value, int):
                    add(deco.args[0].value, node.name, node.lineno)
        # enumerate block: for i, t in enumerate([A, B]): register_message(i, t)
        if isinstance(node, ast.For) and isinstance(node.iter, ast.Call) \
                and isinstance(node.iter.func, ast.Name) \
                and node.iter.func.id == "enumerate" \
                and node.iter.args \
                and isinstance(node.iter.args[0], (ast.List, ast.Tuple)):
            body_regs = [
                c for c in ast.walk(node)
                if isinstance(c, ast.Call) and isinstance(c.func, ast.Name)
                and c.func.id == "register_message"]
            if body_regs:
                for i, elt in enumerate(node.iter.args[0].elts):
                    if isinstance(elt, ast.Name):
                        add(i, elt.id, node.lineno)
    return found


def extract_tree(mods: list[Module]) -> dict[int, dict]:
    schema: dict[int, dict] = {}
    for mod in mods:
        for tid, entry in extract_module(mod).items():
            prev = schema.get(tid)
            if prev is not None and prev["cls"] != entry["cls"]:
                # duplicate tid across modules: surfaced by check()
                entry = dict(entry)
                entry["duplicate_of"] = prev["cls"]
            schema[tid] = entry
    return schema


# ---- lockfile + drift rules -------------------------------------------------


def record(mods: list[Module], path: str | None = None) -> None:
    schema = extract_tree(mods)
    payload = {
        "_comment": (
            "Committed wire schema (graftcheck wire-schema): tid -> "
            "ordered dataclass fields + defaults for every "
            "register_message type.  decode_message fills missing "
            "trailing defaulted fields, so order/defaults ARE the wire "
            "format.  Regenerate with `python -m tpuraft.analysis "
            "--record` after reviewing the change for mixed-fleet "
            "compatibility (docs/operations.md)."),
        "types": {
            str(tid): {k: v for k, v in entry.items() if k != "line"}
            for tid, entry in sorted(schema.items())
        },
    }
    with open(path or lock_file_path(), "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2)
        f.write("\n")


def load_lock(path: str | None = None) -> dict[int, dict] | None:
    try:
        with open(path or lock_file_path(), "r", encoding="utf-8") as f:
            data = json.load(f)
    except FileNotFoundError:
        return None
    return {int(tid): entry for tid, entry in data.get("types", {}).items()}


def check(mods: list[Module], record: bool = False,
          path: str | None = None) -> list[Finding]:
    if record:
        _record_fn(mods, path)
    live = extract_tree(mods)
    lock = load_lock(path)
    out: list[Finding] = []

    for tid, entry in sorted(live.items()):
        if "duplicate_of" in entry:
            out.append(Finding(
                RULE, entry["module"], entry["line"],
                f"type id {tid} registered twice: {entry['duplicate_of']} "
                f"and {entry['cls']}"))

    if lock is None:
        out.append(Finding(
            RULE, "tpuraft/analysis/" + LOCK_FILE, 0,
            "wire_schema.lock.json missing — run "
            "`python -m tpuraft.analysis --record` and commit it"))
        return out

    # a targeted run (`python -m tpuraft.analysis <subpath>`) only
    # extracts the modules it was given: lock entries for modules
    # OUTSIDE the analyzed set are not comparable (everything would
    # read as "removed") — the full-tree gate still covers them
    analyzed = {m.rel.replace(os.sep, "/") for m in mods}
    for tid, old in sorted(lock.items()):
        cur = live.get(tid)
        loc = (old["module"], 0)
        if cur is None:
            if old["module"] not in analyzed:
                continue
            out.append(Finding(
                RULE, *loc,
                f"message type {tid} ({old['cls']}) removed — peers still "
                f"send it; decode_message would KeyError.  Deprecate by "
                f"keeping the class and refusing in the handler"))
            continue
        loc = (cur["module"], cur["line"])
        if cur["cls"] != old["cls"]:
            out.append(Finding(
                RULE, *loc,
                f"type id {tid} renamed {old['cls']} -> {cur['cls']} — "
                f"if the shape changed too this is wire-breaking; "
                f"re-record after review"))
        out.extend(_diff_fields(tid, old, cur, loc))

    for tid, cur in sorted(live.items()):
        if tid not in lock:
            out.append(Finding(
                RULE, cur["module"], cur["line"],
                f"new message type {tid} ({cur['cls']}) not in the "
                f"committed schema — review mixed-fleet behavior (an old "
                f"receiver KeyErrors on an unknown tid: gate it behind "
                f"method negotiation / ENOMETHOD fallback) then "
                f"`python -m tpuraft.analysis --record`"))
    return out


def _diff_fields(tid: int, old: dict, cur: dict,
                 loc: tuple[str, int]) -> list[Finding]:
    out: list[Finding] = []
    ofields, cfields = old["fields"], cur["fields"]
    name = cur["cls"]
    for i, of in enumerate(ofields):
        if i >= len(cfields):
            out.append(Finding(
                RULE, *loc,
                f"{name} (tid {tid}): field '{of['name']}' removed — "
                f"wire-breaking (old peers still encode it); keep the "
                f"field or version the message"))
            continue
        cf = cfields[i]
        if cf["name"] != of["name"]:
            out.append(Finding(
                RULE, *loc,
                f"{name} (tid {tid}): field #{i} changed "
                f"'{of['name']}' -> '{cf['name']}' — insertion/reorder/"
                f"rename is wire-breaking: fields decode by position; "
                f"new fields go LAST with a default"))
        elif cf["type"] != of["type"]:
            out.append(Finding(
                RULE, *loc,
                f"{name} (tid {tid}): field '{cf['name']}' retyped "
                f"{of['type']} -> {cf['type']} — the codec packs by "
                f"annotation; wire-breaking"))
        elif (cf["default"] or None) != (of["default"] or None):
            out.append(Finding(
                RULE, *loc,
                f"{name} (tid {tid}): default of '{cf['name']}' changed "
                f"{of['default']!r} -> {cf['default']!r} — old-format "
                f"frames decode to the default, so this silently changes "
                f"their meaning; re-record only if that is intended"))
    for cf in cfields[len(ofields):]:
        if cf["default"] is None:
            out.append(Finding(
                RULE, *loc,
                f"{name} (tid {tid}): new field '{cf['name']}' has no "
                f"default — frames from old senders fail to decode "
                f"(the PR 3 mixed-fleet guard only fills TRAILING "
                f"DEFAULTED fields).  Give it a default"))
        else:
            out.append(Finding(
                RULE, *loc,
                f"{name} (tid {tid}): compatible extension — new trailing "
                f"defaulted field '{cf['name']}'.  Review then "
                f"`python -m tpuraft.analysis --record`"))
    return out


_record_fn = record
