"""Checkers 7+8: device-plane lane lint (graftcheck v2).

The engine's premise is vectorizing per-group protocol state over the
``[G]`` / ``[G, P]`` device plane — and every new lane pays a wiring
tax at four engine lifecycle sites.  PR 10's ``tick_q_ack`` touched
grow/``pad``, the ``release`` reset, ``set_conf`` invalidation and the
time-shift path, and nothing but review memory catches a missed site
until state silently corrupts on resize.  These rules mechanize that
contract:

``lane-coverage``
    Every ``[G]``/``[G, P]`` lane — a ``self.X = np.zeros/full/ones/
    empty(g, ...)`` assignment in ``MultiRaftEngine.__init__`` whose
    leading dimension is the group-capacity local ``g`` — must be
    WRITTEN at each of the four lifecycle sites:

      grow   ``_grow``               (capacity doubling pads every lane)
      free   ``release``             (slot reuse resets every lane)
      conf   ``set_conf``            (conf-derived lanes re-map/invalidate)
      shift  ``_maybe_time_rebase``  (time-valued lanes epoch-shift)

    One level of intra-class call resolution applies (``release`` covers
    ``has_ctrl`` through its ``self.unregister_ctrl(s)`` call).  A lane
    that legitimately skips a site declares it ON ITS DECLARATION LINE:

        self.role = np.full(g, ROLE_INACTIVE, np.int32) \\
            # lane: no-conf no-shift — role is host-applied, not
            # conf-derived; not time-valued

    A waiver with no reason is itself a finding (the graftcheck
    escape-hatch policy).  The same rule keeps the device dataclasses
    honest: ``GroupState``/``TickOutputs`` field sets must match every
    keyword construction of them (engine upload, mesh shardings, the
    numpy twin) and ``_NpOutputs.__slots__`` must equal ``TickOutputs``
    — the exact multi-file drift PR 10 hand-wired.

``host-sync`` / ``donated-read``
    Inside jitted bodies (functions reachable from a ``jax.jit`` root
    or a ``pallas_call`` kernel through the project call graph), flag
    host synchronization on traced values: ``.item()``, ``np.asarray/
    np.array``, ``int()/float()/bool()`` of a traced parameter, and
    data-dependent Python branching (``if``/``while`` on a traced
    parameter — stage it through ``jnp.where`` or lift it to a static
    argument).  A parameter is traced unless its annotation is scalar
    (str/int/bool/float) or it appears in the root's
    ``static_argnames``.  And a buffer passed at a donated position
    (``donate_argnums``) of a jitted callable must not be read after
    the call — donation invalidates it; rebinding the name to the
    call's result re-arms it.
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from tpuraft.analysis.callgraph import ProjectIndex, _all_functions
from tpuraft.analysis.core import Finding, Module, attr_chain

RULE_LANE = "lane-coverage"
RULE_SYNC = "host-sync"
RULE_DONATED = "donated-read"

ENGINE_CLASS = "MultiRaftEngine"
SITES = (
    ("grow", "_grow"),
    ("free", "release"),
    ("conf", "set_conf"),
    ("shift", "_maybe_time_rebase"),
)
_SITE_NAMES = {s for s, _ in SITES}

_LANE_RE = re.compile(r"#\s*lane:\s*((?:no-[a-z]+\s*)+)(?:[—–-]+\s*(\S.*))?")
_NP_CTORS = {"np.zeros", "np.full", "np.ones", "np.empty",
             "numpy.zeros", "numpy.full", "numpy.ones", "numpy.empty"}
_STATE_CLASSES = ("GroupState", "TickOutputs")
_NP_TWIN = "_NpOutputs"
_SCALARISH = re.compile(r"\b(str|int|bool|float|bytes|None)\b")
_ARRAYISH = re.compile(r"ndarray|Array|GroupState|TickParams|TickOutputs")


def check(mods: list[Module], index: ProjectIndex) -> list[Finding]:
    out: list[Finding] = []
    out.extend(_check_lanes(mods))
    out.extend(_check_state_parity(mods))
    jit = _JitIndex(mods, index)
    out.extend(_check_host_sync(index, jit))
    out.extend(_check_donated_reads(mods, jit))
    return out


# ---- lane-site coverage -----------------------------------------------------


def _check_lanes(mods: list[Module]) -> list[Finding]:
    out: list[Finding] = []
    for mod in mods:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef) and node.name == ENGINE_CLASS:
                out.extend(_check_engine_class(mod, node))
    return out


def _check_engine_class(mod: Module, cls: ast.ClassDef) -> list[Finding]:
    methods = {item.name: item for item in cls.body
               if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))}
    init = methods.get("__init__")
    if init is None:
        return []
    lanes = _collect_lanes(mod, init)
    if not lanes:
        return []
    out: list[Finding] = []
    written = {}
    for site, meth_name in SITES:
        fn = methods.get(meth_name)
        written[site] = (_written_attrs(methods, fn) if fn is not None
                         else set())
    for name, (line, waived, reason, bad_tokens) in sorted(lanes.items()):
        for tok in bad_tokens:
            out.append(Finding(
                RULE_LANE, mod.rel, line,
                f"lane '{name}': unknown waiver site 'no-{tok}' (known: "
                + ", ".join(f"no-{s}" for s in _SITE_NAMES) + ")"))
        if waived and not reason:
            out.append(Finding(
                RULE_LANE, mod.rel, line,
                f"lane '{name}': waiver carries no justification — write "
                f"'# lane: no-<site> — <reason>'"))
        for site, meth_name in SITES:
            if site in waived:
                continue
            if name not in written[site]:
                out.append(Finding(
                    RULE_LANE, mod.rel, line,
                    f"[G] lane '{name}' (declared line {line}) is not "
                    f"covered at the {site} site ({ENGINE_CLASS}."
                    f"{meth_name}) — handle it there or waive with "
                    f"'# lane: no-{site} — <reason>'"))
    return out


def _collect_lanes(mod: Module, init) -> dict:
    """lane name -> (decl line, waived site set, reason, bad tokens)."""
    lanes: dict = {}
    for node in ast.walk(init):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        t = node.targets[0]
        if not (isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name)
                and t.value.id == "self"):
            continue
        if not _is_group_row_ctor(node.value):
            continue
        waived: set[str] = set()
        bad: list[str] = []
        reason = ""
        m = _LANE_RE.search(mod.comment_block_above(node.lineno))
        if m:
            for tok in m.group(1).split():
                site = tok[3:]
                if site in _SITE_NAMES:
                    waived.add(site)
                else:
                    bad.append(site)
            reason = (m.group(2) or "").strip()
        lanes[t.attr] = (node.lineno, waived, reason, bad)
    return lanes


def _is_group_row_ctor(value: ast.AST) -> bool:
    """np.zeros/full/ones/empty with the group-capacity local ``g`` as
    the leading dimension."""
    if not isinstance(value, ast.Call) or not value.args:
        return False
    if attr_chain(value.func) not in _NP_CTORS:
        return False
    shape = value.args[0]
    if isinstance(shape, ast.Tuple) and shape.elts:
        shape = shape.elts[0]
    return isinstance(shape, ast.Name) and shape.id == "g"


def _written_attrs(methods: dict, fn, depth: int = 1) -> set[str]:
    """self attributes written anywhere in ``fn``, with one level of
    intra-class self-call resolution."""
    written: set[str] = set()
    calls: list[str] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                _collect_target(t, written)
        elif isinstance(node, ast.AugAssign):
            _collect_target(node.target, written)
        elif isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            for kw in node.keywords:
                if kw.arg == "out":
                    _self_attr_of(kw.value, written)
            if chain.endswith("copyto") and node.args:
                _self_attr_of(node.args[0], written)
            if chain.startswith("self.") and chain.count(".") == 2 \
                    and chain.endswith((".fill", ".clear")):
                written.add(chain.split(".")[1])
            if chain.startswith("self.") and chain.count(".") == 1:
                calls.append(chain[5:])
    if depth > 0:
        for name in calls:
            callee = methods.get(name)
            if callee is not None and callee is not fn:
                written |= _written_attrs(methods, callee, depth - 1)
    return written


def _collect_target(t: ast.AST, written: set[str]) -> None:
    if isinstance(t, (ast.Tuple, ast.List)):
        for e in t.elts:
            _collect_target(e, written)
        return
    if isinstance(t, ast.Starred):
        _collect_target(t.value, written)
        return
    if isinstance(t, ast.Subscript):
        _self_attr_of(t.value, written)
        return
    _self_attr_of(t, written)


def _self_attr_of(node: ast.AST, written: set[str]) -> None:
    chain = attr_chain(node)
    if chain.startswith("self.") and chain.count(".") == 1:
        written.add(chain[5:])


# ---- device dataclass parity ------------------------------------------------


def _check_state_parity(mods: list[Module]) -> list[Finding]:
    fields: dict[str, tuple[list[str], str, int]] = {}  # cls -> (names, rel, line)
    slots: list[tuple[list[str], Module, int]] = []
    for mod in mods:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            if node.name in _STATE_CLASSES:
                names = [item.target.id for item in node.body
                         if isinstance(item, ast.AnnAssign)
                         and isinstance(item.target, ast.Name)]
                if names:
                    fields.setdefault(node.name,
                                      (names, mod.rel, node.lineno))
            elif node.name == _NP_TWIN:
                for item in node.body:
                    if isinstance(item, ast.Assign) and any(
                            isinstance(t, ast.Name) and t.id == "__slots__"
                            for t in item.targets):
                        vals = getattr(item.value, "elts", [])
                        names = [v.value for v in vals
                                 if isinstance(v, ast.Constant)]
                        slots.append((names, mod, item.lineno))
    out: list[Finding] = []
    tick_out = fields.get("TickOutputs")
    if tick_out is not None:
        expected = set(tick_out[0])
        for names, mod, line in slots:
            missing = expected - set(names)
            extra = set(names) - expected
            if missing or extra:
                out.append(Finding(
                    RULE_LANE, mod.rel, line,
                    f"{_NP_TWIN}.__slots__ drifted from TickOutputs "
                    f"({tick_out[1]}:{tick_out[2]})"
                    + (f": missing {sorted(missing)}" if missing else "")
                    + (f": extra {sorted(extra)}" if extra else "")
                    + " — the numpy twin must mirror the device lanes"))
    # every keyword construction of a state class passes the full lane set
    for mod in mods:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = attr_chain(node.func).split(".")[-1]
            target = name if name in _STATE_CLASSES else (
                "TickOutputs" if name == _NP_TWIN else None)
            if target is None or target not in fields:
                continue
            if node.args or not node.keywords \
                    or any(kw.arg is None for kw in node.keywords):
                continue  # positional/**kw constructions: out of scope
            expected = set(fields[target][0])
            got = {kw.arg for kw in node.keywords}
            missing = expected - got
            if missing:
                out.append(Finding(
                    RULE_LANE, mod.rel, node.lineno,
                    f"{name}(...) construction misses lane field(s) "
                    f"{sorted(missing)} (declared {fields[target][1]}:"
                    f"{fields[target][2]}) — every device-state "
                    f"construction site must carry every lane"))
    return out


# ---- jit-body discovery -----------------------------------------------------


class _JitRoot:
    __slots__ = ("fn_name", "statics", "donated", "bound_name", "line")

    def __init__(self, fn_name, statics, donated, bound_name, line):
        self.fn_name = fn_name
        self.statics = statics      # static_argnames
        self.donated = donated      # donate_argnums positions
        self.bound_name = bound_name  # the jitted callable's local name
        self.line = line


class _JitIndex:
    """Per-module jit roots + the transitive jit-body set."""

    def __init__(self, mods: list[Module], index: ProjectIndex):
        self.index = index
        self.roots: dict[str, list[_JitRoot]] = {}   # mod.rel -> roots
        # (mod.rel, bound name) -> donated positions, for donated-read
        self.donated_names: dict[tuple[str, str], tuple] = {}
        for mod in mods:
            self.roots[mod.rel] = list(self._scan_module(mod))
        # id(fn node) -> static param names for that body
        self.bodies: dict[int, frozenset] = {}
        self._close()

    def _scan_module(self, mod: Module):
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                root = self._jit_call_root(node.value)
                if root is not None:
                    if len(node.targets) == 1 and isinstance(
                            node.targets[0], ast.Name):
                        root.bound_name = node.targets[0].id
                        if root.donated:
                            self.donated_names[(mod.rel, root.bound_name)] \
                                = root.donated
                    yield root
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    statics = self._jit_decorator(dec)
                    if statics is not None:
                        yield _JitRoot(node.name, statics, (), None,
                                       node.lineno)
            elif isinstance(node, ast.Call):
                chain = attr_chain(node.func)
                if chain.split(".")[-1] == "pallas_call" and node.args \
                        and isinstance(node.args[0], ast.Name):
                    yield _JitRoot(node.args[0].id, frozenset(), (), None,
                                   node.lineno)

    def _jit_call_root(self, call: ast.Call) -> Optional[_JitRoot]:
        if attr_chain(call.func) not in ("jax.jit", "jit"):
            return None
        if not call.args or not isinstance(call.args[0], ast.Name):
            return None
        statics, donated = _jit_kwargs(call)
        return _JitRoot(call.args[0].id, statics, donated, None, call.lineno)

    def _jit_decorator(self, dec) -> Optional[frozenset]:
        chain = attr_chain(dec) if not isinstance(dec, ast.Call) \
            else attr_chain(dec.func)
        if chain in ("jax.jit", "jit"):
            return (_jit_kwargs(dec)[0] if isinstance(dec, ast.Call)
                    else frozenset())
        if isinstance(dec, ast.Call) \
                and chain in ("functools.partial", "partial") and dec.args:
            inner = dec.args[0]
            if attr_chain(inner) in ("jax.jit", "jit"):
                return _jit_kwargs(dec)[0]
        return None

    def _close(self) -> None:
        stack = []
        for rel, roots in self.roots.items():
            midx = self.index.by_rel.get(rel)
            if midx is None:
                continue
            for root in roots:
                info = midx.functions.get(root.fn_name)
                if info is not None:
                    stack.append((info, root.statics))
        while stack:
            info, statics = stack.pop()
            key = id(info.node)
            if key in self.bodies:
                continue
            self.bodies[key] = frozenset(statics)
            for site in info.calls:
                callee = self.index.resolve_call(info, site.call)
                if callee is not None and not callee.is_async:
                    stack.append((callee, frozenset()))


def _jit_kwargs(call: ast.Call) -> tuple[frozenset, tuple]:
    statics: set[str] = set()
    donated: tuple = ()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            vals = getattr(kw.value, "elts", [kw.value])
            statics = {v.value for v in vals
                       if isinstance(v, ast.Constant)
                       and isinstance(v.value, str)}
        elif kw.arg == "donate_argnums":
            vals = getattr(kw.value, "elts", [kw.value])
            donated = tuple(v.value for v in vals
                            if isinstance(v, ast.Constant)
                            and isinstance(v.value, int))
    return frozenset(statics), donated


# ---- host-sync lint ---------------------------------------------------------


def _check_host_sync(index: ProjectIndex, jit: _JitIndex) -> list[Finding]:
    out: list[Finding] = []
    for midx in index.by_rel.values():
        for info in _all_functions(midx):
            statics = jit.bodies.get(id(info.node))
            if statics is None:
                continue
            out.extend(_scan_jit_body(info, statics))
    return out


def _traced_params(fn, statics: frozenset) -> set[str]:
    traced = set()
    args = fn.args
    for a in (args.posonlyargs + args.args + args.kwonlyargs):
        if a.arg in statics or a.arg == "self":
            continue
        if a.annotation is None:
            traced.add(a.arg)
            continue
        ann = ast.unparse(a.annotation) if hasattr(ast, "unparse") else ""
        if _ARRAYISH.search(ann) or not _SCALARISH.search(ann):
            traced.add(a.arg)
    return traced


def _scan_jit_body(info, statics: frozenset) -> list[Finding]:
    fn = info.node
    mod = info.mod
    traced = _traced_params(fn, statics)
    out: list[Finding] = []

    def touches_traced(expr) -> bool:
        for n in ast.walk(expr):
            if isinstance(n, ast.Name) and n.id in traced:
                return True
        return False

    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "item" and not node.args:
                out.append(Finding(
                    RULE_SYNC, mod.rel, node.lineno,
                    f"{info.qualname}(): .item() in a jitted body forces "
                    f"a device->host sync per trace — return the array "
                    f"and read it host-side"))
            elif chain in ("np.asarray", "np.array", "numpy.asarray",
                           "numpy.array"):
                out.append(Finding(
                    RULE_SYNC, mod.rel, node.lineno,
                    f"{info.qualname}(): {chain}() in a jitted body "
                    f"materializes a traced value on host — use jnp, or "
                    f"hoist the conversion out of the jit"))
            elif chain in ("int", "float", "bool") and node.args \
                    and touches_traced(node.args[0]):
                out.append(Finding(
                    RULE_SYNC, mod.rel, node.lineno,
                    f"{info.qualname}(): {chain}() of traced value in a "
                    f"jitted body is a concretization error under jit — "
                    f"keep it an array or lift it to a static argument"))
        elif isinstance(node, (ast.If, ast.While)) \
                and touches_traced(node.test):
            kind = "if" if isinstance(node, ast.If) else "while"
            out.append(Finding(
                RULE_SYNC, mod.rel, node.lineno,
                f"{info.qualname}(): data-dependent Python `{kind}` on a "
                f"traced value in a jitted body — branch with jnp.where/"
                f"lax.cond or make the operand a static argument"))
    return out


# ---- donated-read lint ------------------------------------------------------


def _check_donated_reads(mods: list[Module], jit: _JitIndex
                         ) -> list[Finding]:
    out: list[Finding] = []
    if not jit.donated_names:
        return out
    for mod in mods:
        # local + imported donated callables visible in this module
        visible: dict[str, tuple[str, tuple]] = {}
        for (rel, name), pos in jit.donated_names.items():
            if rel == mod.rel:
                visible[name] = (name, pos)
        midx = jit.index.by_rel.get(mod.rel)
        if midx is not None:
            for local, entry in midx.imports.items():
                imp = jit.index.resolve_import(midx, local)
                if imp is None or imp[1] is None:
                    continue
                pos = jit.donated_names.get((imp[0], imp[1]))
                if pos is not None:
                    visible[local] = (imp[1], pos)
        if not visible:
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.extend(_scan_donated_fn(mod, node, visible))
    return out


def _scan_donated_fn(mod: Module, fn, visible: dict) -> list[Finding]:
    out: list[Finding] = []
    donations: list[tuple[str, str, int]] = []  # (var, callee, call line)
    rebinds: list[tuple[str, int]] = []
    loads: list[tuple[str, int]] = []

    for node in _direct(fn):
        # every binding form re-arms tracking: plain/annotated/augmented
        # assignment and loop targets (an annotated rebind on the call
        # line — `state: TickState = step(state, ...)` — must not leave
        # the name flagged)
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            targets = [node.target]
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            targets = [node.target]
        for t in targets:
            for n in ast.walk(t):
                if isinstance(n, ast.Name):
                    rebinds.append((n.id, node.lineno))
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            entry = visible.get(chain)
            if entry is not None:
                callee, positions = entry
                for pos in positions:
                    if pos < len(node.args) \
                            and isinstance(node.args[pos], ast.Name):
                        donations.append(
                            (node.args[pos].id, callee, node.lineno))
        elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            loads.append((node.id, node.lineno))

    for var, callee, call_line in donations:
        for name, line in sorted(loads, key=lambda x: x[1]):
            if name != var or line <= call_line:
                continue
            if any(rb == var and call_line <= rline <= line
                   for rb, rline in rebinds):
                # rebound (including `state = donating(state, ...)` on
                # the call line itself): the name now holds the fresh
                # output, so tracking re-arms
                break
            out.append(Finding(
                RULE_DONATED, mod.rel, line,
                f"{fn.name}() reads '{var}' after passing it to "
                f"{callee}() at line {call_line}, which donates that "
                f"argument (donate_argnums) — the buffer is invalidated "
                f"by donation; use the returned arrays instead"))
            break
    return out


def _direct(fn):
    """Walk fn's body without descending into nested defs/lambdas."""
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))
