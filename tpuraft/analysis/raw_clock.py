"""raw-clock: timing-sensitive code must read the injectable clock.

ISSUE 18 made every store's time plane injectable (tpuraft/util/clock.py):
election timers, engine tick deadlines, store-lease bookkeeping, lease
windows and health hysteresis all read ONE per-store clock, so a
ChaosClock skews a store exactly like a machine with a bad oscillator —
and the drift-bound lease math stays honest because no consumer secretly
falls back to the real clock.  A direct ``time.monotonic()`` /
``time.time()`` / ``loop.time()`` call inside the clock-disciplined
tree punches a hole in that plane: the chaos soak can no longer reach
the code path, and the lease-safety argument silently loses a premise.

Scope: ``tpuraft/core/``, ``tpuraft/rheakv/`` and
``tpuraft/util/health.py`` (the hysteresis trackers).  ``time.
perf_counter()`` is exempt — it only feeds trace/latency telemetry and
MUST stay on the real clock (a frozen chaos clock would zero every
duration histogram).  Genuinely real-time sites (operator drain
budgets, scrape-cache TTLs, PD-side cooldowns, client retry deadlines)
carry ``# graftcheck: allow(raw-clock) — <reason>`` waivers; the
reason requirement rides the existing reasonless-waiver finding.
"""

from __future__ import annotations

import ast

from tpuraft.analysis.core import (
    _ALLOW_RE,
    Finding,
    Module,
    attr_chain,
)

# rel-path prefixes under the clock discipline
_SCOPES = ("tpuraft/core/", "tpuraft/rheakv/")
_SCOPE_FILES = ("tpuraft/util/health.py",)

# dotted call chains that read the REAL clock directly
_RAW_CHAINS = {"time.monotonic", "time.time"}


def _in_scope(rel: str) -> bool:
    rel = rel.replace("\\", "/")
    return rel.startswith(_SCOPES) or rel in _SCOPE_FILES


def _is_raw_call(node: ast.Call) -> str:
    """'' when fine, else the offending dotted chain."""
    chain = attr_chain(node.func)
    if chain in _RAW_CHAINS:
        return chain
    # loop.time() in any spelling: `loop.time()`, `self._loop.time()`,
    # `asyncio.get_running_loop().time()` resolves to no plain chain,
    # but the common direct forms do
    if chain.endswith(".time") and "loop" in chain.rsplit(".", 2)[-2]:
        return chain
    return ""


def _block_waived(mod: Module, line: int) -> bool:
    """Multi-line waiver blocks: the allow() marker may sit on the FIRST
    line of a wrapped standalone comment block above the call — the
    single-line ``Module.waived`` lookback misses those, exactly like
    the loop-confined annotations before ``comment_block_above``."""
    for m in _ALLOW_RE.finditer(mod.comment_block_above(line)):
        if m.group(1) == "raw-clock":
            return True
    return False


def check(mods: list[Module]) -> list[Finding]:
    out: list[Finding] = []
    for mod in mods:
        if not _in_scope(mod.rel):
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = _is_raw_call(node)
            if not chain:
                continue
            if _block_waived(mod, node.lineno):
                continue
            out.append(Finding(
                "raw-clock", mod.rel, node.lineno,
                f"direct {chain}() in clock-disciplined code — read the "
                f"store's injectable clock (tpuraft/util/clock.py; "
                f"node._clock / hub.clock / engine._clock) so chaos "
                f"clocks and the drift-bound lease math reach this "
                f"path, or waive with a written reason"))
    return out
