"""graftcheck: project-invariant static analysis for the Python plane.

PAPER.md §6 wires race detection and sanitizers into the native engines
(``make san``); this package is the equivalent gate for the ~20k-line
Python plane — five AST-based checkers for the defect classes the chaos
harness kept catching *dynamically* (PR 2's storage lock races and
wedged future waiters, PR 3's wire-format trailing-default drift):

  guarded-by     fields annotated ``# guarded-by: <lock>`` are only
                 touched under ``with self.<lock>`` (checkers/guarded_by)
  loop-confined  classes annotated ``# graftcheck: loop-confined`` never
                 reach for threading primitives (checkers/guarded_by)
  lock-order     the static lock-acquisition graph is acyclic and a
                 subset of the sanctioned partial order committed in
                 ``lock_order.json`` (checkers/lock_order)
  wire-schema    every ``register_message`` dataclass matches the
                 committed ``wire_schema.lock.json`` — no field
                 insertion/reorder/removal, new fields only trailing
                 with defaults (checkers/wire_schema)
  blocking-call  no ``time.sleep`` / blocking socket IO / untimed
                 ``Future.result()`` in tick-plane code (``ops/``), FSM
                 apply paths, coroutines, or while holding a lock
                 (checkers/blocking_calls)
  future-leak    functions that create AND complete a future locally
                 complete it on every path — try/except/finally
                 coverage (checkers/future_leaks)

Run ``python -m tpuraft.analysis`` (or ``make lint``); intentional wire
or lock-order changes are re-recorded with ``--record`` after review.
Escapes: ``# graftcheck: allow(<rule>) — <reason>`` on the offending
line (or on a ``def`` line to waive the whole function); a waiver with
no reason is itself a finding.
"""

from tpuraft.analysis.core import Finding, Module, load_modules, run_checkers

__all__ = ["Finding", "Module", "load_modules", "run_checkers"]
