"""graftcheck: project-invariant static analysis for the Python plane.

PAPER.md §6 wires race detection and sanitizers into the native engines
(``make san``); this package is the equivalent gate for the ~20k-line
Python plane — eight AST-based checkers for the defect classes the
chaos harness kept catching *dynamically* (PR 2's storage lock races
and wedged future waiters, PR 3's wire-format trailing-default drift,
PR 10's hand-wired lane lifecycle sites):

  guarded-by     fields annotated ``# guarded-by: <lock>`` are only
                 touched under ``with self.<lock>``; ``holds(<lock>)``
                 helpers may only be called lock-held — including
                 CROSS-OBJECT calls, satisfied lexically or by a
                 class-level ``# graftcheck: called-under(<lock>)``
                 declaration (guarded_by + concurrency)
  loop-confined  classes annotated ``# graftcheck: loop-confined`` never
                 reach for threading primitives (guarded_by)
  lock-order     the static lock-acquisition graph is acyclic and a
                 subset of the sanctioned partial order committed in
                 ``lock_order.json`` (lock_order)
  wire-schema    every ``register_message`` dataclass matches the
                 committed ``wire_schema.lock.json`` — no field
                 insertion/reorder/removal, new fields only trailing
                 with defaults (wire_schema)
  blocking-call  no ``time.sleep`` / blocking socket IO / untimed
                 ``Future.result()`` in tick-plane code (``ops/``), FSM
                 apply paths, coroutines, or while holding a lock
                 (blocking_calls)
  future-leak    functions that create AND complete a future locally
                 complete it on every path — try/except/finally
                 coverage (future_leaks)
  transitive-blocking / loop-affinity
                 the v2 whole-program pass (callgraph + concurrency):
                 per-function summaries {blocks, acquires,
                 awaits-under-lock} propagate over a project-wide call
                 graph, so the blocking contexts see THROUGH calls;
                 executor/thread targets are inferred off-loop and may
                 not write unguarded loop-confined state
  lane-coverage / host-sync / donated-read
                 the device-plane lint (lanes): every ``[G]`` engine
                 lane is handled at grow/free/conf/shift (``# lane:
                 no-<site> — <reason>`` waivers), device dataclasses
                 stay in parity with their twins and construction
                 sites, jitted bodies never host-sync traced values,
                 donated buffers are never read after the call

Run ``python -m tpuraft.analysis`` (or ``make lint``); intentional wire
or lock-order changes are re-recorded with ``--record`` after review;
``--rule <name>`` filters, ``--json`` emits machine-readable findings.
Escapes: ``# graftcheck: allow(<rule>) — <reason>`` on the offending
line (or on a ``def`` line to waive the whole function); a waiver with
no reason is itself a finding.
"""

from tpuraft.analysis.core import Finding, Module, load_modules, run_checkers

__all__ = ["Finding", "Module", "load_modules", "run_checkers"]
