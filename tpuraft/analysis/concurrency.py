"""Checker 6: interprocedural concurrency analysis (graftcheck v2).

Built on the whole-program :mod:`tpuraft.analysis.callgraph` index, two
rules close the one-hop blind spots of the intra-procedural lints:

``transitive-blocking``
    The blocking-call lint's four contexts (tick plane, FSM apply path,
    coroutine bodies, lexically under a lock) now see THROUGH calls: a
    call site whose resolved callee *transitively* reaches
    ``time.sleep`` / blocking socket IO / an untimed ``.result()`` is a
    finding, and the message carries the offending chain
    (``helper -> _sync -> time.sleep() (tpuraft/x.py:42)``) so review
    lands on the real sink, not the innocent call.  Coroutine bodies
    keep the direct lint's softer contract (sleep/socket only — an
    untimed ``.result()`` on a done task is idiomatic asyncio), and
    propagation follows only edges that execute synchronously: plain
    calls to sync functions, plus ``await``-ed coroutine calls.  The
    rule also flags an ``await`` lexically inside a *sync* ``with
    <lock-ish>`` block: a threading lock held across a suspension point
    convoys every other task behind the awaiting one.

``loop-affinity``
    Infers which functions run OFF the event loop — ``run_in_executor``
    targets, ``Thread(target=)`` callables, ``<executor>.submit(...)``
    arguments, including lambdas and nested defs, closed transitively
    over the call graph — and flags loop-confined state touched from
    that inferred executor context: an off-loop function belonging to a
    ``# graftcheck: loop-confined`` class may not WRITE a ``self``
    attribute unless that attribute is ``# guarded-by:``-annotated
    (locked state is the sanctioned cross-thread channel — the PR 11/12
    in-thread flush-timing pattern times the fsync in the executor and
    feeds a LOCKED probe; this rule checks that shape instead of
    remembering it).  Reads are documented out of scope (an off-loop
    read of a config attribute is ubiquitous and benign; the write is
    where corruption starts).  The rule also extends the loop-confined
    lint transitively: a confined class's method calling an
    out-of-class helper that eventually sleeps or spawns threads is a
    finding (in-class sinks are already flagged directly).

The ``holds(_lock)`` call-site rule also becomes transitive here: the
intra-class rule (guarded_by.py) only sees ``self.<m>()`` calls, but a
collaborator routinely drives a node's holds-annotated methods through
a CROSS-OBJECT reference (``node._step_down(...)`` from the membership
ctx).  Such a call must either sit lexically inside ``with
<receiver>.<lock>`` or come from a class annotated ``# graftcheck:
called-under(<lock>)`` — the class-level declaration that every one of
its methods is invoked with the collaborator's named lock already held
(the _ConfigurationCtx convention, previously enforced by prose alone).
These findings report under the ``guarded-by`` rule: they are the same
lock discipline, seen one hop further.

Known limits (documented, not silently unchecked): attribute-receiver
calls (``self._log.flush()``) are never resolved, so chains through a
collaborator object are invisible — the lock-order checker's resolution
contract, kept deliberately; callables that escape through containers
or constructor wiring (``render=self.metrics_text``) are likewise out
of reach.  The chaos harness remains the net for those.
"""

from __future__ import annotations

import ast
import os
import re

from tpuraft.analysis import guarded_by
from tpuraft.analysis.blocking_calls import _is_fsm_class, _is_fsm_fn
from tpuraft.analysis.callgraph import (RESULT, FunctionInfo, ProjectIndex,
                                        _all_functions, attr_chain,
                                        format_chain)
from tpuraft.analysis.core import Finding, Module, decl_lineno, iter_classes

RULE_BLOCKING = "transitive-blocking"
RULE_AFFINITY = "loop-affinity"
RULE_HOLDS = "guarded-by"   # the holds call-site rule, one hop further

_CALLED_UNDER_RE = re.compile(r"#\s*graftcheck:\s*called-under\((\w+)\)")


def check(mods: list[Module], index: ProjectIndex) -> list[Finding]:
    out: list[Finding] = []
    confined = _confined_classes(mods)
    holds = _holds_methods(mods)
    called_under = _called_under_classes(mods)
    for mod in mods:
        midx = index.by_rel.get(mod.rel)
        if midx is None:
            continue
        tick_plane = (os.sep + "ops" + os.sep) in mod.rel \
            or mod.rel.startswith("ops" + os.sep)
        fsm_classes = {ci.name for ci in midx.classes.values()
                       if _is_fsm_class(ci.node)}
        for info in _all_functions(midx):
            out.extend(_check_function(index, info, tick_plane,
                                       fsm_classes, confined))
            out.extend(_check_holds_cross_object(index, info, holds,
                                                 called_under))
    out.extend(_check_off_loop_writes(index, confined))
    return out


# ---- transitive blocking ----------------------------------------------------


def _check_function(index: ProjectIndex, info: FunctionInfo,
                    tick_plane: bool, fsm_classes: set[str],
                    confined: dict) -> list[Finding]:
    out: list[Finding] = []
    mod = info.mod
    hard_why = None
    if tick_plane:
        hard_why = "in tick-plane code (tpuraft/ops)"
    elif (info.cls_name in fsm_classes
          and "<locals>" not in info.qualname) or _is_fsm_fn(info.name):
        hard_why = "on the FSM apply path"
    loop_why = ("in a coroutine (blocks the shared event loop)"
                if info.is_async else None)

    for line, lock in info.awaits_under_lock:
        out.append(Finding(
            RULE_BLOCKING, mod.rel, line,
            f"{info.qualname}() awaits while holding sync lock {lock} — "
            f"a threading lock held across a suspension point convoys "
            f"every task behind this one; use an asyncio lock or move "
            f"the await outside the critical section"))

    cls_key = (mod.rel, info.cls_name)
    in_confined = confined.get(cls_key)

    for site in info.calls:
        callee = index.resolve_call(info, site.call)
        if callee is None:
            continue
        if callee.is_async and not site.awaited:
            continue  # builds a coroutine; nothing runs here
        tb = index.transitive_blocks(callee)
        if tb:
            ctx = None
            kinds = list(tb)
            if site.lock is not None:
                ctx = f"while holding {site.lock}"
            elif hard_why is not None:
                ctx = hard_why
            elif loop_why is not None:
                kinds = [k for k in kinds if k != RESULT]
                ctx = loop_why if kinds else None
            if ctx is not None and kinds:
                names, msg, rel, line = tb[kinds[0]]
                # a chain that is empty means the callee blocks
                # DIRECTLY — the intra-procedural lint owns that
                # finding when callee and context share a function, but
                # from the CALLER's side it is still one hop away and
                # invisible to it, so report it here
                out.append(Finding(
                    RULE_BLOCKING, mod.rel, site.line,
                    f"call to {callee.qualname}() transitively blocks "
                    f"{ctx}: "
                    + format_chain((callee.qualname,) + names,
                                   msg, rel, line)))
                continue
        if in_confined is not None:
            out.extend(_confined_transitive(index, info, site, callee,
                                            in_confined))
    return out


# ---- loop-confined, transitively --------------------------------------------


def _confined_transitive(index: ProjectIndex, info: FunctionInfo, site,
                         callee: FunctionInfo, cls_name: str
                         ) -> list[Finding]:
    """A loop-confined class's method calling OUT-OF-CLASS code that
    eventually sleeps or spawns threads.  Same-class sinks are skipped:
    the direct loop-confined rule already flags those lines."""
    if callee.cls_name == cls_name and callee.mod is info.mod:
        return []
    out = []
    tb = index.transitive_blocks(callee)
    sleep = tb.get("sleep")
    if sleep is not None:
        names, msg, rel, line = sleep
        out.append(Finding(
            RULE_AFFINITY, info.mod.rel, site.line,
            f"loop-confined {cls_name}.{info.name}() calls "
            f"{callee.qualname}() which transitively sleeps: "
            + format_chain((callee.qualname,) + names, msg, rel, line)
            + " — blocks the event loop every other group runs on"))
    threads = index.transitive_threads(callee)
    if threads is not None:
        names, msg, rel, line = threads
        out.append(Finding(
            RULE_AFFINITY, info.mod.rel, site.line,
            f"loop-confined {cls_name}.{info.name}() calls "
            f"{callee.qualname}() which transitively reaches a "
            f"threading primitive: "
            + format_chain((callee.qualname,) + names, msg, rel, line)
            + " — its state has no lock; cross-thread access is a race"))
    return out


# ---- cross-object holds call-site rule --------------------------------------


def _check_holds_cross_object(index: ProjectIndex, info: FunctionInfo,
                              holds: dict, called_under: dict
                              ) -> list[Finding]:
    out: list[Finding] = []
    for site in info.calls:
        callee = index.resolve_call(info, site.call)
        if callee is None or callee.cls_name is None:
            continue
        need = holds.get((callee.mod.rel, callee.cls_name, callee.name))
        if not need:
            continue
        chain = attr_chain(site.call.func)
        if chain.startswith("self.") and info.cls_name == callee.cls_name \
                and info.mod is callee.mod:
            continue  # the intra-class rule (guarded_by.py) owns this
        recv = chain.rsplit(".", 1)[0] if "." in chain else ""
        lexically = {f"{recv}.{lk}" for lk in need} <= set(site.held)
        declared = need <= called_under.get((info.mod.rel, info.cls_name),
                                            set())
        if lexically or declared:
            continue
        out.append(Finding(
            RULE_HOLDS, info.mod.rel, site.line,
            f"{callee.qualname}() requires the caller to hold "
            f"{', '.join(sorted(need))} (holds annotation) but "
            f"{info.qualname}() calls it through "
            f"'{recv or chain}' without — wrap the call in "
            f"'with {recv or '<receiver>'}.{sorted(need)[0]}' or annotate "
            f"the calling class '# graftcheck: "
            f"called-under({sorted(need)[0]})'"))
    return out


def _holds_methods(mods: list[Module]) -> dict:
    """(mod.rel, cls, method) -> lock names the caller must hold."""
    out: dict = {}
    for mod in mods:
        for cls in iter_classes(mod):
            fields = guarded_by._collect_fields(mod, cls)
            for name, locks in guarded_by._holds_locks(
                    mod, cls, fields).items():
                out[(mod.rel, cls.node.name, name)] = locks
    return out


def _called_under_classes(mods: list[Module]) -> dict:
    """(mod.rel, cls) -> lock names the class's methods are always
    invoked under (collaborator-owned locks, declared at class level)."""
    out: dict = {}
    for mod in mods:
        for cls in iter_classes(mod):
            text = mod.comment_block_above(decl_lineno(cls.node))
            if cls.node.body and isinstance(cls.node.body[0], ast.Expr) \
                    and isinstance(cls.node.body[0].value, ast.Constant) \
                    and isinstance(cls.node.body[0].value.value, str):
                text += "\n" + cls.node.body[0].value.value
            locks = {m.group(1)
                     for m in _CALLED_UNDER_RE.finditer(text)}
            if locks:
                out[(mod.rel, cls.node.name)] = locks
    return out


# ---- executor context touching loop-confined state --------------------------


def _check_off_loop_writes(index: ProjectIndex, confined: dict
                           ) -> list[Finding]:
    out: list[Finding] = []
    for info, desc, root_rel, root_line in index.off_loop().values():
        cls = confined.get((info.mod.rel, info.cls_name))
        if cls is None:
            continue
        guarded = _guarded_fields(info.mod, cls)
        for attr, line in info.writes_self:
            if attr in guarded:
                continue  # locked state is the sanctioned channel
            out.append(Finding(
                RULE_AFFINITY, info.mod.rel, line,
                f"loop-confined {cls}.{info.name}() runs off-loop "
                f"({desc}, submitted at {root_rel}:{root_line}) and "
                f"writes self.{attr} without a guard — loop-confined "
                f"state touched from an inferred executor context; "
                f"post it back to the loop or annotate the field "
                f"guarded-by a real lock"))
    return out


# ---- shared lookups ---------------------------------------------------------


def _confined_classes(mods: list[Module]) -> dict:
    """(mod.rel, cls_name) -> cls_name for every loop-confined class;
    also caches the ClassInfo for guarded-field lookups."""
    out: dict = {}
    for mod in mods:
        for cls in iter_classes(mod):
            if _is_loop_confined(mod, cls):
                out[(mod.rel, cls.node.name)] = cls.node.name
    return out


def _is_loop_confined(mod: Module, cls) -> bool:
    node = cls.node
    return bool(
        guarded_by._LOOP_CONFINED_RE.search(
            mod.comment_block_above(decl_lineno(node)))
        or (node.body and isinstance(node.body[0], ast.Expr)
            and isinstance(node.body[0].value, ast.Constant)
            and isinstance(node.body[0].value.value, str)
            and "graftcheck: loop-confined" in node.body[0].value.value))


def _guarded_fields(mod: Module, cls_name: str) -> set[str]:
    for cls in iter_classes(mod):
        if cls.node.name == cls_name:
            return set(guarded_by._collect_fields(mod, cls))
    return set()
