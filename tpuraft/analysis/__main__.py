"""graftcheck CLI: ``python -m tpuraft.analysis [paths...] [options]``.

Exit codes: 0 clean, 1 findings, 2 internal error.  Pure-stdlib and
import-free with respect to the analyzed tree — a whole-tree run stays
well under the ~10s lint budget (the jax import alone would triple it).

  python -m tpuraft.analysis                 # lint tpuraft/ (the gate)
  python -m tpuraft.analysis examples        # lint another tree
  python -m tpuraft.analysis --rule guarded-by
  python -m tpuraft.analysis --json          # machine-readable findings
                                             # (file/line/rule/message)
                                             # for CI annotation
  python -m tpuraft.analysis --record        # re-record wire_schema.
                                             # lock.json + lock_order.json
                                             # after reviewing a change
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from tpuraft.analysis.core import (RULES, load_modules, repo_root,
                                   run_checkers)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpuraft.analysis",
        description="graftcheck: project-invariant static analysis "
                    "(guarded-by, lock-order, wire-schema, blocking-call, "
                    "future-leak, transitive-blocking, loop-affinity, "
                    "lane-coverage, host-sync, donated-read, raw-clock)")
    ap.add_argument("paths", nargs="*",
                    help="files/directories to lint (default: tpuraft/)")
    ap.add_argument("--record", action="store_true",
                    help="re-record wire_schema.lock.json and "
                         "lock_order.json from the live tree, then verify")
    ap.add_argument("--rule", action="append", choices=sorted(RULES),
                    help="run only these rules (repeatable)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as a JSON array of {file, line, "
                         "rule, message} on stdout (for CI annotation)")
    ap.add_argument("--quiet", action="store_true",
                    help="findings only, no summary line")
    args = ap.parse_args(argv)

    t0 = time.monotonic()
    roots = args.paths or [os.path.join(repo_root(), "tpuraft")]
    mods, findings = load_modules(roots)
    findings += run_checkers(mods, record=args.record,
                             rules=set(args.rule) if args.rule else None)
    if args.as_json:
        print(json.dumps(
            [{"file": f.path, "line": f.line, "rule": f.rule,
              "message": f.message} for f in findings], indent=2))
    else:
        for f in findings:
            print(f)
    if not args.quiet:
        dt = time.monotonic() - t0
        verdict = "clean" if not findings else f"{len(findings)} finding(s)"
        print(f"graftcheck: {len(mods)} files, {verdict} "
              f"[{dt:.2f}s]" + (" (lockfiles re-recorded)"
                                if args.record else ""),
              file=sys.stderr)
    return 1 if findings else 0


def _run() -> int:
    try:
        return main()
    except SystemExit:
        raise
    except Exception:  # noqa: BLE001 — the gate's error contract
        import traceback

        traceback.print_exc()
        print("graftcheck: internal error (exit 2)", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(_run())
