"""Checker 4: blocking-call lint.

The tick plane (``tpuraft/ops/``) sits under the device-step budget,
FSM apply paths run inline on the commit pipeline, coroutines share one
event loop with every raft group of the process, and anything holding a
lock convoys every waiter behind it.  A blocking call in any of those
contexts stalls the whole multi-raft plane, not one caller — so inside
them this lint forbids:

  * ``time.sleep(...)``
  * blocking socket IO: ``socket.create_connection`` /
    ``socket.socket(...)`` use, and ``.recv/.send/.sendall/.accept/
    .connect(...)`` on a receiver whose name mentions ``sock``
  * untimed ``<future>.result()`` — ``concurrent.futures`` waits with
    no timeout are exactly the PR 2 wedged-waiter class (#7/#8): the
    completer dies, the waiter blocks forever.  Pass ``timeout=`` so a
    wedge becomes a visible error.

Contexts checked (everything else is free to block):
  1. every function in ``tpuraft/ops/``                (tick plane)
  2. methods of ``*StateMachine`` classes (by name or base) and
     functions named ``on_apply*`` / ``apply_*``       (FSM apply path)
  3. any ``async def`` body — sleep/socket only: ``.result()`` on a
     *done* asyncio task is non-blocking and idiomatic   (event loop)
  4. statements lexically inside ``with <lock-ish>``   (lock held)

Passing a blocking function as a *reference* (``run_in_executor(None,
time.sleep, ...)``) is fine — only calls are flagged.
"""

from __future__ import annotations

import ast
import os
import re

from tpuraft.analysis.core import Finding, Module, attr_chain

RULE = "blocking-call"

_LOCKISH = re.compile(r"lock|guard|mutex", re.IGNORECASE)
_SOCK_METHODS = {"recv", "recv_into", "send", "sendall", "accept", "connect"}


def check(mods: list[Module]) -> list[Finding]:
    out: list[Finding] = []
    for mod in mods:
        tick_plane = (os.sep + "ops" + os.sep) in mod.rel \
            or mod.rel.startswith("ops" + os.sep)
        out.extend(_scan_module(mod, tick_plane))
    return out


def _is_fsm_class(cls: ast.ClassDef) -> bool:
    names = [cls.name] + [attr_chain(b) or getattr(b, "id", "")
                          for b in cls.bases]
    return any(n.split(".")[-1].endswith("StateMachine") for n in names if n)


def _is_fsm_fn(name: str) -> bool:
    return name.startswith("on_apply") or name.startswith("apply_")


def _lock_name(item: ast.withitem) -> str | None:
    expr = item.context_expr
    chain = attr_chain(expr)
    if not chain and isinstance(expr, ast.Call):
        chain = attr_chain(expr.func)
    if chain and _LOCKISH.search(chain):
        return chain
    return None


def _scan_module(mod: Module, tick_plane: bool) -> list[Finding]:
    out: list[Finding] = []

    def visit(node, held: str | None, hard_why: str | None,
              loop_why: str | None) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            # async with counts too: blocking under the asyncio node
            # lock stalls the loop AND every waiter queued on the lock
            lock = next((_lock_name(i) for i in node.items
                         if _lock_name(i)), None)
            inner = lock or held
            for child in node.body:
                visit(child, inner, hard_why, loop_why)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scan_fn(node, hard_why if tick_plane else None)
            return
        if isinstance(node, ast.Lambda):
            # a lambda body runs when called — commonly on an executor
            # thread (run_in_executor(None, lambda: ...)): never under
            # the lexical lock or the enclosing coroutine; only the
            # module-wide tick-plane context persists
            visit(node.body, None,
                  hard_why if tick_plane else None, None)
            return
        if isinstance(node, ast.Call):
            found = _blocking_call(node)
            if found:
                msg, is_result_wait = found
                ctx = (f"while holding {held}" if held
                       else hard_why if hard_why
                       else loop_why if not is_result_wait else None)
                if ctx:
                    out.append(Finding(
                        RULE, mod.rel, node.lineno, f"{msg} {ctx}"))
        for child in ast.iter_child_nodes(node):
            visit(child, held, hard_why, loop_why)

    def scan_fn(fn, hard_why: str | None) -> None:
        """hard_why: tick-plane / FSM context (flags everything incl.
        untimed result()); coroutine bodies get the softer loop context
        (sleep/socket only)."""
        if hard_why is None and _is_fsm_fn(fn.name):
            hard_why = "on the FSM apply path"
        loop_why = ("in a coroutine (blocks the shared event loop)"
                    if isinstance(fn, ast.AsyncFunctionDef) else None)
        for stmt in fn.body:
            visit(stmt, None, hard_why, loop_why)

    why_module = "in tick-plane code (tpuraft/ops)" if tick_plane else None
    for node in mod.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scan_fn(node, why_module)
        elif isinstance(node, ast.ClassDef):
            fsm = _is_fsm_class(node)
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    scan_fn(item, why_module or (
                        "on the FSM apply path" if fsm else None))
    return out


def _blocking_call(node: ast.Call) -> tuple[str, bool] | None:
    chain = attr_chain(node.func)
    if chain == "time.sleep":
        return "time.sleep()", False
    if chain in ("socket.create_connection", "socket.socket"):
        return f"{chain}()", False
    if isinstance(node.func, ast.Attribute):
        meth = node.func.attr
        recv = attr_chain(node.func.value)
        if meth in _SOCK_METHODS and recv and "sock" in recv.lower():
            return f"blocking socket IO {recv}.{meth}()", False
        if meth == "result" and not node.args \
                and not any(kw.arg == "timeout" for kw in node.keywords):
            return (f"untimed {recv or '<expr>'}.result() (wedged-waiter "
                    f"class: pass timeout=)"), True
    return None
