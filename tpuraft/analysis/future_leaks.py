"""Checker 5: future-completion lint (the wedged-waiter class).

A function that creates a future and completes it locally must complete
it on EVERY path.  The shape that wedges (PR 2 #7/#8: the in-flight
``change_peers`` waiter on shutdown, the catch-up waiter on abort):

    fut = loop.create_future()
    ...
    result = do_risky_work()        # raises ->
    fut.set_result(result)          # never runs; waiter blocks forever

The rule: between the creation and the first completion call, any
expression that can raise (i.e. any call) makes the straight-line
completion insufficient — there must ALSO be a completion
(``set_result`` / ``set_exception`` / ``cancel``) inside an ``except``
handler or ``finally`` block of the function, covering the failure path.

Scope (deliberate, documented): futures whose OWNERSHIP ESCAPES the
function — returned, yielded, stored into an attribute/container,
passed to another call, or captured by a closure — are skipped: their
completion contract lives with the new owner, which a per-function AST
pass cannot see.  The chaos harness remains the check for those; this
lint kills the local-completion class at review time instead.  A future
that neither escapes nor is completed is flagged outright.
"""

from __future__ import annotations

import ast

from tpuraft.analysis.core import Finding, Module, attr_chain, parent_map

RULE = "future-leak"

_COMPLETES = {"set_result", "set_exception", "cancel"}

_CREATORS = (
    "create_future",      # loop.create_future() / get_event_loop()...
)


def _is_future_creation(value: ast.AST) -> bool:
    if not isinstance(value, ast.Call):
        return False
    # X.create_future() for any receiver, including the chained
    # asyncio.get_running_loop().create_future() (receiver is a Call,
    # so attr_chain alone can't see it)
    if isinstance(value.func, ast.Attribute) and value.func.attr in _CREATORS:
        return True
    chain = attr_chain(value.func)
    # asyncio.Future() / concurrent.futures.Future() / bare Future()
    return chain in ("asyncio.Future", "concurrent.futures.Future",
                     "futures.Future", "Future")


def check(mods: list[Module]) -> list[Finding]:
    out: list[Finding] = []
    for mod in mods:
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.extend(_scan_function(mod, node))
    return out


class _FutUse:
    def __init__(self, name: str, line: int):
        self.name = name
        self.line = line
        self.escapes = False
        self.completions: list[ast.Call] = []   # X.set_result(...) etc.
        self.other_uses = 0


def _scan_function(mod: Module, fn) -> list[Finding]:
    # locals assigned a fresh future in THIS function's direct body
    # (nested defs analyzed on their own walk(tree) visit)
    futs: dict[str, _FutUse] = {}
    direct = list(_iter_direct(fn))
    for node in direct:
        target = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
        elif isinstance(node, ast.AnnAssign):
            # fut: asyncio.Future = loop.create_future() — the annotated
            # form is common in-tree (tcp.py/native_tcp.py) and must not
            # exempt the rule
            target = node.target
        if target is not None and isinstance(target, ast.Name) \
                and node.value is not None \
                and _is_future_creation(node.value):
            futs[target.id] = _FutUse(target.id, node.lineno)
    if not futs:
        return []

    parents = parent_map(fn)
    for node in direct:
        if isinstance(node, ast.Name) and node.id in futs \
                and isinstance(node.ctx, ast.Load):
            use = futs[node.id]
            parent = parents.get(node)
            # completion: X.set_result(...) / X.set_exception / X.cancel
            if isinstance(parent, ast.Attribute) \
                    and parent.attr in _COMPLETES:
                call = parents.get(parent)
                if isinstance(call, ast.Call) and call.func is parent:
                    use.completions.append(call)
                    continue
            # done-guard reads don't transfer ownership
            if isinstance(parent, ast.Attribute) and parent.attr in (
                    "done", "cancelled", "result", "exception",
                    "add_done_callback"):
                use.other_uses += 1
                continue
            if isinstance(parent, (ast.Return, ast.Yield, ast.Await)):
                use.escapes = True
                continue
            # any other Load use: argument, container element, attribute
            # store RHS, closure capture... — ownership escapes
            use.escapes = True
    # closure capture: a nested def referencing the name
    for node in ast.walk(fn):
        if node is fn:
            continue
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            for inner in ast.walk(node):
                if isinstance(inner, ast.Name) and inner.id in futs:
                    futs[inner.id].escapes = True

    out: list[Finding] = []
    for use in futs.values():
        if use.escapes:
            continue
        if not use.completions:
            out.append(Finding(
                RULE, mod.rel, use.line,
                f"{fn.name}() creates future '{use.name}' but never "
                f"completes it and it never escapes — every waiter "
                f"wedges"))
            continue
        if _has_risky_gap(fn, use, parents) \
                and not _completed_on_failure_path(use, parents):
            out.append(Finding(
                RULE, mod.rel, use.line,
                f"{fn.name}() completes future '{use.name}' only on the "
                f"straight-line path; a raise between creation "
                f"(line {use.line}) and completion leaves waiters wedged "
                f"— complete it in an except/finally too"))
    return out


def _iter_direct(fn):
    """Walk fn's body but do not descend into nested function defs."""
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _has_risky_gap(fn, use: _FutUse, parents) -> bool:
    """Any call (other than the creation and the completions themselves)
    between creation and the first completion can raise."""
    first_completion = min(c.lineno for c in use.completions)
    for node in _iter_direct(fn):
        if isinstance(node, ast.Call) \
                and use.line < node.lineno < first_completion:
            chain = attr_chain(node.func)
            if chain.split(".")[-1] in _COMPLETES:
                continue
            return True
    return False


def _completed_on_failure_path(use: _FutUse, parents) -> bool:
    """Some completion call sits in an except handler or finally block."""
    for call in use.completions:
        node = call
        while True:
            parent = parents.get(node)
            if parent is None:
                break
            if isinstance(parent, ast.ExceptHandler):
                return True
            if isinstance(parent, ast.Try) and _in_body(
                    parent.finalbody, node):
                return True
            node = parent
    return False


def _in_body(body: list, node: ast.AST) -> bool:
    return any(node is stmt for stmt in body)
