"""Checker 1: guarded-by lock discipline (+ loop-confined classes).

Annotations (trailing comment on the statement, or the line above):

  self._segments = []        # guarded-by: _lock
  self.state = ...           # guarded-by: _lock (writes)
  _path_locks: dict = {}     # guarded-by: _paths_guard   (module global)

A field annotated ``guarded-by: <lock>`` may only be touched inside a
``with self.<lock>`` / ``async with self.<lock>`` block.  The
``(writes)`` variant checks mutations only — the asyncio-plane
convention (node.py): single reads on the owning event loop are safe,
multi-await critical sections must hold the lock, so every *rebind* of
protocol state goes through it.

Helper methods that are *called with the lock held* declare it:

  def _enter_error_locked(self, status):          # name suffix, or
  def _find_segment(self, index):  # graftcheck: holds(_lock)

and the call-site rule closes the loop: a ``holds``-annotated method may
only be invoked (as ``self.m(...)``) from a lock-held context — calling
``_step_down`` without the node lock is itself a finding.

Closures reset the held set: a nested ``def``/lambda runs later, outside
the lexical ``with`` (the PR 2 `FileLogStorage.shutdown` race was
exactly a "looks inside the block, runs outside it" confusion).

Classes annotated ``# graftcheck: loop-confined`` declare event-loop
confinement; reaching for ``threading`` primitives or ``time.sleep``
inside one is a finding (rule ``loop-confined``) — their state has no
lock to take, so the only legal concurrency is the loop itself.

Known limits (documented, not silently unchecked): cross-object access
(``node.conf_entry = ...`` from a collaborator) and container-interior
mutation under ``(writes)`` (``self._acks[k] = v`` reads the dict
attribute) are out of scope; the lock-order and blocking-call checkers
cover the inter-object hazards this checker cannot see.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass

from tpuraft.analysis.core import (Finding, Module, attr_chain, decl_lineno,
                                   iter_classes)

_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_]\w*)\s*(\(writes\))?")
_HOLDS_RE = re.compile(r"#\s*graftcheck:\s*holds\((\w+)\)")
_LOOP_CONFINED_RE = re.compile(r"#\s*graftcheck:\s*loop-confined")

RULE = "guarded-by"
RULE_LOOP = "loop-confined"


@dataclass
class _Field:
    name: str
    lock: str          # attribute name relative to self ('' prefix) or global
    writes_only: bool
    line: int


def check(mods: list[Module]) -> list[Finding]:
    out: list[Finding] = []
    for mod in mods:
        out.extend(_check_module_globals(mod))
        for cls in iter_classes(mod):
            out.extend(_check_class(mod, cls))
    return out


# ---- class fields -----------------------------------------------------------


def _collect_fields(mod: Module, cls) -> dict[str, _Field]:
    fields: dict[str, _Field] = {}

    def note(target: ast.AST, line: int) -> None:
        m = _GUARDED_RE.search(mod.comment_at_or_above(line))
        if not m:
            return
        name = None
        if isinstance(target, ast.Attribute) and isinstance(
                target.value, ast.Name) and target.value.id == "self":
            name = target.attr
        elif isinstance(target, ast.Name):
            name = target.id
        if name:
            fields[name] = _Field(name, m.group(1), bool(m.group(2)), line)

    init = cls.methods.get("__init__")
    bodies = list(cls.node.body) + (list(ast.walk(init)) if init else [])
    for node in bodies:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                note(t, node.lineno)
        elif isinstance(node, ast.AnnAssign):
            note(node.target, node.lineno)
    return fields


def _holds_locks(mod: Module, cls, fields) -> dict[str, set[str]]:
    """method name -> set of lock names the caller must hold."""
    class_locks = {f.lock for f in fields.values()}
    holds: dict[str, set[str]] = {}
    for name, fn in cls.methods.items():
        locks = set()
        for m in _HOLDS_RE.finditer(mod.comment_at_or_above(fn.lineno)):
            locks.add(m.group(1))
        # the bare name suffix is only unambiguous when the class guards
        # everything with ONE lock; with several, the suffix can't say
        # WHICH is held (granting all of them both over-demands at call
        # sites and over-grants in the body) — annotate explicitly
        if name.endswith("_locked") and len(class_locks) == 1:
            locks |= class_locks
        if locks:
            holds[name] = locks
    return holds


def _with_locks(node: ast.With | ast.AsyncWith) -> set[str]:
    """Lock names acquired by this with-statement, as dotted chains
    ('self._lock', 'G')."""
    acquired = set()
    for item in node.items:
        chain = attr_chain(item.context_expr)
        if chain:
            acquired.add(chain)
    return acquired


def _check_class(mod: Module, cls) -> list[Finding]:
    out: list[Finding] = []
    fields = _collect_fields(mod, cls)
    holds = _holds_locks(mod, cls, fields)
    loop_confined = bool(
        _LOOP_CONFINED_RE.search(
            mod.comment_block_above(decl_lineno(cls.node)))
        or (cls.node.body and isinstance(cls.node.body[0], ast.Expr)
            and isinstance(cls.node.body[0].value, ast.Constant)
            and isinstance(cls.node.body[0].value.value, str)
            and "graftcheck: loop-confined" in cls.node.body[0].value.value))

    for name, fn in cls.methods.items():
        if loop_confined:
            # __init__ included: construction predates SHARING (which is
            # why guarded-by exempts it below) but a constructor that
            # spawns threads or sleeps is no less a confinement breach
            out.extend(_scan_loop_confined(mod, fn))
        if name == "__init__":
            continue
        held0 = {f"self.{lk}" for lk in holds.get(name, ())}
        out.extend(_scan_body(mod, cls, fn, fields, holds, held0))
    return out


def _scan_body(mod: Module, cls, fn, fields, holds,
               held: set[str]) -> list[Finding]:
    out: list[Finding] = []

    def visit(node: ast.AST, held: frozenset[str]) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = held | _with_locks(node)
            for item in node.items:
                visit(item.context_expr, held)
            for child in node.body:
                visit(child, frozenset(inner))
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # a closure runs later, outside the lexical lock scope
            body = node.body if isinstance(node.body, list) else [node.body]
            for child in body:
                visit(child, frozenset())
            return
        if isinstance(node, ast.Attribute) and isinstance(
                node.value, ast.Name) and node.value.id == "self":
            f = fields.get(node.attr)
            if f is not None:
                is_write = isinstance(node.ctx, (ast.Store, ast.Del))
                if (is_write or not f.writes_only) \
                        and f"self.{f.lock}" not in held:
                    kind = "written" if is_write else "read"
                    out.append(Finding(
                        RULE, mod.rel, node.lineno,
                        f"{cls.node.name}.{node.attr} is guarded-by "
                        f"{f.lock} (declared at line {f.line}) but {kind} "
                        f"in {fn.name}() without holding self.{f.lock}"))
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            if chain.startswith("self."):
                callee = chain[len("self."):]
                need = holds.get(callee)
                if need and not {f"self.{lk}" for lk in need} <= held:
                    out.append(Finding(
                        RULE, mod.rel, node.lineno,
                        f"{cls.node.name}.{callee}() requires the caller "
                        f"to hold {', '.join(sorted(need))} (holds "
                        f"annotation) but {fn.name}() calls it without"))
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for stmt in fn.body:
        visit(stmt, frozenset(held))
    return out


# ---- loop-confined ----------------------------------------------------------


def _scan_loop_confined(mod: Module, fn) -> list[Finding]:
    out = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        chain = attr_chain(node.func)
        if chain.startswith("threading."):
            out.append(Finding(
                RULE_LOOP, mod.rel, node.lineno,
                f"loop-confined class uses {chain}() in {fn.name}() — "
                f"its state has no lock; cross-thread access is a race"))
        elif chain == "time.sleep":
            out.append(Finding(
                RULE_LOOP, mod.rel, node.lineno,
                f"loop-confined class calls time.sleep() in {fn.name}() — "
                f"blocks the event loop every other group runs on"))
    return out


# ---- module-level globals ---------------------------------------------------


def _module_global_fields(mod: Module) -> dict[str, _Field]:
    fields: dict[str, _Field] = {}
    for node in mod.tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        for t in targets:
            if isinstance(t, ast.Name):
                m = _GUARDED_RE.search(mod.comment_at_or_above(node.lineno))
                if m:
                    fields[t.id] = _Field(t.id, m.group(1), bool(m.group(2)),
                                          node.lineno)
    return fields


def _check_module_globals(mod: Module) -> list[Finding]:
    fields = _module_global_fields(mod)
    if not fields:
        return []
    out: list[Finding] = []

    def visit(node: ast.AST, held: frozenset[str]) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = held | _with_locks(node)
            for child in node.body:
                visit(child, frozenset(inner))
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # same closure rule as the class checker: a nested def runs
            # later, outside the lexical lock scope
            body = node.body if isinstance(node.body, list) else [node.body]
            for child in body:
                visit(child, frozenset())
            return
        if isinstance(node, ast.Name) and node.id in fields:
            f = fields[node.id]
            if node.lineno != f.line and f.lock not in held:
                is_write = isinstance(node.ctx, (ast.Store, ast.Del))
                if is_write or not f.writes_only:
                    out.append(Finding(
                        RULE, mod.rel, node.lineno,
                        f"module global {node.id} is guarded-by {f.lock} "
                        f"(declared at line {f.line}) but touched without "
                        f"holding it"))
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    visit_targets = [n for n in mod.tree.body
                     if not isinstance(n, (ast.Import, ast.ImportFrom))]
    for stmt in visit_targets:
        visit(stmt, frozenset())
    return out
