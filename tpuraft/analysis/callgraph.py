"""Whole-program index for graftcheck: call graph + function summaries.

PR 7's checkers were deliberately intra-procedural — one level of
call resolution inside one module (lock_order.py).  That stops seeing
hazards the moment they take one hop: a loop-confined method calling a
helper that transitively ``time.sleep``s, an FSM apply path reaching an
untimed ``Future.result()`` through two utility functions, a lambda
handed to ``run_in_executor`` that fans out into methods mutating
loop-confined state.  This module builds, ONCE per lint run:

  * a project-wide call graph.  Resolution rules are lock_order.py's
    (``self.m()``, module ``f()``, ``ClassName()`` ctors, bare-local
    ``obj.m()`` iff the method name is unique in the module), extended
    CROSS-MODULE along absolute imports whose target module is in the
    analyzed set (``from tpuraft.x import f`` / ``import tpuraft.x``):
    the gate analyzes all of ``tpuraft/``, so every in-package import
    edge resolves.  Attribute receivers (``self._log.flush()``) stay
    deliberately unresolved — common method names collide with stdlib
    handles, and a wrong edge is worse than a missing one.

  * per-function summaries {blocks, acquires, awaits-under-lock,
    spawns-threads, writes-self-attrs}, computed from the function's
    DIRECT synchronous body (nested defs/lambdas run later, in their
    own context — they get their own summaries).

  * transitive closures over those summaries (memoized): "does calling
    f eventually block?", with the offending chain retained so the
    finding can say ``f -> g -> time.sleep() (storage/x.py:42)``
    instead of pointing at an innocent-looking call site.

  * an OFF-LOOP set: functions inferred to run on executor threads —
    ``run_in_executor`` targets, ``Thread(target=)``, ``executor
    .submit(...)`` arguments, including lambdas and nested defs —
    closed transitively over the call graph.  The PR 11/12 in-thread
    flush-timing pattern (time the fsync IN the executor, feed a
    LOCKED probe) is safe exactly because the off-loop code writes no
    unguarded loop-confined state; the concurrency checker verifies
    that instead of remembering it.

Everything is pure stdlib AST; summaries are computed lazily and cached
per function node, so a whole-tree run pays one extra AST walk per
module plus the (small) transitive closure.
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from tpuraft.analysis.core import Module, attr_chain

_LOCKISH = re.compile(r"lock|guard|mutex", re.IGNORECASE)
_SOCK_METHODS = {"recv", "recv_into", "send", "sendall", "accept", "connect"}
_EXECUTORISH = re.compile(r"executor|pool|worker", re.IGNORECASE)

# blocking kinds a summary can carry
SLEEP, SOCKET, RESULT = "sleep", "socket", "result"


def direct_blocking_call(node: ast.Call) -> Optional[tuple[str, str]]:
    """(kind, message) when this call blocks directly; None otherwise.
    Mirrors blocking_calls._blocking_call — one definition of "blocks"
    shared by the direct lint and the summaries."""
    chain = attr_chain(node.func)
    if chain == "time.sleep":
        return SLEEP, "time.sleep()"
    if chain in ("socket.create_connection", "socket.socket"):
        return SOCKET, f"{chain}()"
    if isinstance(node.func, ast.Attribute):
        meth = node.func.attr
        recv = attr_chain(node.func.value)
        if meth in _SOCK_METHODS and recv and "sock" in recv.lower():
            return SOCKET, f"blocking socket IO {recv}.{meth}()"
        if meth == "result" and not node.args \
                and not any(kw.arg == "timeout" for kw in node.keywords):
            return RESULT, f"untimed {recv or '<expr>'}.result()"
    return None


def _module_name_to_rel(dotted: str) -> str:
    """'tpuraft.core.node' -> 'tpuraft/core/node.py' (the Module.rel
    shape for in-repo files)."""
    return dotted.replace(".", "/") + ".py"


class CallSite:
    __slots__ = ("call", "line", "awaited", "lock", "held")

    def __init__(self, call: ast.Call, line: int, awaited: bool,
                 held: tuple[str, ...]):
        self.call = call
        self.line = line
        self.awaited = awaited   # the call is the operand of an Await
        # lexically-enclosing SYNC with-locks, outermost first; ``lock``
        # keeps the innermost for messages
        self.held = held
        self.lock = held[-1] if held else None


class FunctionInfo:
    """Direct (non-transitive) facts about one function/method body."""

    __slots__ = ("mod", "cls_name", "name", "node", "is_async",
                 "blocks", "threads", "acquires", "awaits_under_lock",
                 "calls", "writes_self", "nested", "qualname")

    def __init__(self, mod: Module, cls_name: Optional[str], name: str,
                 node, qualname: str):
        self.mod = mod
        self.cls_name = cls_name
        self.name = name
        self.node = node
        self.qualname = qualname
        self.is_async = isinstance(node, ast.AsyncFunctionDef)
        self.blocks: list[tuple[str, str, int]] = []   # (kind, msg, line)
        self.threads: list[tuple[str, int]] = []       # (chain, line)
        self.acquires: set[str] = set()
        self.awaits_under_lock: list[tuple[int, str]] = []
        self.calls: list[CallSite] = []
        self.writes_self: list[tuple[str, int]] = []   # (attr, line)
        self.nested: dict[str, "FunctionInfo"] = {}    # nested defs by name


class _ClassIdx:
    __slots__ = ("name", "node", "methods", "bases")

    def __init__(self, name: str, node: ast.ClassDef):
        self.name = name
        self.node = node
        self.methods: dict[str, FunctionInfo] = {}
        self.bases: list[str] = [attr_chain(b) or getattr(b, "id", "")
                                 for b in node.bases]


class _ModuleIdx:
    __slots__ = ("mod", "functions", "classes", "imports", "method_owners")

    def __init__(self, mod: Module):
        self.mod = mod
        self.functions: dict[str, FunctionInfo] = {}
        self.classes: dict[str, _ClassIdx] = {}
        # local name -> ("mod", rel) for imported modules,
        #               ("sym", rel, symbol) for imported symbols
        self.imports: dict[str, tuple] = {}
        self.method_owners: dict[str, list[str]] = {}


class ProjectIndex:
    """The once-per-run whole-program index (ISSUE 14 tentpole)."""

    def __init__(self, mods: list[Module]):
        self.mods = mods
        self.by_rel: dict[str, _ModuleIdx] = {}
        for mod in mods:
            self.by_rel[mod.rel] = self._index_module(mod)
        # memo caches for the transitive closures
        self._block_memo: dict[int, dict[str, tuple]] = {}
        self._thread_memo: dict[int, Optional[tuple]] = {}
        self._off_loop: Optional[dict[int, tuple]] = None

    # -- module indexing -----------------------------------------------------

    def _index_module(self, mod: Module) -> _ModuleIdx:
        idx = _ModuleIdx(mod)
        for node in mod.tree.body:
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                self._index_import(idx, node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                idx.functions[node.name] = self._scan_function(
                    mod, None, node, node.name)
            elif isinstance(node, ast.ClassDef):
                ci = _ClassIdx(node.name, node)
                idx.classes[node.name] = ci
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        ci.methods[item.name] = self._scan_function(
                            mod, node.name, item,
                            f"{node.name}.{item.name}")
                        idx.method_owners.setdefault(
                            item.name, []).append(node.name)
        return idx

    def _index_import(self, idx: _ModuleIdx, node) -> None:
        if isinstance(node, ast.Import):
            for alias in node.names:
                rel = _module_name_to_rel(alias.name)
                local = alias.asname or alias.name.split(".")[0]
                if alias.asname is None and "." in alias.name:
                    # `import tpuraft.core.node` binds `tpuraft`; calls
                    # spell the full chain, which attr_chain flattens —
                    # map the full dotted prefix instead
                    idx.imports.setdefault(alias.name, ("mod", rel))
                else:
                    idx.imports[local] = ("mod", rel)
            return
        if node.level:           # relative imports: not used in-tree
            return
        if node.module is None:
            return
        mod_rel = _module_name_to_rel(node.module)
        for alias in node.names:
            local = alias.asname or alias.name
            # `from tpuraft.core import node` imports a MODULE; `from
            # tpuraft.core.node import Node` imports a symbol.  Decide
            # by what exists in the analyzed set.
            sub_rel = _module_name_to_rel(f"{node.module}.{alias.name}")
            idx.imports[local] = ("maybe", mod_rel, alias.name, sub_rel)

    # -- per-function fact scan ----------------------------------------------

    def _scan_function(self, mod: Module, cls_name: Optional[str],
                       fn, qualname: str) -> FunctionInfo:
        info = FunctionInfo(mod, cls_name, fn.name, fn, qualname)

        def visit(node, held: tuple[str, ...]) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # nested def: its body runs later in its own context
                info.nested[node.name] = self._scan_function(
                    mod, cls_name, node, f"{qualname}.<locals>.{node.name}")
                return
            if isinstance(node, ast.Lambda):
                return  # lambdas handled at their use sites (off-loop roots)
            if isinstance(node, (ast.With, ast.AsyncWith)):
                inner = held
                for item in node.items:
                    ln = _lock_name(item)
                    if ln:
                        info.acquires.add(ln)
                        if isinstance(node, ast.With):
                            inner = inner + (ln,)  # sync lock: held across
                for item in node.items:
                    visit(item.context_expr, held)
                for child in node.body:
                    visit(child, inner)
                return
            if isinstance(node, ast.Await):
                if held:
                    info.awaits_under_lock.append((node.lineno, held[-1]))
                if isinstance(node.value, ast.Call):
                    self._note_call(info, node.value, awaited=True, held=held)
                    for arg in ast.iter_child_nodes(node.value):
                        visit(arg, held)
                    return
                visit(node.value, held)
                return
            if isinstance(node, ast.Call):
                self._note_call(info, node, awaited=False, held=held)
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.ctx, (ast.Store, ast.Del)) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id == "self":
                info.writes_self.append((node.attr, node.lineno))
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in fn.body:
            visit(stmt, ())
        return info

    def _note_call(self, info: FunctionInfo, node: ast.Call,
                   awaited: bool, held: tuple[str, ...]) -> None:
        found = direct_blocking_call(node)
        if found:
            kind, msg = found
            info.blocks.append((kind, msg, node.lineno))
        chain = attr_chain(node.func)
        # only CONCURRENCY SPAWNS propagate transitively: a helper that
        # constructs a threading.Lock() is a thread-SAFE collaborator
        # (locked state is the sanctioned cross-thread channel), not a
        # confinement breach — the direct loop-confined rule still
        # flags any threading.* use written inside the class itself
        if chain in ("threading.Thread", "Thread", "threading.Timer"):
            info.threads.append((chain, node.lineno))
        info.calls.append(CallSite(node, node.lineno, awaited, held))

    # -- resolution ----------------------------------------------------------

    def resolve_import(self, idx: _ModuleIdx, local: str
                       ) -> Optional[tuple[str, Optional[str]]]:
        """Local imported name -> (module rel, symbol|None)."""
        entry = idx.imports.get(local)
        if entry is None:
            return None
        if entry[0] == "mod":
            return (entry[1], None) if entry[1] in self.by_rel else None
        # "maybe": symbol of mod_rel, or submodule sub_rel
        _, mod_rel, sym, sub_rel = entry
        if sub_rel in self.by_rel:
            return (sub_rel, None)
        if mod_rel in self.by_rel:
            return (mod_rel, sym)
        return None

    def _lookup(self, rel: str, name: str) -> Optional[FunctionInfo]:
        midx = self.by_rel.get(rel)
        if midx is None:
            return None
        fn = midx.functions.get(name)
        if fn is not None:
            return fn
        ci = midx.classes.get(name)
        if ci is not None:
            return ci.methods.get("__init__")
        return None

    def resolve_call(self, info: FunctionInfo, call: ast.Call
                     ) -> Optional[FunctionInfo]:
        """Resolve a call site inside ``info`` to a known function, or
        None (unresolvable / out of the analyzed set)."""
        return self._resolve_expr(info, call.func)

    def _resolve_expr(self, info: FunctionInfo, func
                      ) -> Optional[FunctionInfo]:
        midx = self.by_rel.get(info.mod.rel)
        if midx is None:
            return None
        chain = attr_chain(func)
        if not chain:
            return None
        # self.m(...): method of the lexical class (one level of base
        # following along resolvable names)
        if chain.startswith("self.") and "." not in chain[5:]:
            return self._resolve_method(midx, info.cls_name, chain[5:])
        if "." not in chain:
            # nested def in the same function
            if chain in info.nested:
                return info.nested[chain]
            # module function / local class ctor
            target = midx.functions.get(chain)
            if target is not None:
                return target
            ci = midx.classes.get(chain)
            if ci is not None:
                return ci.methods.get("__init__")
            imp = self.resolve_import(midx, chain)
            if imp is not None and imp[1] is not None:
                return self._lookup(imp[0], imp[1])
            return None
        head, rest = chain.split(".", 1)
        # imported module attribute: mod.f(...) / pkg.mod.f(...)
        for prefix in (_dotted_prefixes(chain)):
            ent = midx.imports.get(prefix)
            if ent is not None:
                imp = self.resolve_import(midx, prefix)
                if imp is None:
                    return None
                rel, sym = imp
                tail = chain[len(prefix) + 1:]
                if sym is None and "." not in tail:
                    return self._lookup(rel, tail)
                return None
        # ClassName.m(...) on a local class
        ci = midx.classes.get(head)
        if ci is not None and "." not in rest:
            return ci.methods.get(rest)
        # obj.m(...) on a bare local: unique-owner rule (lock_order.py)
        if isinstance(func, ast.Attribute) \
                and isinstance(func.value, ast.Name) \
                and func.value.id != "self" and "." not in rest:
            owners = midx.method_owners.get(rest, ())
            if len(owners) == 1:
                return midx.classes[owners[0]].methods.get(rest)
        return None

    def _resolve_method(self, midx: _ModuleIdx, cls_name: Optional[str],
                        meth: str) -> Optional[FunctionInfo]:
        seen = set()
        while cls_name and cls_name not in seen:
            seen.add(cls_name)
            ci = midx.classes.get(cls_name)
            if ci is None:
                return None
            m = ci.methods.get(meth)
            if m is not None:
                return m
            # one resolvable base, same module or imported
            nxt = None
            for b in ci.bases:
                base = b.split(".")[-1]
                if base in midx.classes:
                    nxt = base
                    break
                imp = self.resolve_import(midx, b.split(".")[0])
                if imp is not None:
                    rel = imp[0]
                    target = self.by_rel.get(rel)
                    if target is not None and base in target.classes:
                        bm = target.classes[base].methods.get(meth)
                        if bm is not None:
                            return bm
            cls_name = nxt
        return None

    # -- transitive closures -------------------------------------------------

    def transitive_blocks(self, info: FunctionInfo
                          ) -> dict[str, tuple]:
        """kind -> (chain_names, msg, rel, line): the first observed
        path from ``info`` to a direct blocking call of that kind,
        following only edges that execute synchronously (plain calls to
        sync functions; awaited calls to coroutines)."""
        memo = self._block_memo
        key = id(info.node)
        if key in memo:
            return memo[key]
        memo[key] = {}  # cycle guard: in-progress = no extra facts
        out: dict[str, tuple] = {}
        for kind, msg, line in info.blocks:
            out.setdefault(kind, ((), msg, info.mod.rel, line))
        for site in info.calls:
            callee = self.resolve_call(info, site.call)
            if callee is None or not _edge_executes(site, callee):
                continue
            for kind, (names, msg, rel, line) in \
                    self.transitive_blocks(callee).items():
                if kind not in out:
                    out[kind] = ((callee.qualname,) + names, msg, rel, line)
        memo[key] = out
        return out

    def transitive_threads(self, info: FunctionInfo) -> Optional[tuple]:
        """(chain_names, chain_msg, rel, line) when calling ``info``
        eventually reaches a threading primitive; None otherwise."""
        memo = self._thread_memo
        key = id(info.node)
        if key in memo:
            return memo[key]
        memo[key] = None
        out = None
        if info.threads:
            chain, line = info.threads[0]
            out = ((), f"{chain}()", info.mod.rel, line)
        else:
            for site in info.calls:
                callee = self.resolve_call(info, site.call)
                if callee is None or not _edge_executes(site, callee):
                    continue
                sub = self.transitive_threads(callee)
                if sub is not None:
                    names, msg, rel, line = sub
                    out = ((callee.qualname,) + names, msg, rel, line)
                    break
        memo[key] = out
        return out

    # -- executor / loop affinity --------------------------------------------

    def off_loop(self) -> dict[int, tuple]:
        """id(fn node) -> (FunctionInfo, root_desc, rel, line):
        functions inferred to run OFF the event loop — executor/thread
        targets and their transitive callees."""
        if self._off_loop is not None:
            return self._off_loop
        roots: list[tuple[FunctionInfo, str, str, int]] = []
        for midx in self.by_rel.values():
            for info in _all_functions(midx):
                for target, desc, line in self._off_loop_targets(info):
                    roots.append((target, desc, info.mod.rel, line))
        out: dict[int, tuple] = {}
        stack = list(roots)
        while stack:
            info, desc, rel, line = stack.pop()
            key = id(info.node)
            if key in out:
                continue
            out[key] = (info, desc, rel, line)
            for site in info.calls:
                callee = self.resolve_call(info, site.call)
                if callee is not None and not callee.is_async:
                    stack.append((callee, desc, rel, line))
        self._off_loop = out
        return out

    def _off_loop_targets(self, info: FunctionInfo):
        """Yield (FunctionInfo, root_desc, line) for every executor /
        thread submission inside ``info``."""
        for site in info.calls:
            call = site.call
            chain = attr_chain(call.func)
            target_expr = None
            desc = None
            if chain.endswith("run_in_executor") and len(call.args) >= 2:
                target_expr = call.args[1]
                desc = "run_in_executor target"
            elif chain.split(".")[-1] == "Thread" or chain == "Thread":
                for kw in call.keywords:
                    if kw.arg == "target":
                        target_expr = kw.value
                        desc = "Thread(target=) callable"
            elif isinstance(call.func, ast.Attribute) \
                    and call.func.attr == "submit" and call.args:
                recv = attr_chain(call.func.value)
                if recv and _EXECUTORISH.search(recv):
                    target_expr = call.args[0]
                    desc = f"{recv}.submit() target"
            if target_expr is None:
                continue
            if isinstance(target_expr, ast.Lambda):
                # scan the lambda body inline: it runs off-loop; give it
                # a synthetic FunctionInfo so callees propagate
                lam = FunctionInfo(info.mod, info.cls_name, "<lambda>",
                                   target_expr,
                                   f"{info.qualname}.<lambda>")
                self._scan_lambda(lam, target_expr)
                yield lam, f"{desc} (lambda)", site.line
                continue
            resolved = self._resolve_expr(info, target_expr)
            if resolved is not None:
                yield resolved, desc, site.line

    def _scan_lambda(self, lam: FunctionInfo, node: ast.Lambda) -> None:
        def visit(n):
            if isinstance(n, ast.Call):
                found = direct_blocking_call(n)
                if found:
                    lam.blocks.append((found[0], found[1], n.lineno))
                lam.calls.append(CallSite(n, n.lineno, False, ()))
            if isinstance(n, ast.Attribute) \
                    and isinstance(n.ctx, (ast.Store, ast.Del)) \
                    and isinstance(n.value, ast.Name) \
                    and n.value.id == "self":
                lam.writes_self.append((n.attr, n.lineno))
            for child in ast.iter_child_nodes(n):
                visit(child)

        visit(node.body)


def _edge_executes(site: CallSite, callee: FunctionInfo) -> bool:
    """A call edge runs the callee's body synchronously iff the callee
    is a plain function, or a coroutine that is awaited right here
    (calling an async def without await just builds the coroutine)."""
    return (not callee.is_async) or site.awaited


def _lock_name(item: ast.withitem) -> Optional[str]:
    expr = item.context_expr
    chain = attr_chain(expr)
    if not chain and isinstance(expr, ast.Call):
        chain = attr_chain(expr.func)
    if chain and _LOCKISH.search(chain):
        return chain
    return None


def _dotted_prefixes(chain: str):
    """'a.b.c' -> ['a.b', 'a'] (longest import-prefix match first)."""
    parts = chain.split(".")
    for i in range(len(parts) - 1, 0, -1):
        yield ".".join(parts[:i])


def _all_functions(midx: _ModuleIdx):
    for info in midx.functions.values():
        yield from _with_nested(info)
    for ci in midx.classes.values():
        for info in ci.methods.values():
            yield from _with_nested(info)


def _with_nested(info: FunctionInfo):
    yield info
    for sub in info.nested.values():
        yield from _with_nested(sub)


def format_chain(names: tuple, msg: str, rel: str, line: int) -> str:
    """'helper -> _sync -> time.sleep() (tpuraft/x.py:42)'."""
    hops = " -> ".join(names + (msg,)) if names else msg
    return f"{hops} ({rel}:{line})"
