"""Shared graftcheck infrastructure: module loading, comment/waiver
extraction, findings, and the checker registry.

Everything here is pure stdlib (``ast`` + ``tokenize``) and import-free
with respect to the analyzed code — the whole-tree lint must stay under
~10s and must not drag jax into a lint run.  The one exception is the
wire-schema *meta-test* (tests/test_analysis.py), which imports the live
registry to prove the AST extraction faithful.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field

# rule ids, in report order
RULES = (
    "guarded-by",
    "loop-confined",
    "lock-order",
    "wire-schema",
    "blocking-call",
    "future-leak",
    "transitive-blocking",
    "loop-affinity",
    "lane-coverage",
    "host-sync",
    "donated-read",
    "raw-clock",
    "waiver",
)

_ALLOW_RE = re.compile(
    r"#\s*graftcheck:\s*allow\(([a-z-]+)\)\s*(?:[—–-]+\s*(.*))?")


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str       # repo-relative
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclass
class Waiver:
    rule: str
    line: int
    reason: str


class Module:
    """One parsed source file: AST + per-line comments + waivers."""

    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.tree = ast.parse(source, filename=rel)
        # line -> comment text (tokenize is string-literal-safe, unlike
        # scanning lines for '#').  A comment annotates the statement it
        # TRAILS, or — only when it owns its whole line — the statement
        # below it; a trailing comment must never leak onto the next
        # statement (``self.a = 1  # guarded-by: _lock`` followed by
        # ``self.b = 2`` does not annotate b).
        self.comments: dict[int, str] = {}
        self.standalone_comments: set[int] = set()
        src_lines = source.splitlines()
        try:
            for tok in tokenize.generate_tokens(io.StringIO(source).readline):
                if tok.type == tokenize.COMMENT:
                    line = tok.start[0]
                    self.comments[line] = tok.string
                    if not src_lines[line - 1][:tok.start[1]].strip():
                        self.standalone_comments.add(line)
        except tokenize.TokenError:
            pass  # ast.parse succeeded; a tokenize edge case loses comments only
        self.waivers: list[Waiver] = []
        for line, text in self.comments.items():
            m = _ALLOW_RE.search(text)
            if m:
                self.waivers.append(
                    Waiver(m.group(1), line, (m.group(2) or "").strip()))
        # def-line waivers cover the whole function body for that rule
        self._fn_waivers: list[tuple[int, int, str]] = []  # (lo, hi, rule)
        by_line = {w.line: w for w in self.waivers}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                w = by_line.get(node.lineno)
                if w is None and node.lineno - 1 in self.standalone_comments:
                    w = by_line.get(node.lineno - 1)
                if w is not None:
                    self._fn_waivers.append(
                        (node.lineno, node.end_lineno or node.lineno, w.rule))

    def comment_at_or_above(self, line: int) -> str:
        """Trailing comment on ``line``, else a STANDALONE comment on the
        line above (the two sanctioned annotation placements)."""
        c = self.comments.get(line)
        if c:
            return c
        if line - 1 in self.standalone_comments:
            return self.comments[line - 1]
        return ""

    def comment_block_above(self, line: int) -> str:
        """The whole CONTIGUOUS standalone-comment block ending just
        above ``line``, joined top-down — class-level annotations are
        routinely written as multi-line comments whose marker sits on
        the FIRST line (``# graftcheck: loop-confined — because...``
        wrapped over two lines), which ``comment_at_or_above``'s
        single-line lookback silently missed: every multi-line
        loop-confined annotation in the tree was dead on arrival."""
        trailing = self.comments.get(line)
        lines: list[str] = [trailing] if trailing else []
        cur = line - 1
        while cur in self.standalone_comments:
            lines.append(self.comments[cur])
            cur -= 1
        return "\n".join(reversed(lines))

    def waived(self, rule: str, line: int) -> bool:
        for w in self.waivers:
            if w.rule == rule and (
                    w.line == line
                    or (w.line == line - 1
                        and w.line in self.standalone_comments)):
                return True
        return any(lo <= line <= hi and r == rule
                   for lo, hi, r in self._fn_waivers)

    def check_waiver_reasons(self) -> list[Finding]:
        """A waiver with no written justification is itself a finding —
        the escape hatch must leave a review trail (no silent
        suppression)."""
        out = []
        for w in self.waivers:
            if not w.reason:
                out.append(Finding(
                    "waiver", self.rel, w.line,
                    f"allow({w.rule}) carries no justification — write "
                    f"'# graftcheck: allow({w.rule}) — <reason>'"))
            if w.rule not in RULES:
                out.append(Finding(
                    "waiver", self.rel, w.line,
                    f"allow({w.rule}) names an unknown rule "
                    f"(known: {', '.join(r for r in RULES if r != 'waiver')})"))
        return out


def repo_root() -> str:
    """The directory containing the ``tpuraft`` package."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


_SKIP_DIRS = {"__pycache__", ".git", "node_modules", ".venv"}


def iter_py_files(roots: list[str]) -> list[str]:
    out = []
    for root in roots:
        if os.path.isfile(root):
            out.append(root)
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
            for name in sorted(filenames):
                if name.endswith(".py"):
                    out.append(os.path.join(dirpath, name))
    return out


def load_modules(roots: list[str]) -> tuple[list[Module], list[Finding]]:
    mods, findings = [], []
    base = repo_root()
    for path in iter_py_files(roots):
        rel = os.path.relpath(path, base)
        try:
            with open(path, "r", encoding="utf-8") as f:
                src = f.read()
            mods.append(Module(path, rel, src))
        except SyntaxError as e:
            findings.append(Finding(
                "waiver", rel, e.lineno or 0, f"unparsable: {e.msg}"))
        except (OSError, UnicodeDecodeError, ValueError) as e:
            # unreadable/non-UTF-8 source must surface as a finding, not
            # crash the gate with a raw traceback
            findings.append(Finding(
                "waiver", rel, 0, f"unreadable: {e!r}"))
    return mods, findings


def run_checkers(mods: list[Module], record: bool = False,
                 rules: set[str] | None = None) -> list[Finding]:
    """Run every checker over the loaded modules.  ``record`` rewrites
    the committed lockfiles (wire_schema.lock.json, lock_order.json)
    from the live tree before verifying."""
    from tpuraft.analysis import (blocking_calls, callgraph, concurrency,
                                  future_leaks, guarded_by, lanes,
                                  lock_order, raw_clock, wire_schema)

    def want(*ids: str) -> bool:
        """Skip checkers whose rules are all filtered out — a targeted
        `--rule guarded-by` run must not pay the whole-program index
        (still filtered post-hoc below, since concurrency also emits
        guarded-by findings)."""
        return rules is None or bool(rules & set(ids))

    findings: list[Finding] = []
    for m in mods:
        findings.extend(m.check_waiver_reasons())
    if want("guarded-by", "loop-confined"):
        findings.extend(guarded_by.check(mods))
    if record or want("lock-order"):
        findings.extend(lock_order.check(mods, record=record))
    if record or want("wire-schema"):
        findings.extend(wire_schema.check(mods, record=record))
    if want("blocking-call"):
        findings.extend(blocking_calls.check(mods))
    if want("future-leak"):
        findings.extend(future_leaks.check(mods))
    if want("raw-clock"):
        findings.extend(raw_clock.check(mods))
    run_concurrency = want("transitive-blocking", "loop-affinity",
                           "guarded-by")
    run_lanes = want("lane-coverage", "host-sync", "donated-read")
    if run_concurrency or run_lanes:
        # the whole-program index (call graph + summaries) is built
        # ONCE per run and shared by every interprocedural rule — the
        # lint budget pays one extra AST walk per module, not one per
        # checker
        index = callgraph.ProjectIndex(mods)
        if run_concurrency:
            findings.extend(concurrency.check(mods, index))
        if run_lanes:
            findings.extend(lanes.check(mods, index))
    if rules is not None:
        findings = [f for f in findings if f.rule in rules]
    # drop waived findings last: waivers apply uniformly to every rule
    # EXCEPT the waiver rule itself — 'allow(waiver)' must not be able
    # to silence the reasonless-waiver finding, or the no-silent-
    # suppression guarantee is one comment away from defeat
    findings = [f for f in findings
                if f.rule == "waiver" or not _waived(mods, f)]
    order = {r: i for i, r in enumerate(RULES)}
    findings.sort(key=lambda f: (f.path, f.line, order.get(f.rule, 99)))
    return findings


def _waived(mods: list[Module], f: Finding) -> bool:
    for m in mods:
        if m.rel == f.path:
            return m.waived(f.rule, f.line)
    return False


# ---- small AST helpers shared by checkers -----------------------------------


def decl_lineno(node) -> int:
    """The line a class/function ANNOTATION comment sits above: the
    first decorator's line when decorators exist, else the def/class
    line itself — ``comment_block_above(node.lineno)`` on a decorated
    class stops at the decorator and silently kills the annotation."""
    if getattr(node, "decorator_list", None):
        return node.decorator_list[0].lineno
    return node.lineno


def attr_chain(node: ast.AST) -> str:
    """Dotted name for Name/Attribute chains ('self._lock', 'a.b.c');
    '' when the expression is not a plain chain."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def parent_map(tree: ast.AST) -> dict[ast.AST, ast.AST]:
    parents: dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


@dataclass
class ClassInfo:
    module: Module
    node: ast.ClassDef
    methods: dict[str, ast.FunctionDef | ast.AsyncFunctionDef] = field(
        default_factory=dict)


def iter_classes(mod: Module):
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ClassDef):
            info = ClassInfo(mod, node)
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    info.methods[item.name] = item
            yield info
