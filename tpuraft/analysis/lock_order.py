"""Checker 2: lock-order cycle detection.

Derives the static lock-acquisition graph: an edge A -> B means some
code path acquires B while (lexically) holding A — from nested ``with``
blocks, plus ONE level of intra-module call resolution (while holding A,
``self.m(...)`` / ``m(...)`` resolves to a same-module function whose
body acquires B at its top level).  Deadlock needs a cycle; the graph
must therefore stay acyclic, and every edge must be pre-sanctioned in
the committed partial order (``lock_order.json``) so a NEW nesting gets
human review before it can ship:

    python -m tpuraft.analysis --record   # after review

Lock identification is lexical: a ``with`` item whose expression chain
contains ``lock``, ``guard`` or ``mutex`` (case-insensitive) is an
acquisition.  Names are canonicalized module-locally:

    self._lock inside class C of storage/multilog.py
        -> storage/multilog.C._lock
    module-global _paths_guard -> storage/meta_storage._paths_guard
    _path_lock(path)           -> storage/meta_storage._path_lock()

All instances of a class share one node — the per-object distinction
("different BallotBox instances") is deliberately collapsed: two
instances of the same class CAN deadlock against each other through the
same code path, and the conservative collapse is what makes that
visible.  Self-edges are skipped: re-entry is either an RLock (legal) or
a self-deadlock the guarded-by discipline already prevents via its
``holds`` call-site rule.
"""

from __future__ import annotations

import ast
import json
import os
import re

from tpuraft.analysis.core import Finding, Module, attr_chain, repo_root

RULE = "lock-order"
LOCK_FILE = "lock_order.json"

_LOCKISH = re.compile(r"lock|guard|mutex", re.IGNORECASE)


def lock_file_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), LOCK_FILE)


def _module_tag(mod: Module) -> str:
    rel = mod.rel
    if rel.startswith("tpuraft" + os.sep):
        rel = rel[len("tpuraft" + os.sep):]
    return rel[:-3] if rel.endswith(".py") else rel


def _lock_id(mod: Module, cls_name: str | None, expr: ast.AST) -> str | None:
    """Canonical node name for a with-item, or None if not lock-ish."""
    tag = _module_tag(mod)
    if isinstance(expr, ast.Call):
        chain = attr_chain(expr.func)
        if chain and _LOCKISH.search(chain):
            return f"{tag}.{chain}()"
        return None
    chain = attr_chain(expr)
    if not chain or not _LOCKISH.search(chain):
        return None
    if chain.startswith("self.") and cls_name:
        return f"{tag}.{cls_name}.{chain[len('self.'):]}"
    return f"{tag}.{chain}"


class _ModuleGraph:
    """Acquisition facts for one module."""

    def __init__(self, mod: Module):
        self.mod = mod
        # function key -> locks acquired anywhere in its body (for one
        # level of call resolution), and edges observed lexically.
        # Key: ("C", "m") for methods, (None, "f") for module functions.
        self.acquires: dict[tuple, set[str]] = {}
        self.calls_under: list[tuple[str, tuple, int]] = []  # (held, callee_key, line)
        self.edges: dict[tuple[str, str], int] = {}  # (a, b) -> first line
        # method name -> class names defining it; class name -> True
        self.method_owners: dict[str, list[str]] = {}
        self.class_methods_by_class: dict[str, bool] = {}
        for node in mod.tree.body:
            if isinstance(node, ast.ClassDef):
                self.class_methods_by_class[node.name] = True
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        self.method_owners.setdefault(
                            item.name, []).append(node.name)
        self._scan()

    def _scan(self) -> None:
        def scan_fn(fn, cls_name: str | None) -> None:
            key = (cls_name, fn.name)
            acquired: set[str] = set()

            def visit(node, held: tuple[str, ...]) -> None:
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    new = []
                    for item in node.items:
                        lid = _lock_id(self.mod, cls_name, item.context_expr)
                        if lid:
                            for h in held + tuple(new):
                                if h != lid:
                                    self.edges.setdefault(
                                        (h, lid), node.lineno)
                            new.append(lid)
                            acquired.add(lid)
                    inner = held + tuple(new)
                    for child in node.body:
                        visit(child, inner)
                    return
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.Lambda)):
                    return  # closures run outside this lexical lock scope
                if isinstance(node, ast.Call) and held:
                    chain = attr_chain(node.func)
                    callee = None
                    if chain.startswith("self.") and "." not in chain[5:]:
                        callee = (cls_name, chain[5:])
                    elif chain and "." not in chain:
                        # module function, or ClassName() -> its __init__
                        callee = (None, chain)
                        if chain in self.class_methods_by_class:
                            callee = (chain, "__init__")
                    elif isinstance(node.func, ast.Attribute) \
                            and isinstance(node.func.value, ast.Name) \
                            and node.func.value.id != "self":
                        # obj.m(...) on a bare local: resolve iff exactly
                        # one class in this module defines m (e.g.
                        # j.close() under the registry lock ->
                        # MetaJournal.close).  Attribute receivers
                        # (self._f.close()) are NOT resolved: common
                        # method names collide with stdlib handles
                        owners = self.method_owners.get(node.func.attr, ())
                        if len(owners) == 1:
                            callee = (owners[0], node.func.attr)
                    if callee:
                        for h in held:
                            self.calls_under.append((h, callee, node.lineno))
                for child in ast.iter_child_nodes(node):
                    visit(child, held)

            for stmt in fn.body:
                visit(stmt, ())
            self.acquires[key] = acquired

        for node in self.mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scan_fn(node, None)
            elif isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        scan_fn(item, node.name)

    def resolve_calls(self) -> None:
        """One level of intra-module call resolution: held-A call sites
        inherit the callee's direct acquisitions as A -> B edges."""
        for held, callee, line in self.calls_under:
            target = self.acquires.get(callee)
            if not target:
                # method name may be unique across the module's classes
                # (self.<m> on a collaborator is out of scope by design)
                continue
            for lid in target:
                if lid != held:
                    self.edges.setdefault((held, lid), line)


def derive_graph(mods: list[Module]) -> dict[tuple[str, str], tuple[str, int]]:
    """(a, b) -> (file, line) of the first observed acquisition of b
    under a."""
    out: dict[tuple[str, str], tuple[str, int]] = {}
    for mod in mods:
        g = _ModuleGraph(mod)
        g.resolve_calls()
        for (a, b), line in g.edges.items():
            out.setdefault((a, b), (mod.rel, line))
    return out


def _find_cycle(edges: set[tuple[str, str]]) -> list[str] | None:
    adj: dict[str, list[str]] = {}
    for a, b in edges:
        adj.setdefault(a, []).append(b)
    WHITE, GREY, BLACK = 0, 1, 2
    color: dict[str, int] = {}
    stack: list[str] = []

    def dfs(n: str) -> list[str] | None:
        color[n] = GREY
        stack.append(n)
        for m in adj.get(n, ()):
            c = color.get(m, WHITE)
            if c == GREY:
                return stack[stack.index(m):] + [m]
            if c == WHITE:
                cyc = dfs(m)
                if cyc:
                    return cyc
        stack.pop()
        color[n] = BLACK
        return None

    for n in sorted(adj):
        if color.get(n, WHITE) == WHITE:
            cyc = dfs(n)
            if cyc:
                return cyc
    return None


def load_sanctioned(path: str | None = None) -> set[tuple[str, str]]:
    path = path or lock_file_path()
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
    except FileNotFoundError:
        return set()
    return {(e[0], e[1]) for e in data.get("edges", [])}


def record(mods: list[Module], path: str | None = None) -> None:
    graph = derive_graph(mods)
    payload = {
        "_comment": (
            "Sanctioned lock acquisition order (graftcheck lock-order). "
            "An edge [A, B] permits acquiring B while holding A. "
            "Regenerate with `python -m tpuraft.analysis --record` after "
            "reviewing any new nesting."),
        "edges": sorted([a, b] for a, b in graph),
    }
    with open(path or lock_file_path(), "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2)
        f.write("\n")


_record_fn = record


def check(mods: list[Module], record: bool = False,
          path: str | None = None) -> list[Finding]:
    if record:
        _record_fn(mods, path)
    graph = derive_graph(mods)
    sanctioned = load_sanctioned(path)
    out: list[Finding] = []

    cycle = _find_cycle(set(graph))
    if cycle:
        a, b = cycle[0], cycle[1]
        rel, line = graph.get((a, b), ("?", 0))
        out.append(Finding(
            RULE, rel, line,
            "lock-order cycle: " + " -> ".join(cycle)
            + " — a concurrent pair of these paths deadlocks"))

    for (a, b), (rel, line) in sorted(graph.items()):
        if (a, b) not in sanctioned:
            out.append(Finding(
                RULE, rel, line,
                f"unsanctioned lock nesting {a} -> {b}: review the "
                f"ordering against tpuraft/analysis/{LOCK_FILE} and run "
                f"`python -m tpuraft.analysis --record`"))
    return out
