"""TCP transport: the real-network protocol plane (host<->host over DCN).

Reference parity: SOFABolt's Netty TCP server/client with custom framing
and connection pooling (SURVEY.md §3.1 "RPC layer", §6 "Distributed
communication backend").  One server port multiplexes every raft group,
CLI processor and KV service in the process (NodeManager registers its
handlers on :class:`TcpRpcServer` exactly as it does on the in-proc
``RpcServer``); clients keep one pooled connection per destination with
pipelined request/response correlation by sequence number.

Frame format (little-endian):
    u32 payload_len | u64 seq | u8 flags | payload
    flags bit0: response, bit1: error (payload is ErrorResponse)
    request payload:  u16 method_len | method utf8 | encode_message(msg)
    response payload: encode_message(msg)

The consensus *math* plane rides ICI via XLA collectives
(tpuraft.parallel); this module is only the protocol envelope.
"""

from __future__ import annotations

import asyncio
import logging
import struct
from typing import Any, Optional

from tpuraft.errors import RaftError, Status
from tpuraft.rpc.messages import ErrorResponse, decode_message, encode_message
from tpuraft.rpc.transport import RpcError, RpcServer, TransportBase

LOG = logging.getLogger(__name__)

_HDR = struct.Struct("<IQB")
_F_RESPONSE = 1
_F_ERROR = 2
MAX_FRAME = 256 * 1024 * 1024  # sanity bound (snapshot chunks are ~MBs)


def _split_endpoint(endpoint: str) -> tuple[str, int]:
    host, port = endpoint.rsplit(":", 1)
    return host, int(port)


async def _read_frame(reader: asyncio.StreamReader) -> tuple[int, int, bytes]:
    hdr = await reader.readexactly(_HDR.size)
    length, seq, flags = _HDR.unpack(hdr)
    if length > MAX_FRAME:
        raise ConnectionError(f"oversized frame: {length}")
    payload = await reader.readexactly(length) if length else b""
    return seq, flags, payload


def _frame(seq: int, flags: int, payload: bytes) -> bytes:
    return _HDR.pack(len(payload), seq, flags) + payload


class TcpRpcServer(RpcServer):
    """One TCP listener per process endpoint; shares the handler registry
    (and therefore NodeManager/CLI/KV processor wiring) with RpcServer."""

    def __init__(self, endpoint: str, bind_host: Optional[str] = None):
        super().__init__(endpoint)
        self._bind_host = bind_host
        self._server: Optional[asyncio.AbstractServer] = None
        self._conn_tasks: set[asyncio.Task] = set()

    @property
    def bound_port(self) -> int:
        """Actual listening port (useful when binding port 0 in tests)."""
        assert self._server is not None
        return self._server.sockets[0].getsockname()[1]

    async def start(self) -> None:
        host, port = _split_endpoint(self.endpoint)
        self._server = await asyncio.start_server(
            self._on_connection, self._bind_host or host, port)
        self.running = True

    async def stop(self) -> None:
        self.running = False
        if self._server is not None:
            self._server.close()
        # cancel live connection handlers BEFORE wait_closed(): since 3.12
        # wait_closed() waits for handlers, which block reading from
        # still-connected clients
        for t in list(self._conn_tasks):
            t.cancel()
        for t in list(self._conn_tasks):
            try:
                await t
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        self._conn_tasks.clear()
        if self._server is not None:
            await self._server.wait_closed()
            self._server = None

    async def _on_connection(self, reader: asyncio.StreamReader,
                             writer: asyncio.StreamWriter) -> None:
        task = asyncio.current_task()
        if task is not None:
            self._conn_tasks.add(task)
        write_lock = asyncio.Lock()
        pending: set[asyncio.Task] = set()
        try:
            while True:
                seq, _flags, payload = await _read_frame(reader)
                # concurrent dispatch: a slow handler (snapshot chunk,
                # big append) must not head-of-line-block heartbeats;
                # the raft protocol itself is safe under reordering
                # (term + prev_log checks; pipelined replicator resolves
                # out-of-order responses)
                t = asyncio.ensure_future(
                    self._serve_one(seq, payload, writer, write_lock))
                pending.add(t)
                t.add_done_callback(pending.discard)
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        except asyncio.CancelledError:
            raise
        finally:
            for t in pending:
                t.cancel()
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass
            if task is not None:
                self._conn_tasks.discard(task)

    async def _serve_one(self, seq: int, payload: bytes,
                         writer: asyncio.StreamWriter,
                         write_lock: asyncio.Lock) -> None:
        flags, blob = await self.serve_framed_payload(
            seq, payload, _F_RESPONSE, _F_ERROR)
        async with write_lock:
            try:
                writer.write(_frame(seq, flags, blob))
                await writer.drain()
            except (ConnectionError, OSError):
                pass  # peer went away; it will retry


class _Connection:
    """One pooled, pipelined client connection."""

    def __init__(self, reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter):
        self.reader = reader
        self.writer = writer
        self.pending: dict[int, asyncio.Future] = {}
        self.write_lock = asyncio.Lock()
        self.reader_task = asyncio.ensure_future(self._read_loop())
        self.closed = False

    async def _read_loop(self) -> None:
        try:
            while True:
                seq, flags, payload = await _read_frame(self.reader)
                fut = self.pending.pop(seq, None)
                if fut is None or fut.done():
                    continue
                if flags & _F_ERROR:
                    err = decode_message(payload)
                    fut.set_exception(
                        RpcError(Status(err.code, err.msg)))
                else:
                    fut.set_result(decode_message(payload))
        except asyncio.CancelledError:
            self._fail_all(ConnectionError("connection closed"))
            raise
        except Exception as e:  # noqa: BLE001 — incl. decode errors: a
            # frame that fails decode_message means protocol desync; the
            # stream position is unrecoverable, so fail+close like a
            # connection error (otherwise the pool would keep handing out
            # a wedged connection whose reader task is dead)
            self._fail_all(e)

    def _fail_all(self, exc: Exception) -> None:
        self.closed = True
        # close the socket here too: the pool overwrites failed
        # connections without awaiting close(), and StreamReaderProtocol
        # keeps the transport registered on EOF (CLOSE_WAIT leak otherwise)
        self.writer.close()
        status = Status.error(RaftError.EHOSTDOWN, f"connection lost: {exc}")
        for fut in self.pending.values():
            if not fut.done():
                fut.set_exception(RpcError(status))
        self.pending.clear()

    async def close(self) -> None:
        self.closed = True
        self.reader_task.cancel()
        try:
            await self.reader_task
        except (asyncio.CancelledError, Exception):  # noqa: BLE001
            pass
        self.writer.close()
        try:
            await self.writer.wait_closed()
        except (ConnectionError, OSError):
            pass


class TcpTransport(TransportBase):
    """Client side: one auto-reconnecting pipelined connection per dst."""

    def __init__(self, endpoint: str = "client:0",
                 default_timeout_ms: float = 1000.0,
                 connect_timeout_ms: float = 1000.0):
        self.endpoint = endpoint
        self._timeout_ms = default_timeout_ms
        self._connect_timeout_ms = connect_timeout_ms
        self._conns: dict[str, _Connection] = {}
        self._conn_locks: dict[str, asyncio.Lock] = {}
        self._seq = 0

    async def _get_connection(self, dst: str) -> _Connection:
        conn = self._conns.get(dst)
        if conn is not None and not conn.closed:
            return conn
        lock = self._conn_locks.setdefault(dst, asyncio.Lock())
        async with lock:
            conn = self._conns.get(dst)
            if conn is not None and not conn.closed:
                return conn
            host, port = _split_endpoint(dst)
            try:
                reader, writer = await asyncio.wait_for(
                    asyncio.open_connection(host, port),
                    self._connect_timeout_ms / 1000.0)
            except (OSError, asyncio.TimeoutError) as e:
                raise RpcError(Status.error(
                    RaftError.EHOSTDOWN, f"connect {dst}: {e}")) from e
            conn = _Connection(reader, writer)
            self._conns[dst] = conn
            return conn

    async def call(self, dst: str, method: str, request: Any,
                   timeout_ms: Optional[float] = None) -> Any:
        timeout = (timeout_ms if timeout_ms is not None
                   else self._timeout_ms) / 1000.0
        conn = await self._get_connection(dst)
        m = method.encode()
        # encode BEFORE registering the future: a codec failure must raise
        # cleanly, not orphan a pending entry
        payload = struct.pack("<H", len(m)) + m + encode_message(request)
        self._seq += 1
        seq = self._seq
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        conn.pending[seq] = fut
        try:
            async with conn.write_lock:
                conn.writer.write(_frame(seq, 0, payload))
                await conn.writer.drain()
        except (ConnectionError, OSError) as e:
            conn.pending.pop(seq, None)
            await conn.close()
            # only evict OUR connection: a concurrent caller may already
            # have replaced it with a fresh healthy one
            if self._conns.get(dst) is conn:
                self._conns.pop(dst, None)
            raise RpcError(Status.error(
                RaftError.EHOSTDOWN, f"send to {dst}: {e}")) from e
        try:
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            conn.pending.pop(seq, None)
            raise RpcError(Status.error(
                RaftError.ETIMEDOUT, f"{method} to {dst}"))

    async def close(self) -> None:
        for conn in list(self._conns.values()):
            await conn.close()
        self._conns.clear()
