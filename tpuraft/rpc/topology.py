"""NetworkTopology: a per-link WAN shape for chaos fabrics.

The soak's fault plane used to be GLOBAL knobs (one delay, one drop
rate for the whole fabric) — fine for same-host chaos, useless for the
geo regime CD-Raft targets: cross-domain sites with *asymmetric* WAN
latencies, partial partitions, and links that flap rather than fail.
This module models that surface once, and both fabrics consult it:

- endpoints are tagged with a **zone** (``set_zone``);
- a zones x zones matrix of :class:`LinkProfile` rows gives each
  DIRECTED zone pair its base latency, jitter, loss rate, and a
  bandwidth cap (token-bucket serialization delay), so ``z0 -> z1``
  and ``z1 -> z0`` can differ (asymmetric routes);
- dynamic events — :meth:`degrade` (WAN brown-out), :meth:`partition`
  (one-way zone partition), :meth:`flap` (periodic up/down square
  wave) — OVERLAY the base matrix and are cleared by
  :meth:`heal_events` without touching the base shape, so nemesis-layer
  noise (drop/delay knobs, per-endpoint blocks) and topology shaping
  compose without stomping each other.

Everything random is drawn from one seeded ``random.Random`` so a
seeded chaos drive replays byte-identically; per-outcome counters are
surfaced through :meth:`describe` (util/describer registration is the
caller's choice — the soak registers its topology).
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field, replace
from random import Random
from typing import Optional


@dataclass(frozen=True)
class LinkProfile:
    """One DIRECTED zone->zone link's shape."""

    latency_ms: float = 0.0     # base one-way transit latency
    jitter_ms: float = 0.0      # uniform extra in [0, jitter_ms)
    loss: float = 0.0           # per-frame drop probability
    bandwidth_kbps: float = 0.0  # 0 = uncapped; else serialization delay

    def degraded(self, latency_x: float = 1.0, extra_loss: float = 0.0,
                 bandwidth_x: float = 1.0) -> "LinkProfile":
        """A browned-out variant of this link (used by degrade events)."""
        return replace(
            self,
            latency_ms=self.latency_ms * latency_x,
            jitter_ms=self.jitter_ms * latency_x,
            loss=min(1.0, self.loss + extra_loss),
            bandwidth_kbps=(self.bandwidth_kbps * bandwidth_x
                            if self.bandwidth_kbps else 0.0))


@dataclass
class _Flap:
    period_s: float
    duty: float       # fraction of the period the link is UP
    phase: float      # seeded start offset so flaps don't align


# graftcheck: loop-confined — consulted only from transport call paths
# on the owning event loop; plan() mutates the token buckets there
class NetworkTopology:
    """Zones x zones link-shape matrix + dynamic fault events.

    ``plan(src, dst, nbytes)`` is the single consultation point: it
    returns ``(delay_s, dropped)`` for one frame, folding base shape,
    degrade overlays, one-way zone partitions, flap state, and the
    per-link bandwidth token bucket.  The TRANSPORT sleeps/drops; the
    topology only decides.
    """

    def __init__(self, seed: int = 0, clock=time.monotonic):
        self._zones: dict[str, str] = {}          # endpoint -> zone
        self._links: dict[tuple[str, str], LinkProfile] = {}
        self._default = LinkProfile()
        self._rng = Random(seed)
        self._clock = clock
        # dynamic overlays (cleared by heal_events, NOT by fabric heal())
        self._degraded: dict[tuple[str, str], LinkProfile] = {}
        self._partitioned: set[tuple[str, str]] = set()   # one-way
        self._flaps: dict[tuple[str, str], _Flap] = {}
        # per-ENDPOINT degrade (gray failures): one store's links limp —
        # extra latency/jitter/loss ADDED to every frame touching the
        # endpoint, both directions — while its zone stays healthy.
        # endpoint -> (latency_ms, jitter_ms, loss)
        self._ep_degraded: dict[str, tuple[float, float, float]] = {}
        # per-link bandwidth token bucket: link -> busy-until timestamp
        self._busy_until: dict[tuple[str, str], float] = {}
        self.counters: dict[str, int] = {
            "frames": 0, "delayed": 0, "dropped_loss": 0,
            "dropped_partition": 0, "dropped_flap": 0, "shaped_bytes": 0,
        }

    # -- static shape --------------------------------------------------------

    def set_zone(self, endpoint: str, zone: str) -> None:
        self._zones[endpoint] = zone

    def zone_of(self, endpoint: str) -> str:
        return self._zones.get(endpoint, "")

    def zones(self) -> list[str]:
        return sorted(set(self._zones.values()))

    def set_default_link(self, profile: LinkProfile) -> None:
        self._default = profile

    def set_link(self, src_zone: str, dst_zone: str, profile: LinkProfile,
                 symmetric: bool = False) -> None:
        """Shape the DIRECTED src->dst zone link; ``symmetric=True``
        also sets the reverse direction (asymmetric WANs set each
        direction separately)."""
        self._links[(src_zone, dst_zone)] = profile
        if symmetric:
            self._links[(dst_zone, src_zone)] = profile

    def link(self, src_zone: str, dst_zone: str) -> LinkProfile:
        """Effective profile (degrade overlay wins over base)."""
        key = (src_zone, dst_zone)
        over = self._degraded.get(key)
        if over is not None:
            return over
        return self._links.get(key, self._default)

    # -- dynamic events (the nemesis menu's verbs) ---------------------------

    def degrade(self, src_zone: str, dst_zone: str,
                latency_x: float = 10.0, extra_loss: float = 0.02,
                bandwidth_x: float = 0.25, symmetric: bool = True) -> None:
        """WAN brown-out: overlay a degraded variant of the base link."""
        base = self._links.get((src_zone, dst_zone), self._default)
        self._degraded[(src_zone, dst_zone)] = base.degraded(
            latency_x, extra_loss, bandwidth_x)
        if symmetric:
            rbase = self._links.get((dst_zone, src_zone), self._default)
            self._degraded[(dst_zone, src_zone)] = rbase.degraded(
                latency_x, extra_loss, bandwidth_x)

    def degrade_wan(self, latency_x: float = 10.0, extra_loss: float = 0.02,
                    bandwidth_x: float = 0.25) -> None:
        """Brown out every INTER-zone link at once (intra-zone spared)."""
        for a in self.zones():
            for b in self.zones():
                if a != b:
                    self.degrade(a, b, latency_x, extra_loss, bandwidth_x,
                                 symmetric=False)

    def partition(self, src_zone: str, dst_zone: str) -> None:
        """One-way zone partition: frames src->dst drop; dst->src flows."""
        self._partitioned.add((src_zone, dst_zone))

    def partition_zone(self, zone: str, one_way: bool = False) -> None:
        """Cut a zone off from every other zone (one_way=True drops only
        the zone's OUTBOUND frames — the classic asymmetric partition)."""
        for other in self.zones():
            if other == zone:
                continue
            self.partition(zone, other)
            if not one_way:
                self.partition(other, zone)

    def flap(self, src_zone: str, dst_zone: str, period_s: float = 1.0,
             duty: float = 0.5, symmetric: bool = True) -> None:
        """Flapping link: up for ``duty`` of each period, down otherwise,
        phase-shifted by the seeded rng so concurrent flaps interleave."""
        f = _Flap(period_s, duty, self._rng.random() * period_s)
        self._flaps[(src_zone, dst_zone)] = f
        if symmetric:
            self._flaps[(dst_zone, src_zone)] = f

    def degrade_endpoint(self, endpoint: str, latency_ms: float = 25.0,
                         jitter_ms: float = 10.0, loss: float = 0.0) -> None:
        """Gray-failure verb: ONE endpoint's links limp (both
        directions) while its zone — and every zone link — stays
        healthy.  The classic fail-slow network shape: a saturated NIC/
        CPU on one store adds latency to everything it touches, and no
        zone-level check sees it."""
        self._ep_degraded[endpoint] = (latency_ms, jitter_ms, loss)

    def stall_endpoint(self, endpoint: str, stall_ms: float = 1500.0,
                       loss: float = 0.0) -> None:
        """Stalled (NOT dead) endpoint: frames to/from it are delivered
        after ``stall_ms`` — long past any heartbeat cadence, short of
        forever.  Distinct from a partition: acks eventually arrive, so
        naive liveness checks keep passing while latency detonates."""
        self.degrade_endpoint(endpoint, latency_ms=stall_ms, jitter_ms=0.0,
                              loss=loss)

    def heal_endpoint(self, endpoint: str) -> None:
        self._ep_degraded.pop(endpoint, None)

    def endpoint_degraded(self, endpoint: str) -> bool:
        return endpoint in self._ep_degraded

    def heal_events(self) -> None:
        """Clear every DYNAMIC event (degrades, partitions, flaps,
        endpoint limps); the base zone matrix — the deployment's real
        shape — stays."""
        self._degraded.clear()
        self._partitioned.clear()
        self._flaps.clear()
        self._ep_degraded.clear()

    # -- the consultation point ----------------------------------------------

    def plan(self, src_ep: str, dst_ep: str, nbytes: int = 256
             ) -> tuple[float, bool]:
        """Decide one frame's fate: returns ``(delay_s, dropped)``.

        Mutates only the bandwidth token bucket; all randomness comes
        from the seeded rng, so identical call sequences replay."""
        sz, dz = self.zone_of(src_ep), self.zone_of(dst_ep)
        key = (sz, dz)
        self.counters["frames"] += 1
        if key in self._partitioned:
            self.counters["dropped_partition"] += 1
            return 0.0, True
        flap_state = self._flaps.get(key)
        if flap_state is not None:
            t = (self._clock() + flap_state.phase) % flap_state.period_s
            if t >= flap_state.duty * flap_state.period_s:
                self.counters["dropped_flap"] += 1
                return 0.0, True
        prof = self.link(sz, dz)
        # per-endpoint limp: additive on top of whatever the zone link
        # says, applied once per degraded endpoint the frame touches
        ep_lat = ep_jit = ep_loss = 0.0
        for ep in (src_ep, dst_ep):
            shape = self._ep_degraded.get(ep)
            if shape is not None:
                ep_lat += shape[0]
                ep_jit += shape[1]
                ep_loss = max(ep_loss, shape[2])
        loss = max(prof.loss, ep_loss) if ep_loss else prof.loss
        if loss > 0 and self._rng.random() < loss:
            self.counters["dropped_loss"] += 1
            return 0.0, True
        delay = (prof.latency_ms + ep_lat) / 1000.0
        if prof.jitter_ms > 0:
            delay += self._rng.random() * prof.jitter_ms / 1000.0
        if ep_jit > 0:
            delay += self._rng.random() * ep_jit / 1000.0
        if ep_lat > 0:
            self.counters["ep_shaped"] = self.counters.get("ep_shaped",
                                                           0) + 1
        if prof.bandwidth_kbps > 0:
            # token-bucket serialization: consecutive frames queue behind
            # the link's busy horizon, so a burst sees growing delays
            now = self._clock()
            ser = nbytes * 8.0 / (prof.bandwidth_kbps * 1000.0)
            start = max(now, self._busy_until.get(key, 0.0))
            self._busy_until[key] = start + ser
            delay += (start - now) + ser
            self.counters["shaped_bytes"] += nbytes
        if delay > 0:
            self.counters["delayed"] += 1
        return delay, False

    async def traverse(self, src_ep: str, dst_ep: str, request,
                       timeout_ms: Optional[float]) -> None:
        """The ONE transit implementation both fabrics share
        (InProcNetwork.call and FaultInjectingTransport.call): sleep
        the planned delay, and on a drop wait the loopback's standard
        lost-request interval then raise — so both fabrics keep
        byte-identical WAN semantics instead of drifting copies."""
        from tpuraft.errors import RaftError, Status
        from tpuraft.rpc.transport import RpcError

        delay_s, dropped = self.plan(src_ep, dst_ep,
                                     approx_frame_bytes(request))
        if delay_s > 0:
            await asyncio.sleep(delay_s)
        if dropped:
            # match the loopback's drop behavior: a lost request is only
            # detected after a wait, so callers' timeout/backoff engages
            wait_ms = min(timeout_ms, 50.0) if timeout_ms else 50.0
            await asyncio.sleep(wait_ms / 1000.0)
            raise RpcError(Status.error(
                RaftError.EHOSTDOWN,
                f"topology drop {src_ep} -> {dst_ep}"))

    # -- observability -------------------------------------------------------

    def describe(self) -> str:
        lines = [f"NetworkTopology<{len(self._zones)} endpoints, "
                 f"{len(self.zones())} zones>:"]
        for z in self.zones():
            eps = sorted(e for e, zz in self._zones.items() if zz == z)
            lines.append(f"  zone {z}: {', '.join(eps)}")
        for (a, b), p in sorted(self._links.items()):
            lines.append(
                f"  link {a}->{b}: {p.latency_ms}ms ±{p.jitter_ms}ms "
                f"loss={p.loss} bw={p.bandwidth_kbps or 'inf'}kbps")
        if self._degraded:
            lines.append(f"  degraded: {sorted(self._degraded)}")
        if self._partitioned:
            lines.append(f"  partitioned (one-way): "
                         f"{sorted(self._partitioned)}")
        if self._flaps:
            lines.append(f"  flapping: {sorted(self._flaps)}")
        if self._ep_degraded:
            lines.append(f"  endpoint-degraded: {sorted(self._ep_degraded)}")
        lines.append(f"  counters: {self.counters}")
        return "\n".join(lines)

    def __str__(self) -> str:
        return f"NetworkTopology<{len(self.zones())} zones>"


def build_geo_topology(endpoints: list[str], zones: int, seed: int = 0,
                       intra_ms: float = 0.2, base_wan_ms: float = 3.0,
                       jitter_ms: float = 1.0, loss: float = 0.001,
                       clock=time.monotonic) -> NetworkTopology:
    """The canonical geo shape the soak and bench share: endpoints
    round-robin into ``zones`` zones, near-zero intra-zone links, and
    ASYMMETRIC inter-zone WAN links — each direction draws its own
    base latency from the seeded rng (0.7x-1.6x of ``base_wan_ms``),
    so z0->z1 and z1->z0 genuinely differ, plus jitter and a small
    steady loss rate."""
    topo = NetworkTopology(seed=seed, clock=clock)
    names = [f"z{i}" for i in range(zones)]
    for i, ep in enumerate(endpoints):
        topo.set_zone(ep, names[i % zones])
    rng = Random(seed ^ 0x9E3779B9)
    intra = LinkProfile(latency_ms=intra_ms)
    for a in names:
        topo.set_link(a, a, intra)
    for a in names:
        for b in names:
            if a == b:
                continue
            lat = base_wan_ms * (0.7 + 0.9 * rng.random())
            topo.set_link(a, b, LinkProfile(
                latency_ms=lat, jitter_ms=jitter_ms, loss=loss))
    return topo


def approx_frame_bytes(request) -> int:
    """Cheap size estimate for bandwidth shaping: entry-bearing
    AppendEntries frames dominate WAN bytes, so count their encoded
    entries; everything else is a small control frame."""
    entries = getattr(request, "entries", None)
    if entries:
        try:
            return 128 + sum(len(e.encode()) for e in entries)
        except Exception:  # noqa: BLE001 — estimate, never fail a send
            return 1024
    items = getattr(request, "items", None) or getattr(request, "beats", None)
    if items:
        return 64 + 96 * len(items)
    return 256
