"""CLI (admin) RPC messages.

Reference parity: generated ``core:rpc/CliRequests`` protobuf — one
request/response pair per admin op (AddPeer, RemovePeer, ChangePeers,
ResetPeer, Snapshot, TransferLeader, GetLeader, GetPeers, AddLearners,
RemoveLearners) — handled server-side by the per-op processors under
``core:rpc/impl/cli/`` (SURVEY.md §3.1 "CLI service & processors").

All requests carry ``group_id`` (multi-raft routing key) and ``peer_id``
(the serving peer; empty string = "whichever node of this group lives on
the addressed endpoint").  Peers travel as ``str`` in PeerId's canonical
``ip:port[:idx[:priority]]`` form.  Type ids 64+ in the shared codec.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from tpuraft.rpc.messages import register_message


def _cli(tid: int):
    def deco(cls):
        return register_message(tid, dataclass(cls))
    return deco


@_cli(64)
class GetLeaderRequest:
    group_id: str
    peer_id: str = ""


@_cli(65)
class GetLeaderResponse:
    leader_id: str = ""
    success: bool = True


@_cli(66)
class GetPeersRequest:
    group_id: str
    peer_id: str = ""
    only_alive: bool = False


@_cli(67)
class GetPeersResponse:
    peers: list[str] = field(default_factory=list)
    learners: list[str] = field(default_factory=list)
    success: bool = True
    # trailing extension (witness replicas): voters that are witnesses
    # (subset of ``peers``); old clients ignore it
    witnesses: list[str] = field(default_factory=list)


@_cli(68)
class AddPeerRequest:
    group_id: str
    peer_id: str
    adding: str = ""
    # trailing extension: add the voter as a WITNESS (metadata-only
    # replica); old servers ignore the flag and add a full voter
    witness: bool = False


@_cli(69)
class RemovePeerRequest:
    group_id: str
    peer_id: str
    removing: str = ""


@_cli(70)
class ChangePeersRequest:
    group_id: str
    peer_id: str
    new_peers: list[str] = field(default_factory=list)      # voters
    new_learners: list[str] = field(default_factory=list)
    # trailing extension: which of new_peers are witnesses
    new_witnesses: list[str] = field(default_factory=list)


@_cli(71)
class ResetPeersRequest:
    group_id: str
    peer_id: str
    new_peers: list[str] = field(default_factory=list)      # voters
    new_learners: list[str] = field(default_factory=list)
    # trailing extension: which of new_peers are witnesses
    new_witnesses: list[str] = field(default_factory=list)


@_cli(72)
class SnapshotRequest:
    group_id: str
    peer_id: str = ""


@_cli(73)
class TransferLeaderRequest:
    group_id: str
    peer_id: str
    transferee: str = ""


@_cli(74)
class AddLearnersRequest:
    group_id: str
    peer_id: str
    learners: list[str] = field(default_factory=list)


@_cli(75)
class RemoveLearnersRequest:
    group_id: str
    peer_id: str
    learners: list[str] = field(default_factory=list)


@_cli(77)
class ResetLearnersRequest:
    group_id: str
    peer_id: str
    learners: list[str] = field(default_factory=list)


@_cli(78)
class DescribeMetricsRequest:
    """Live-metrics scrape over the wire (observability plane): the
    addressed STORE answers with its Prometheus text rendering — the
    same content its optional HTTP /metrics listener serves, reachable
    through the admin transport without signals or extra ports."""

    # reserved scope selector (""=whole store); trailing-compatible
    scope: str = ""


@_cli(79)
class DescribeMetricsResponse:
    text: str = ""
    success: bool = True


@_cli(76)
class CliResponse:
    """Uniform admin-op outcome: ok/error code/msg + new conf if changed."""

    code: int = 0
    msg: str = ""
    old_peers: list[str] = field(default_factory=list)
    new_peers: list[str] = field(default_factory=list)

    @property
    def success(self) -> bool:
        return self.code == 0
