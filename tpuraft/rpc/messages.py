"""Raft RPC messages + compact binary codec.

Reference parity: protobuf ``RpcRequests.*`` (AppendEntries, RequestVote,
InstallSnapshot, TimeoutNow, ReadIndex, GetFile) — SURVEY.md §3.1 "RPC
layer".  Dataclasses here are the in-proc representation; ``encode``/
``decode`` give a deterministic wire format shared with the native
transport (length-prefixed little-endian fields, LogEntry's own codec for
entries).
"""

from __future__ import annotations

import struct
from dataclasses import MISSING as _MISSING
from dataclasses import dataclass, field
from typing import Optional

from tpuraft.entity import LogEntry

_U16 = struct.Struct("<H")
_I64 = struct.Struct("<q")


def _pack_str(s: str) -> bytes:
    b = s.encode()
    return _U16.pack(len(b)) + b


def _unpack_str(buf: memoryview, off: int) -> tuple[str, int]:
    (n,) = _U16.unpack_from(buf, off)
    off += 2
    return bytes(buf[off : off + n]).decode(), off + n


def _pack_bytes(b: bytes) -> bytes:
    return struct.pack("<I", len(b)) + b


def _unpack_bytes(buf: memoryview, off: int) -> tuple[bytes, int]:
    (n,) = struct.unpack_from("<I", buf, off)
    off += 4
    return bytes(buf[off : off + n]), off + n


@dataclass
class SnapshotMeta:
    """Snapshot manifest meta (reference: RaftOutter.SnapshotMeta)."""

    last_included_index: int = 0
    last_included_term: int = 0
    peers: list[str] = field(default_factory=list)
    old_peers: list[str] = field(default_factory=list)
    learners: list[str] = field(default_factory=list)
    old_learners: list[str] = field(default_factory=list)
    # TRAILING extension (witness replicas): omitted when empty, so a
    # witness-free meta encodes bit-identically to the old format and
    # an old decoder ignores the trailing lists of a new one
    witnesses: list[str] = field(default_factory=list)
    old_witnesses: list[str] = field(default_factory=list)

    def encode(self) -> bytes:
        out = bytearray(_I64.pack(self.last_included_index))
        out += _I64.pack(self.last_included_term)
        lists = [self.peers, self.old_peers, self.learners,
                 self.old_learners]
        if self.witnesses or self.old_witnesses:
            lists += [self.witnesses, self.old_witnesses]
        for lst in lists:
            out += _U16.pack(len(lst))
            for s in lst:
                out += _pack_str(s)
        return bytes(out)

    @staticmethod
    def decode(buf: bytes | memoryview) -> "SnapshotMeta":
        buf = memoryview(buf)
        idx, term = _I64.unpack_from(buf, 0)[0], _I64.unpack_from(buf, 8)[0]
        off = 16
        lists = []
        for i in range(6):
            if i >= 4 and off >= len(buf):
                lists.append([])  # pre-witness meta: trailing defaults
                continue
            (n,) = _U16.unpack_from(buf, off)
            off += 2
            cur = []
            for _ in range(n):
                s, off = _unpack_str(buf, off)
                cur.append(s)
            lists.append(cur)
        return SnapshotMeta(idx, term, *lists)


# ---- message dataclasses ---------------------------------------------------
# All carry group_id (multi-raft routing key), server_id (sender), peer_id
# (target) as strings — the reference's protobuf does the same.


@dataclass
class AppendEntriesRequest:
    group_id: str
    server_id: str
    peer_id: str
    term: int
    prev_log_index: int
    prev_log_term: int
    committed_index: int
    entries: list[LogEntry] = field(default_factory=list)
    # heartbeats are empty-entry requests (reference: sendEmptyEntries)
    # TRAILING trace-plane extension (wire-compatible: old decoders
    # stop before it, old encoders leave the default): one packed i64
    # trace context per entry (util/trace.pack_ctx), b"" when no entry
    # of the batch is traced — zero wire cost on the untraced path.
    # Follower-side append/flush spans join the originating trace.
    trace_ctx: bytes = b""


@dataclass
class AppendEntriesResponse:
    term: int
    success: bool
    last_log_index: int  # hint for nextIndex backoff on rejection
    # on a prev-term conflict: the first index of the follower's
    # conflicting term, so the leader can skip the whole term run in one
    # step instead of one-entry-per-RTT linear backoff (classic Raft §5.3
    # fast-backoff optimization; 0 = no hint)
    conflict_index: int = 0
    # capability advertisement: the responder's endpoint runs a
    # NodeManager serving ``multi_heartbeat``, so the leader may
    # auto-coalesce its beats to this endpoint (VERDICT r2 #6)
    multi_hb: bool = False


@dataclass
class RequestVoteRequest:
    group_id: str
    server_id: str
    peer_id: str
    term: int
    last_log_index: int
    last_log_term: int
    pre_vote: bool


@dataclass
class RequestVoteResponse:
    term: int
    granted: bool


@dataclass
class InstallSnapshotRequest:
    group_id: str
    server_id: str
    peer_id: str
    term: int
    meta: SnapshotMeta
    uri: str  # remote://<endpoint>/<reader_id>


@dataclass
class InstallSnapshotResponse:
    term: int
    success: bool


@dataclass
class TimeoutNowRequest:
    group_id: str
    server_id: str
    peer_id: str
    term: int


@dataclass
class TimeoutNowResponse:
    term: int
    success: bool


@dataclass
class ReadIndexRequest:
    group_id: str
    server_id: str
    peer_id: str


@dataclass
class ReadIndexResponse:
    index: int
    success: bool
    # trailing read-plane extensions (wire-compatible: old decoders drop
    # them, old encoders leave the defaults).  On a rejection
    # (success=False) the responder reports its term and its current
    # leader hint so the forwarding follower can re-probe the REAL
    # leader inside the same attempt instead of failing the whole read
    # batch with a terminal error (ReadOnlyService._forward_once).
    term: int = 0
    leader_hint: str = ""


@dataclass
class GetFileRequest:
    reader_id: int
    filename: str
    offset: int
    count: int


@dataclass
class GetFileResponse:
    eof: bool
    data: bytes


@dataclass
class ErrorResponse:
    code: int
    msg: str


# ---- codec -----------------------------------------------------------------
# Extensible registry so other message families (CLI ops, KV ops) claim
# stable type-id ranges: 0-31 raft core, 64-95 CLI, 128-159 KV.

_MSG_TYPES: dict[int, type] = {}
_TYPE_ID: dict[type, int] = {}


def register_message(tid: int, cls: type) -> type:
    if tid in _MSG_TYPES and _MSG_TYPES[tid] is not cls:
        raise ValueError(f"type id {tid} already taken by {_MSG_TYPES[tid]}")
    _MSG_TYPES[tid] = cls
    _TYPE_ID[cls] = tid
    return cls


@dataclass
class MultiHeartbeatRequest:
    """Coalesced heartbeats: one RPC per (src, dst) endpoint pair carries
    the empty-AppendEntries beats of EVERY leader group between them
    (the batched send-matrix plane — SURVEY.md §3.5; no reference
    counterpart, the reference sends per-group heartbeats).  Each beat
    is an encoded AppendEntriesRequest."""

    beats: list[bytes]


@dataclass
class MultiHeartbeatResponse:
    """One frame per beat, in request order: an encoded
    AppendEntriesResponse, or an encoded ErrorResponse for a group that
    was unroutable/unserviceable on the receiver."""

    acks: list[bytes]


@dataclass
class CompactBeat:
    """One steady-state heartbeat as data, not a frame (the beat-plane
    fast path): the receiver validates (term, leader, committed) against
    its row and touches the election deadline INLINE — no node lock, no
    handler task.  Anything unusual (term moved, candidate, committed
    behind, unknown node) answers needs_full and the sender follows up
    with a classic empty-AppendEntries beat carrying full semantics."""

    group_id: str
    server_id: str  # the sending leader
    peer_id: str    # the target node
    term: int
    committed_index: int
    # quiesce handshake: the leader saw N consecutive fully-acked idle
    # rounds and proposes hibernation.  A follower that matches the
    # beat's (term, leader, committed) row AND is at the leader's tail
    # suppresses its election timeout, registers on the sender store's
    # liveness lease (lease_ms horizon), and acks ok; the leader only
    # hibernates once EVERY follower acked — a single refusal keeps the
    # group active (a follower with a live election timer must keep
    # receiving beats).
    quiesce: bool = False
    lease_ms: int = 0


@dataclass
class BeatAck:
    ok: bool            # False => send a full beat (slow path)
    term: int           # receiver's current term (observability only)
    # responder's store clock (monotonic ms) at ack time: piggybacked
    # sample for the sender's peer-skew estimator (ISSUE 18).  Trailing
    # + defaulted: old peers decode as 0 ("no reading").
    clock_ms: int = 0


@dataclass
class StoreLeaseBeat:
    """Store-level liveness lease (ONE per endpoint pair per interval):
    while groups between two stores are quiescent, this tiny beat is the
    only thing proving the sender store alive.  The receiver re-arms the
    sender's lease for ``lease_ms``; on expiry it wakes every quiescent
    group that depends on that store with a randomized election timeout
    (no thundering herd).  The ack, back on the sender, refreshes the
    last_ack rows of the sender's quiescent leader groups toward this
    endpoint — dead-quorum step-down and leader-lease reads for
    hibernating groups consult exactly this lease."""

    endpoint: str   # the sending store's endpoint
    lease_ms: int   # horizon the receiver should hold the lease for


@dataclass
class StoreLeaseAck:
    ok: bool
    # how many quiescent groups on the receiver currently depend on the
    # sender's lease (observability: hub counters / describe)
    dependents: int = 0
    # responder's store clock (monotonic ms) at ack time — same skew
    # probe as BeatAck.clock_ms; 0 = old peer / no reading
    clock_ms: int = 0


@dataclass
class BatchRequest:
    """Generic batched RPC envelope (the send-plane wire unit —
    SURVEY.md §3.5 "batched per-tick (group, peer) send matrices",
    §8.2 "send-plans"): one RPC per (src, dst) endpoint pair carries
    MANY groups' protocol messages.  ``items`` are full request
    messages (AppendEntriesRequest / RequestVoteRequest); the method
    name ("multi_append" / "multi_vote") selects the receiver's
    dispatch.  In-proc transports pass the objects through untouched;
    framed transports nest-encode them at the wire (``list[msg]``)."""

    items: list[msg]  # noqa: F821 — codec annotation, not a type


@dataclass
class BatchResponse:
    """One response message per request item, in order; an
    ErrorResponse marks an item whose group was unroutable or
    unserviceable on the receiver."""

    items: list[msg]  # noqa: F821


@dataclass
class StoreAppendRequest:
    """Store-wide append round (the WRITE-plane mirror of the read
    plane's ``multi_beat_fast`` fence round): one RPC per destination
    endpoint carries the pending entry windows of EVERY led group on
    the sending store whose follower lives there.  Each row is a full
    ``AppendEntriesRequest`` — per-group prev-log/term semantics are
    unchanged, so safety is exactly per-group AppendEntries; only the
    RPC round trip is shared.  Dispatched by ``AppendBatcher``
    (tpuraft/core/append_batcher.py); a receiver that predates it
    answers ENOMETHOD and the sender downgrades PERMANENTLY to
    per-group ``append_entries`` for that endpoint (the PD delta-batch
    / kv_batch mixed-fleet pattern)."""

    rows: list[msg]  # noqa: F821 — AppendEntriesRequest rows


@dataclass
class StoreAppendResponse:
    """One ack per request row, in order: an ``AppendEntriesResponse``,
    or an ``ErrorResponse`` for a row whose node was unroutable or busy
    on the receiver."""

    acks: list[msg]  # noqa: F821


for _i, _t in enumerate([
    AppendEntriesRequest,
    AppendEntriesResponse,
    RequestVoteRequest,
    RequestVoteResponse,
    InstallSnapshotRequest,
    InstallSnapshotResponse,
    TimeoutNowRequest,
    TimeoutNowResponse,
    ReadIndexRequest,
    ReadIndexResponse,
    GetFileRequest,
    GetFileResponse,
    ErrorResponse,
    MultiHeartbeatRequest,
    MultiHeartbeatResponse,
    BatchRequest,
    BatchResponse,
    CompactBeat,
    BeatAck,
    StoreLeaseBeat,
    StoreLeaseAck,
    StoreAppendRequest,
    StoreAppendResponse,
]):
    register_message(_i, _t)




def _ann(f) -> str:
    """Field annotation as a string, whether or not the defining module
    uses ``from __future__ import annotations``."""
    t = f.type
    if isinstance(t, str):
        return t
    if isinstance(t, type):
        return t.__name__
    return str(t)  # e.g. types.GenericAlias: list[str] -> "list[str]"


def encode_message(msg) -> bytes:
    """Wire-encode any message: u8 type id + field stream."""
    tid = _TYPE_ID[type(msg)]
    out = bytearray(struct.pack("<B", tid))
    for name, f in type(msg).__dataclass_fields__.items():
        v = getattr(msg, name)
        ann = _ann(f)
        if ann == "bool":
            out += struct.pack("<B", v)
        elif ann == "int":
            out += _I64.pack(v)
        elif ann == "str":
            out += _pack_str(v)
        elif ann == "bytes":
            out += _pack_bytes(v)
        elif ann == "SnapshotMeta":
            out += _pack_bytes(v.encode())
        elif ann.startswith("list[str]"):
            out += struct.pack("<I", len(v))
            for s in v:
                out += _pack_str(s)
        elif ann.startswith("list[bytes]"):
            out += struct.pack("<I", len(v))
            for b in v:
                out += _pack_bytes(b)
        elif ann.startswith("list[LogEntry]"):
            out += struct.pack("<I", len(v))
            for e in v:
                out += _pack_bytes(e.encode())
        elif ann.startswith("list[msg]"):
            out += struct.pack("<I", len(v))
            for m in v:
                out += _pack_bytes(encode_message(m))
        else:
            raise TypeError(f"cannot encode field {name}={v!r} ({ann})")
    return bytes(out)


def decode_message(buf: bytes | memoryview):
    buf = memoryview(buf)
    (tid,) = struct.unpack_from("<B", buf, 0)
    cls = _MSG_TYPES[tid]
    off = 1
    kwargs = {}
    for name, f in cls.__dataclass_fields__.items():
        if off >= len(buf) and (f.default is not _MISSING
                                or f.default_factory is not _MISSING):
            # a shorter buffer from an old-format sender: trailing
            # fields added since (always declared with defaults) take
            # those defaults — mixed-version fleets keep decoding.
            # Required fields still raise on a genuinely short frame.
            break
        ann = _ann(f)
        if ann == "bool":
            (v,) = struct.unpack_from("<B", buf, off)
            kwargs[name] = bool(v)
            off += 1
        elif ann == "int":
            (kwargs[name],) = _I64.unpack_from(buf, off)
            off += 8
        elif ann == "str":
            kwargs[name], off = _unpack_str(buf, off)
        elif ann == "bytes":
            kwargs[name], off = _unpack_bytes(buf, off)
        elif ann == "SnapshotMeta":
            blob, off = _unpack_bytes(buf, off)
            kwargs[name] = SnapshotMeta.decode(blob)
        elif ann.startswith("list[str]"):
            (n,) = struct.unpack_from("<I", buf, off)
            off += 4
            items = []
            for _ in range(n):
                s, off = _unpack_str(buf, off)
                items.append(s)
            kwargs[name] = items
        elif ann.startswith("list[bytes]"):
            (n,) = struct.unpack_from("<I", buf, off)
            off += 4
            blobs = []
            for _ in range(n):
                b, off = _unpack_bytes(buf, off)
                blobs.append(b)
            kwargs[name] = blobs
        elif ann.startswith("list[LogEntry]"):
            (n,) = struct.unpack_from("<I", buf, off)
            off += 4
            entries = []
            for _ in range(n):
                blob, off = _unpack_bytes(buf, off)
                # wire path: TCP is already checksummed and the journal
                # CRCs records at write time — skip the per-entry CRC
                # (storage reads keep verify=True)
                entries.append(LogEntry.decode(blob, verify=False))
            kwargs[name] = entries
        elif ann.startswith("list[msg]"):
            (n,) = struct.unpack_from("<I", buf, off)
            off += 4
            msgs = []
            for _ in range(n):
                blob, off = _unpack_bytes(buf, off)
                msgs.append(decode_message(blob))
            kwargs[name] = msgs
        else:
            raise TypeError(f"cannot decode field {name}: {ann}")
    return cls(**kwargs)
